"""WindowAggOperator — keyed windowed aggregation on dense TPU state.

The north-star operator (reference: ``WindowOperator.java:98``,
``processElement:300`` / ``onEventTime:459`` / ``emitWindowContents:574``),
re-designed for the MXU/HBM execution model instead of the per-record JVM
loop:

- Keyed state is a **pane ring buffer** in HBM: per accumulator leaf an array
  ``[K_cap, P, *leaf]`` (K_cap = key capacity, P = ring of panes) plus an
  ``int32[K_cap, P]`` element count.  A pane is the gcd-span shared by all
  windows covering it (``assigners.py``); tumbling windows have one pane per
  window, sliding windows share panes across overlapping windows (the blink
  pane optimization, ``HeapWindowsGrouping.java``, made the *only* path).
- ``process_batch`` = one host key-index probe (vectorized, ``keyindex.py``)
  plus ONE jitted device step: lift values, scatter-combine into
  ``(key_slot, pane_slot)`` cells (``ops/scatter.py``).  This replaces the
  reference's per-record ``windowState.add(value)``
  (``WindowOperator.java:422`` → ``HeapAggregatingState.java:42``).
- Watermark advance fires every window whose end it passed, through one of
  two **emit tiers** (device->host bytes are the scarce resource on
  egress-constrained links — tunnel transport: ~3MB/s down vs ~1.5GB/s up):
  * ``device``: a host emit mirror (pane id -> bool[K], maintained from the
    scatter ids the host already computes) yields the exact emit set without
    any device->host metadata traffic; the device gathers just those key
    rows, combines their panes, and downloads ONLY the result values — the
    batched analog of timer-queue polling + ``emitWindowContents``
    (``InternalTimerServiceImpl.advanceWatermark`` → ``onEventTime:459``).
  * ``host``: a write-through host VALUE mirror of the ACC cells (same
    (slot, pane, value) triples as the device scatter, evaluated with the
    aggregate's numpy twins in higher precision) serves fires with ZERO
    device traffic — and can back snapshots (``snapshot_source="mirror"``).
    The device state stays authoritative for sharding/rescale and remains
    continuously equal to the mirror (``verify_mirror``).  ``auto`` picks
    by capability + backend.
- **Allowed lateness** (``WindowOperator.java:630`` cleanup timers): panes are
  retained until ``last_window_end + lateness`` passes the watermark; late
  records within lateness fold into the retained panes and immediately
  re-fire their windows (EventTimeTrigger late-firing semantics); records
  beyond lateness are dropped and counted (side-output hook).
- Count triggers (``CountTrigger.java`` over ``GlobalWindows``) fire per-key
  when the device count crosses the threshold, then purge those keys' state —
  evaluated once per micro-batch (mini-batch semantics, like the reference's
  SQL ``bundle/`` operators).

Static shapes throughout: batches are padded to pow2 sizes (padding rows use
out-of-range slot ids, dropped by XLA scatter), state grows by doubling
(K_cap) / ring doubling (P) — so XLA recompiles only O(log) times per run.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.core.batch import (LONG_MIN, RecordBatch, StreamElement,
                                  TaggedBatch, Watermark)
from flink_tpu.core.functions import (SCATTER_UFUNCS, AggregateFunction,
                                      RuntimeContext)
from flink_tpu.core import keygroups
from flink_tpu.observability import tracing
from flink_tpu.operators.base import (StreamOperator, current_checkpoint_id,
                                      snapshot_is_incremental)
from flink_tpu.runtime.device_health import DeviceQuarantinedError
from flink_tpu.ops.scatter import (combine_along_axis,
                                   gather_row_pane_columns, reset_rows,
                                   scatter_fast, scatter_fold_counts,
                                   scatter_generic, set_row_pane_columns)
from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex, make_key_index
from flink_tpu.state.paging import identity_grid
from flink_tpu.windowing.assigners import GlobalWindows, WindowAssigner
from flink_tpu.windowing.triggers import EventTimeTrigger, Trigger


def _quantize_cap(n: int) -> int:
    """Static gather width for ``n`` emitted rows: 1/8-pow2 steps — padding
    waste <=12.5%, because the download is the scarce resource (see the
    tunnel-asymmetry note in ``_fire_window``)."""
    from flink_tpu.ops.shapes import quantize_pow2
    return quantize_pow2(n, floor=64, steps=8)


def _fetch_enqueue(arrays, chunk_bytes: int = 0):
    """Start async device->host copies of whole arrays; returns a handle for
    :func:`_fetch_collect`.

    Whole-array transfers, deliberately UNCHUNKED: on the tunnel transport
    every device op pays ~100ms+ of round-trip latency, so slicing an array
    into row chunks multiplies that latency per chunk (measured: 4MB chunked
    ≈ 1.2-1.8s vs ≈ 0.1-0.2s whole).  ``chunk_bytes`` is accepted for
    call-site compatibility and ignored."""
    sliced = [[a] for a in arrays]
    for chunks in sliced:
        for c in chunks:
            try:
                c.copy_to_host_async()
            except AttributeError:
                pass
    return sliced


def _fetch_collect(sliced):
    out = []
    for chunks in sliced:
        if len(chunks) == 1:
            out.append(np.asarray(chunks[0]))
        else:
            out.append(np.concatenate([np.asarray(c) for c in chunks]))
    return out


def _handle_ready(sliced) -> bool:
    """True when every array's device->host copy has completed."""
    for chunks in sliced:
        for c in chunks:
            try:
                if not c.is_ready():
                    return False
            except AttributeError:
                return True  # no readiness API: treat as ready (will block)
    return True


from flink_tpu.ops.shapes import next_pow2 as _next_pow2  # noqa: E402

#: flat scatter id for padding rows: INT32_MAX is out of range for any
#: [K_cap * P] state, so XLA's mode="drop" scatter discards it at EVERY
#: capacity — unlike K*P, it stays a dropped id across mid-stage key growth
_PAD_ID = np.int32(np.iinfo(np.int32).max)


def _x64():
    """Scoped 64-bit trace context for the device-probe DELTA arrays: the
    mirror's f64/i64 precision must ride the device, but the repo runs jax
    in 32-bit mode — ``enable_x64`` widens dtypes for exactly the delta
    steps (allocation, fold, pull, clear) and nothing else."""
    from jax.experimental import enable_x64
    return enable_x64()


def _device_trace():
    """``jax.profiler`` annotation around the jitted device step: nests the
    dispatch under "window_agg.device_step" in profiler traces
    (``bench.py --profile``); a cheap no-op when no trace is active."""
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation("window_agg.device_step")
    except Exception:  # noqa: BLE001 — profiler unavailable: plain no-op
        import contextlib
        return contextlib.nullcontext()


class _HotPipeline:
    """Single background worker running hot-path stages IN ORDER.

    The two-stage software pipeline of ``WindowAggOperator.process_batch``:
    the fused host probe/mirror + device dispatch of batch N runs on this
    worker while the main thread returns to the driver (source decode,
    channel IO, the next batch's serial front) and while the device executes
    batch N-1's async dispatch.  Exactly one worker — stages are strictly
    sequential, so state mutation order (and thus every fire digest,
    snapshot, and counter) is identical to the serial path; only the thread
    that runs them changes.  ``depth`` bounds the QUEUE: ``submit`` blocks
    once ``depth`` stages are queued, so at most ``depth + 1`` batches are
    held (queued plus the one executing) — the memory/backpressure bound.

    Errors: a stage exception parks the worker (later stages are skipped)
    and re-raises at EVERY subsequent ``flush()``/``submit()`` — the error
    is STICKY, never consumed: a metrics/REST poller flushing from a
    foreign thread (``job_status()`` -> ``paging_stats()``) must not steal
    the failure from the task thread, whose own next barrier still has to
    fail the task.  Only ``close()`` clears it.
    """

    __slots__ = ("depth", "_q", "_err", "_t")

    def __init__(self, depth: int = 1):
        import queue
        self.depth = max(1, int(depth))
        self._q = queue.Queue(maxsize=self.depth)
        self._err: Optional[BaseException] = None
        self._t = None

    def _loop(self):
        while True:
            fn = self._q.get()
            try:
                if fn is None:
                    return
                if self._err is None:
                    fn()
            except BaseException as e:  # noqa: BLE001 — re-raised at flush
                self._err = e
            finally:
                self._q.task_done()

    def submit(self, fn) -> None:
        if self._err is not None:
            self.flush()
        if self._t is None:
            import threading
            self._t = threading.Thread(target=self._loop, daemon=True,
                                       name="winagg-pipeline")
            self._t.start()
        self._q.put(fn)  # blocks at depth: bounded pipeline

    def pending(self) -> bool:
        return self._q.unfinished_tasks > 0

    def flush(self) -> None:
        """Barrier: block until every submitted stage completed.  A parked
        stage error re-raises here and STAYS parked (see class docstring)."""
        if self._t is not None:
            self._q.join()
        if self._err is not None:
            raise self._err

    def close(self) -> None:
        self._err = None
        if self._t is not None:
            self._q.put(None)
            self._t.join(timeout=10)
            self._t = None


class _Staging:
    """One reusable padded upload set: the int32 flat-id buffer plus one
    pow2-padded buffer per value leaf.  ``token`` is the device array the
    consuming dispatch produced — the set is free for reuse once that
    execution finished (``is_ready``), which protects against backends that
    zero-copy alias host numpy buffers into dispatched computations."""

    __slots__ = ("flat", "bufs", "treedef", "token")

    def __init__(self, Bp: int, leaves, treedef):
        self.flat = np.empty(Bp, np.int32)
        self.bufs = [np.empty((Bp,) + a.shape[1:], a.dtype) for a in leaves]
        self.treedef = treedef
        self.token = None

    def ready(self) -> bool:
        tok = self.token
        if tok is None:
            return True
        try:
            return bool(tok.is_ready())
        except Exception:  # noqa: BLE001 — deleted (donated) or no API:
            return False   # provably-finished unknown -> never reuse

    def fill_values(self, leaves, B: int):
        """Edge-pad the value leaves into the reused buffers (same values
        as ``_pad_rows``); full-width leaves pass through uncopied."""
        out = []
        for buf, a in zip(self.bufs, leaves):
            if a.shape[0] == buf.shape[0]:
                out.append(a)  # already pow2: no copy (matches _pad_rows)
                continue
            buf[:B] = a
            buf[B:] = a[-1]
            out.append(buf)
        return jax.tree_util.tree_unflatten(self.treedef, out)


class _PhaseTimer:
    """Accumulates wall time into a dict entry (bench phase breakdown).
    When the span journal is installed, each timed region ALSO records a
    "hot_stage" span under the SAME phase name — ``--profile`` and traces
    agree on the vocabulary (tests/test_bench_gate scrapes it)."""

    __slots__ = ("_d", "_k", "_t0")

    def __init__(self, d: Dict[str, int], key: str):
        self._d = d
        self._k = key

    def __enter__(self):
        import time
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        import time
        t1 = time.perf_counter_ns()
        self._d[self._k] = self._d.get(self._k, 0) + t1 - self._t0
        j = tracing._JOURNAL       # one attr read + None check when off
        if j is not None:
            j.record("X", self._t0, t1 - self._t0, self._k, "hot_stage")
        return False


class WindowAggOperator(StreamOperator):
    """Keyed window aggregation: ``key_by(key_col).window(assigner).aggregate(agg)``."""

    #: sharded-state capability flags, overridden by the mesh subclass
    #: (``parallel/mesh_runtime.MeshWindowAggOperator``): the base operator
    #: treats ``sharding is not None`` as an opaque placement hint and
    #: disables the host emit tier / paging / degraded-tier migration; the
    #: mesh operator owns a key-group-range state LAYOUT (state/shard_layout)
    #: and runs all three per-shard.
    _SHARDED_HOST_TIER = False
    _SHARDED_PAGING = False
    _SHARDED_DEGRADE = False
    #: fused scan-lane capability (operators/fused_step.py): the single-
    #: dispatch ``lax.scan`` megastep over a staged [N, B] super-batch.
    #: The mesh subclass turns it off — its exchange routing (bucket plan,
    #: sticky capacity) is host-computed per batch — and stages through the
    #: fused HOST pass instead (one concatenated C probe+fold + one
    #: exchange dispatch per super-batch).
    _FUSED_SCAN = True

    def __init__(
        self,
        assigner: WindowAssigner,
        agg: AggregateFunction,
        key_column: str,
        value_selector: Optional[Callable[[Dict[str, Any]], Any]] = None,
        value_column: Optional[str] = None,
        allowed_lateness_ms: int = 0,
        trigger: Optional[Trigger] = None,
        output_column: str = "result",
        emit_window_bounds: bool = True,
        initial_key_capacity: int = 1 << 10,
        initial_panes: int = 16,
        max_batch: int = 1 << 16,
        name: str = "window-agg",
        sharding=None,
        async_fire: bool = False,
        late_output_tag: Optional[str] = None,
        emit_tier: str = "auto",
        snapshot_source: str = "auto",
        native_emit: bool = True,
        device_sync: str = "auto",
        paging=None,
        pipeline_depth: int = 0,
        native_shards: int = 0,
        device_probe: str = "auto",
        queryable: Optional[str] = None,
        superbatch: int = 1,
    ):
        #: host tier: use the C++ WinMirror kernels (fused probe+mirror,
        #: compacting fire) when eligible; False pins the numpy mirror —
        #: used by equivalence tests, and the portable fallback either way
        self.native_emit = native_emit
        #: two-stage software pipeline (0 = serial): the hot stage (fused
        #: probe/mirror + paging + device dispatch) of batch N runs on a
        #: background worker, overlapping the driver's serial front for
        #: batch N+1 and the device's async compute of batch N-1.  Barriers
        #: at every state READ — fires, snapshots, watermark advances that
        #: pass a window end, expiry with lateness, verification — keep
        #: fire digests, snapshots, and counters bit-identical to the
        #: serial path; ``depth`` bounds queued stages (at most depth + 1
        #: batches held, queued plus executing).  Count triggers read
        #: device counts inside process_batch, so they pin serial.
        if int(pipeline_depth) < 0:
            raise ValueError("pipeline_depth must be >= 0")
        self.pipeline_depth = int(pipeline_depth)
        self._pipe: Optional[_HotPipeline] = None
        #: native probe shard count (0 = auto: FLINK_TPU_NATIVE_SHARDS or
        #: one per core up to 4).  >1 hash-partitions the fused C probe's
        #: mirror fold across the native worker pool — disjoint slot
        #: ownership, lock-free, bit-identical at any count.
        self.native_shards = int(native_shards)
        self._nm_shards = 1
        #: reusable padded staging sets keyed by (Bp, value tree spec):
        #: scatter-mode dispatch reuses the flat-id and padded value
        #: buffers across batches instead of reallocating per batch
        self._staging_pool: Dict[tuple, List[_Staging]] = {}
        self._nm = None          # NativeWindowMirror when active
        self._nm_tried = False
        #: sideOutputLateData: beyond-lateness records emit as TaggedBatch
        #: on this tag instead of being dropped; the drop counter does NOT
        #: move for side-output rows (reference semantics)
        self.late_output_tag = late_output_tag
        #: opt-in: window emissions materialize on the NEXT operator call
        #: (downloads overlap subsequent device work).  Terminal-sink
        #: pipelines only — downstream event-time operators would see fired
        #: rows after the firing watermark.
        self.async_fire = async_fire
        self._pending_fires: List[tuple] = []
        self.assigner = assigner
        self.agg = agg
        self.key_column = key_column
        self.value_column = value_column
        if value_selector is not None:
            self._select = value_selector
        elif value_column is not None:
            self._select = lambda cols: cols[value_column]
        else:
            self._select = lambda cols: cols
        self.lateness = int(allowed_lateness_ms)
        if trigger is None:
            # GlobalWindows defaults to NeverTrigger (GlobalWindows.java
            # getDefaultTrigger); time windows default to EventTimeTrigger.
            from flink_tpu.windowing.triggers import NeverTrigger
            trigger = (NeverTrigger() if isinstance(assigner, GlobalWindows)
                       else EventTimeTrigger())
        if trigger.fires_on_count and not isinstance(assigner, GlobalWindows) \
                and assigner.panes_per_window != 1 \
                and trigger.purges_on_fire \
                and not agg.supports_retraction():
            raise NotImplementedError(
                "PURGING count triggers over MULTI-PANE (sliding) assigners "
                "need an INVERTIBLE aggregate (all-'add' ACC leaves: "
                "sum/count/avg): overlapping windows share panes, so the "
                "purge is logical — a per-(key, window) value baseline is "
                "subtracted instead of clearing shared cells.  Min/max "
                "cannot retract; use a plain CountTrigger (fire without "
                "purge) for those.")
        self.trigger = trigger
        self.output_column = output_column
        self.emit_window_bounds = emit_window_bounds
        self.name = name
        self.max_batch = max_batch

        self.spec = agg.acc_spec()
        self.kinds = agg.scatter_kind_leaves()

        # ---- cold-key paging (state/paging.py): the pane ring becomes a
        # CACHE over an unbounded key space — K_cap is pinned to
        # paging.capacity, cold keys' pane cells page out to the native
        # SpillStore and back in on access.  Device-tier only: the host
        # value mirror would hold every key in host RAM anyway (its scale
        # story is the spill *backend*), and paging's point is bounding the
        # DEVICE footprint.  Count triggers are excluded — their per-row
        # fire registers don't survive row reassignment.
        self.paging = paging
        self._pager = None
        if paging is not None:
            if sharding is not None and not self._SHARDED_PAGING:
                raise ValueError("paging requires unsharded state (shard "
                                 "first, page within each shard)")
            if isinstance(assigner, GlobalWindows) \
                    or self.trigger.fires_on_count \
                    or not self.trigger.fires_on_time:
                raise ValueError("paging requires time-triggered time "
                                 "windows (no count triggers/GlobalWindows)")
            if emit_tier == "auto":
                emit_tier = "device"
            if emit_tier != "device":
                raise ValueError("paging pins the device emit tier (the "
                                 "host mirror is unbounded host state)")

        # ---- emit tier (VERDICT r2 #1): which memory serves window fires.
        # "device": gather+download emitted rows (the r1/r2 path) — right
        #   when device->host bandwidth is healthy (PCIe, ICI) or state is
        #   sharded.  "host": a write-through HOST VALUE MIRROR of the ACC
        #   cells — maintained from the very same (slot, pane, value)
        #   triples the host computes to build the device scatter — serves
        #   fires with ZERO device->host traffic.  Decisive on
        #   egress-constrained links (tunnel transport: ~100ms fixed +
        #   ~350ms/MB per download): a 1M-key fire costs ~1.4s of download
        #   device-side vs ~20ms of numpy host-side.  The device state stays
        #   authoritative for sharding/rescale and remains continuously
        #   equal to the mirror (asserted by tests and checkable via
        #   ``verify_mirror``); "auto" picks host exactly when the agg
        #   declares numpy twins (functions.py ``supports_host_emit``), the
        #   state is unsharded, fires are time-triggered, and the backend is
        #   an accelerator (on CPU there is no transfer cost to dodge).
        host_capable = (
            agg.supports_host_emit()
            and (sharding is None or self._SHARDED_HOST_TIER)
            and self.trigger.fires_on_time
            and not self.trigger.fires_on_count
            and not isinstance(assigner, GlobalWindows))
        if emit_tier == "auto":
            backend = jax.default_backend()
            emit_tier = "host" if (host_capable and backend != "cpu") \
                else "device"
        if emit_tier == "host" and not host_capable:
            raise ValueError(
                "emit_tier='host' requires an unsharded, time-triggered "
                "window over an aggregate with numpy twins "
                "(AggregateFunction.supports_host_emit)")
        self.emit_tier = emit_tier
        #: which memory backs snapshots: "device" downloads state (the
        #: authoritative copy), "mirror" serializes the host mirror (equal
        #: by construction; zero download).  "auto" follows the emit tier.
        if snapshot_source == "auto":
            snapshot_source = "mirror" if emit_tier == "host" else "device"
        if snapshot_source == "mirror" and emit_tier != "host":
            raise ValueError("snapshot_source='mirror' requires the host "
                             "emit tier")
        self.snapshot_source = snapshot_source
        # ---- device sync cadence (host tier only): how the device replica
        # tracks the authoritative host mirror.  "scatter": every micro-batch
        # dispatches the jitted scatter-combine — the device is continuously
        # current (right on direct PCIe/ICI links, where dispatch is ~free).
        # "deferred": per-record dispatch is skipped and the replica
        # refreshes from the mirror at sync points (``device_refresh``:
        # restore, verification, idle) — right on TAXED transports (tunnel/
        # proxy links) where executing a dispatched step costs the host tens
        # of CPU-ms per uploaded MB and that CPU is stolen from the native
        # hot path (utils/transport.py; the ingress twin of the emit-tier
        # download finding), and on slow CPU hosts, where the XLA scatter's
        # ~0.5µs/update replica maintenance dwarfs the native mirror fold.
        # "auto" self-calibrates on EVERY backend: the first host-tier
        # operator measures its own first few real update steps and the
        # verdict is shared process-wide; sub-MB batches never sample and
        # settle on scatter (deterministic for unit-sized traffic).
        # Outside the host tier (device fires, sharded/mesh state) the
        # device IS the authority and always scatters.
        if device_sync not in ("auto", "scatter", "deferred"):
            raise ValueError(f"device_sync must be auto|scatter|deferred, "
                             f"got {device_sync!r}")
        if device_sync == "deferred":
            if emit_tier != "host" or (sharding is not None
                                       and not self._SHARDED_HOST_TIER):
                raise ValueError(
                    "device_sync='deferred' requires the unsharded host emit "
                    "tier (the host mirror must be the authoritative copy)")
            if snapshot_source != "mirror":
                raise ValueError(
                    "device_sync='deferred' requires snapshot_source="
                    "'mirror' (device-sourced snapshots would read a stale "
                    "replica)")
        self.device_sync = device_sync
        #: resolved cadence ("scatter"/"deferred"); None until first batch
        self.device_sync_mode: Optional[str] = None
        #: deferred mode: device replica lags the mirror until device_refresh
        self._device_stale = False
        #: auto-calibration attempts so far; bounded so workloads whose
        #: batches are too small to yield a calibration sample settle on
        #: scatter instead of measuring (and blocking) forever
        self._calib_batches = 0
        #: mirror leaf dtypes: integer leaves widen to int64, floats to
        #: float64 — the host tier is the HIGHER-precision replica
        self._mirror_dtypes = tuple(
            np.int64 if np.issubdtype(np.dtype(d), np.integer) else np.float64
            for d in self.spec.leaf_dtypes)
        #: host value mirror: pane id -> [counts int64 [K], leaf_0 [K,...],
        #: ...] (only when emit_tier == "host")
        self._vmirror: Dict[int, list] = {}
        #: per-phase time/byte accounting (bench transparency, VERDICT r2
        #: weak #1): probe/mirror/device_dispatch/fire/snapshot ns, h2d/d2h
        #: bytes
        self.phase_ns: Dict[str, int] = {}
        self.phase_bytes: Dict[str, int] = {}
        #: per-shard phase accounting: phase name -> int64[n_shards] ns,
        #: filled when the fused probe runs sharded with a timing buffer
        #: (the mesh runtime's per-shard probe breakdown; empty otherwise)
        self.phase_shard_ns: Dict[str, np.ndarray] = {}

        # ring geometry — P must exceed the live pane span (window length in
        # panes + out-of-orderness + lateness retention)
        self._P = _next_pow2(max(initial_panes, 2 * assigner.panes_per_window))
        if paging is not None:
            # paged: K_cap is the FIXED resident capacity — the ring never
            # grows with key cardinality (that is the whole point).  The
            # DevicePager itself is created below, AFTER the shard-count
            # divisibility rounding: pager.K must equal the final ring
            # capacity or row assignment and restore overflow
            self._K = _next_pow2(paging.capacity)
        else:
            self._K = _next_pow2(initial_key_capacity)

        #: jax.sharding.Sharding for state arrays ([K, P, ...] sharded over the
        #: key-slot dim = key-group axis, SURVEY §7.1).  The jitted steps are
        #: placement-agnostic: XLA's SPMD partitioner splits the scatters per
        #: shard (indices replicated, out-of-range rows dropped locally), so
        #: multi-chip is pure data placement — no kernel changes.
        self.sharding = sharding
        # shard count must divide K for even state splits: round K up to
        # lcm(K, n_shards); doubling growth preserves divisibility after that
        if sharding is not None:
            import math
            nsh = max(len(sharding.mesh.devices.reshape(-1))
                      if hasattr(sharding, "mesh") else 1, 1)
            self._K = self._K * nsh // math.gcd(self._K, nsh)
        if paging is not None:
            from flink_tpu.state.paging import DevicePager
            self._pager = DevicePager(paging, self.spec, self._K)
        self.key_index: Optional[KeyIndex | ObjectKeyIndex] = None
        self._leaves = None          # tuple of [K, P, *leaf] device arrays
        self._counts = None          # int32 [K, P]
        #: sliding count triggers: window id -> int64[<=K] count already
        #: fired per key slot (the CountTrigger count register, which clears
        #: on FIRE — next fire needs n MORE elements)
        self._count_baselines: Dict[int, np.ndarray] = {}
        #: FIRE_AND_PURGE over sliding windows: per-window VALUE baselines
        #: (one np array per ACC leaf) — the fired-so-far accumulator that
        #: gets subtracted from the live pane sum (logical purge; physical
        #: purge would corrupt pane-sharing neighbours)
        self._value_baselines: Dict[int, List[np.ndarray]] = {}
        #: host emit mirror: pane id -> bool[K] "this (key, pane) cell holds
        #: data".  The host computes every scatter id, so it KNOWS which keys
        #: a window will emit — fires upload the exact emit index and
        #: download only the emitted rows' values.  On the tunnel transport
        #: device->host bytes are ~500x more expensive than host->device
        #: (measured ~3MB/s vs ~1.5GB/s), so eliminating mask/count/index
        #: downloads is the difference between a 4MB and a <1MB fire.
        self._mirror: Dict[int, np.ndarray] = {}
        self.pane_base: Optional[int] = None   # smallest retained pane id
        self.max_pane: Optional[int] = None    # largest pane seen
        self.last_fired_window: Optional[int] = None
        self.watermark: int = LONG_MIN
        self.late_dropped: int = 0   # beyond-lateness drop counter (numRecordsDropped)
        self._proc_time: int = LONG_MIN
        #: device-lane health (runtime/device_health.py): True while this
        #: operator runs on the DEGRADED host/numpy tier after the process
        #: -wide monitor quarantined the device.  Host-tier operators keep
        #: folding into their (authoritative) mirror and just stop
        #: dispatching (deferred-sync semantics); device-tier operators
        #: materialize the pane ring into the host value mirror and serve
        #: fires/snapshots from it until re-promotion at a checkpoint-
        #: aligned safe point.
        self._degraded = False
        self._quarantine_migrations = 0
        self._repromotions = 0
        #: tier-transition fencing: every degrade/abandoned-promotion
        #: bumps the epoch; a re-promotion attempt commits only if the
        #: epoch it started under is still current (under _tier_lock), so
        #: a watchdog-abandoned attempt that later limps to completion on
        #: its sacrificed lane thread can never land stale state
        self._tier_epoch = 0
        import threading as _threading
        self._tier_lock = _threading.Lock()

        # ---- device-resident key probe (state/device_keyindex.py): resolve
        # warm keys ON the device, inside the already-dispatched XLA step —
        # the host C pass then touches only misses.  Warm-row contributions
        # accumulate in device-resident DELTA arrays (mirror precision:
        # f64/i64) and the host value mirror catches up pane-granularly at
        # fire/snapshot/verify time (wm_apply_delta + a bounded d2h pull of
        # only the panes about to fire).  "auto" runs the measured A/B
        # calibration (calibrated_device_probe); "on"/"off" force.
        if device_probe not in ("auto", "on", "off"):
            raise ValueError(f"device_probe must be auto|on|off, "
                             f"got {device_probe!r}")
        self.device_probe = device_probe
        self._dki = None                      # DeviceKeyIndex when active
        self._devprobe_resolved: Optional[bool] = None
        self._delta_leaves = None             # mirror-dtype [K, P] arrays
        self._delta_counts = None             # int32 [K, P]
        self._delta_panes: set = set()        # panes with unsynced delta
        self._dp_stats = {"probe_hits": 0, "probe_misses": 0,
                          "miss_inserts": 0, "delta_syncs": 0}

        # ---- one-dispatch fused megastep (operators/fused_step.py,
        # ROADMAP item 6): stage up to ``superbatch`` micro-batches and
        # advance them in ONE pass — a device-side lax.scan over donated
        # state buffers when the device-resident probe is active, or one
        # concatenated fused C probe+fold (+ one replica dispatch under
        # scatter sync) on the host tier.  1 = off (the default — the
        # serial-equivalent baseline, like pipeline_depth=0); 0 = auto
        # (measured process-wide A/B, calibrated_superbatch); N > 1
        # forces depth N.
        # Watermarks that pass no window end leave the stage untouched
        # (fire-boundary math decides the scan boundary); every state read
        # flushes through flush_pipeline, so observable behaviour is
        # bit-identical to the unfused path.
        if int(superbatch) < 0:
            raise ValueError("superbatch must be >= 0 (0 = auto)")
        self.superbatch = int(superbatch)
        from flink_tpu.operators.fused_step import SuperBatchStage
        self._fused_resolved: Optional[int] = None   # depth; 1 = off
        self._fused_stage = SuperBatchStage()
        self._fused_counters = {"flushes": 0, "staged_batches": 0,
                                "scan_dispatches": 0, "scan_steps": 0,
                                "host_super_passes": 0}
        self._fused_bp_hw = 0    # sticky pow2 high-water: scan step width
        self._fused_n_hw = 0     # sticky pow2 high-water: scan depth
        self._fused_shards = 0   # super-pass C shard count (0 = unresolved)
        #: guarded hot-path dispatch count (bench: dispatches/batch)
        self._hot_dispatches = 0

        # ---- queryable serving tier (ISSUE-9): when named, every fired
        # window's emissions publish into a barrier-free live-read view
        # (queryable/view.py) — the SAME (keys, values) arrays the fire
        # emitted, off the delta-synced host mirror, so a live read is
        # bit-equal to the operator's fire-time values on every tier and
        # mesh size.  Tagged with the watermark + last-completed-checkpoint
        # id they reflect.  None (the default) costs one attribute check
        # per fire and nothing on the record hot path.
        self.queryable = queryable
        self._qview = None
        self._last_completed_checkpoint: Optional[int] = None
        if queryable is not None:
            from flink_tpu.queryable.view import WindowReadView
            self._qview = WindowReadView(key_column)

        # ---- incremental (delta) checkpoints (ISSUE-16): when the runtime
        # enables it, every state mutation marks its (key, pane) cells /
        # baseline windows dirty, and a non-savepoint snapshot ships only
        # the dirt accumulated since the last CONFIRMED checkpoint as a
        # ``window_delta`` increment (runtime/checkpoint/delta.py) instead
        # of the full dense grid.  Off (the default) costs one attribute
        # check per batch.
        self.incremental_state = False
        #: full re-base when dirty cells exceed this fraction of the grid
        self.incr_rebase_ratio = 0.5
        self._incr_clear()

    #: snapshot entries row-indexed by key slot (rescale redistribution)
    ROW_FIELDS = ("leaves", "counts")

    @staticmethod
    def _pack_baselines(snap: Dict[str, Any],
                        windows: Optional[List[int]] = None):
        """dict(window -> slot-row array) → parallel list row-field (the
        redistribute helpers split/concat list-valued row fields per array),
        aligned on ``windows`` (zeros for windows this snapshot lacks)."""
        snap = dict(snap)
        cb = snap.pop("count_baselines", None) or {}
        if windows is None:
            if not cb:
                return snap, ()
            windows = sorted(cb)
        n = next((len(np.asarray(v)) for v in cb.values()),
                 snap["counts"].shape[0] if "counts" in snap else 0)
        snap["count_baseline_windows"] = list(windows)
        snap["count_baseline_rows"] = [
            np.asarray(cb.get(w, np.zeros(n, np.int64))) for w in windows]
        return snap, ("count_baseline_rows",)

    @staticmethod
    def _unpack_baselines(snap: Dict[str, Any]) -> Dict[str, Any]:
        wins = snap.pop("count_baseline_windows", None)
        rows = snap.pop("count_baseline_rows", None)
        if wins:
            snap["count_baselines"] = dict(zip(wins, rows))
        return snap

    @staticmethod
    def split_snapshot(snap: Dict[str, Any], max_parallelism: int,
                       new_parallelism: int) -> List[Dict[str, Any]]:
        """Rescale a snapshot across key-group ranges
        (``StateAssignmentOperation.reDistributeKeyedStates`` analog)."""
        from flink_tpu.state.redistribute import split_keyed_snapshot
        from flink_tpu.state.shard_layout import densify_keyed_snapshot
        snap = densify_keyed_snapshot(snap)  # mesh per-shard slice format
        snap, extra = WindowAggOperator._pack_baselines(snap)
        parts = split_keyed_snapshot(snap, WindowAggOperator.ROW_FIELDS + extra,
                                     max_parallelism, new_parallelism)
        return [WindowAggOperator._unpack_baselines(p) for p in parts]

    @staticmethod
    def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Merge same-checkpoint snapshots (scale-down).

        Subtasks of one coordinated ALIGNED checkpoint share pane
        progress (every subtask saw the same watermark at the barrier);
        an UNALIGNED checkpoint's subtasks snapshot at different
        watermarks (the barrier overtakes each at its own moment), so
        their pane rings cover different-but-overlapping ranges.  The
        keys are disjoint (key-group partitioned), so heterogeneous
        progress merges safely by EXPANDING every part onto the union
        pane range (zero panes a part never reached / already expired)
        and taking the MINIMUM watermark / last-fired-window: windows a
        faster subtask already fired have their state evicted there (no
        double fire), while a slower subtask's unfired windows stay live
        and fire when the restored job's watermark passes them again."""
        from flink_tpu.state.redistribute import merge_keyed_snapshots
        from flink_tpu.state.shard_layout import densify_keyed_snapshot
        snaps = [densify_keyed_snapshot(s) for s in snaps]
        live = [s for s in snaps if "panes" in s]
        if live and any(not np.array_equal(s["panes"], live[0]["panes"])
                        for s in live[1:]):
            snaps = WindowAggOperator._align_pane_progress(snaps)
            live = [s for s in snaps if "panes" in s]
        all_windows = sorted({w for s in snaps
                              for w in (s.get("count_baselines") or {})})
        extra = ()
        if all_windows:
            packed = []
            for s in snaps:
                p, e = WindowAggOperator._pack_baselines(s, all_windows)
                packed.append(p)
                extra = e or extra
            snaps = packed
        merged = merge_keyed_snapshots(snaps,
                                       WindowAggOperator.ROW_FIELDS + extra)
        merged = WindowAggOperator._unpack_baselines(merged)
        if live:
            # MIN is correct for both cases: aligned parts all agree (min
            # == max), unaligned parts must resume from the slowest
            # subtask's progress or its not-yet-fired windows never fire
            merged["watermark"] = min(s["watermark"] for s in live)
            lf = [s.get("last_fired_window") for s in live]
            merged["last_fired_window"] = (None if any(w is None for w in lf)
                                           else min(lf))
        return merged

    @staticmethod
    def _align_pane_progress(snaps: List[Dict[str, Any]]
                             ) -> List[Dict[str, Any]]:
        """Expand each part's pane-indexed row fields onto the UNION pane
        range (contiguous ``arange(min pane_base, max max_pane + 1)``):
        panes a part already expired or never reached hold zero counts,
        which is exactly their state there.  Keys stay disjoint across
        parts, so the subsequent keyed merge concatenates rows without
        ever adding two parts' values for one (key, pane)."""
        live = [s for s in snaps if "panes" in s]
        base = min(int(s["pane_base"]) for s in live)
        top = max(int(s["max_pane"]) for s in live)
        union = np.arange(base, top + 1, dtype=np.int64)
        # the restored ring maps slot = pane % P: P must cover the union
        # span or distinct panes would collide in one slot
        ring = max(int(s.get("P", 2)) for s in live)
        while ring < len(union):
            ring <<= 1
        out = []
        for s in snaps:
            if "panes" not in s:
                out.append(s)
                continue
            s2 = dict(s)
            off = int(s["pane_base"]) - base
            counts = np.asarray(s["counts"])
            n_p = counts.shape[1]
            wide = np.zeros((counts.shape[0], len(union)), counts.dtype)
            wide[:, off:off + n_p] = counts
            s2["counts"] = wide
            leaves = []
            for leaf in s["leaves"]:
                leaf = np.asarray(leaf)
                w = np.zeros((leaf.shape[0], len(union)) + leaf.shape[2:],
                             leaf.dtype)
                w[:, off:off + n_p] = leaf
                leaves.append(w)
            s2["leaves"] = leaves
            s2["panes"] = union
            s2["pane_base"] = base
            s2["max_pane"] = top
            s2["P"] = ring
            out.append(s2)
        return out

    def reset_state(self) -> None:
        """Drop all keyed state/time progress but KEEP compiled steps (the
        jit caches key on this instance).  Used by benchmarks/tests to re-run
        a warm operator, and by restore paths before loading a snapshot."""
        if self._pipe is not None:
            self._pipe.flush()   # in-flight stages still write this state
        # staged micro-batches die with the state they were bound for (a
        # fold into state we are about to drop would be wasted work); the
        # sticky scan geometry and the resolved depth survive, like the
        # resolved sync mode — compile-once across warm re-runs
        self._fused_stage.take()
        self._staging_pool = {}
        self.key_index = None
        self._leaves = None
        self._counts = None
        self._count_baselines = {}
        self._value_baselines = {}
        self._pending_fires = []
        self._mirror = {}
        self._vmirror = {}
        self._nm = None          # keydict died with key_index
        self._nm_tried = False
        self.pane_base = None
        self.max_pane = None
        self.last_fired_window = None
        self.watermark = LONG_MIN
        self.late_dropped = 0
        self._proc_time = LONG_MIN
        self.phase_ns = {}
        self.phase_bytes = {}
        self.phase_shard_ns = {}
        self._hot_dispatches = 0
        self._fused_counters = {"flushes": 0, "staged_batches": 0,
                                "scan_dispatches": 0, "scan_steps": 0,
                                "host_super_passes": 0}
        self._device_stale = False  # resolved sync mode survives the reset
        self._degraded = False      # fresh state restores on the device
        with self._tier_lock:
            self._tier_epoch += 1   # fence any in-flight promotion
        self._active_rows = None
        self._dki = None            # device probe table died with key_index
        self._drop_delta()
        self._devprobe_resolved = None
        self._dp_stats = {"probe_hits": 0, "probe_misses": 0,
                          "miss_inserts": 0, "delta_syncs": 0}
        if self._pager is not None:
            self._pager.reset()
        self._incr_clear()      # a fresh state has no confirmed delta base

    # ------------------------------------------------------------------ state
    def _alloc(self, K: int, P: int):
        leaves = []
        for init, shape, dtype in zip(self.spec.leaf_inits, self.spec.leaf_shapes,
                                      self.spec.leaf_dtypes):
            leaves.append(jnp.broadcast_to(jnp.asarray(init, dtype), (K, P) + tuple(shape)).copy())
        counts = jnp.zeros((K, P), jnp.int32)
        if self.sharding is not None:
            leaves = [jax.device_put(l, self.sharding) for l in leaves]
            counts = jax.device_put(counts, self.sharding)
        return tuple(leaves), counts

    def _ensure_alloc(self):
        if self._leaves is None:
            self._leaves, self._counts = self._alloc(self._K, self._P)

    # -------------------------------------------------------- emit mirror
    def _mirror_mark(self, pane: int, slots: np.ndarray) -> None:
        arr = self._mirror.get(pane)
        if arr is None or arr.size < self._K:
            grown = np.zeros(self._K, bool)
            if arr is not None:
                grown[: arr.size] = arr
            arr = self._mirror[pane] = grown
        arr[slots] = True

    def _mirror_emit_idx(self, panes: np.ndarray) -> np.ndarray:
        """Exact ascending key-slot ids that hold data in any of ``panes``."""
        n = self.key_index.num_keys if self.key_index is not None else 0
        acc = None
        for p in panes.tolist():
            arr = self._mirror.get(int(p))
            if arr is None:
                continue
            a = arr[:n] if arr.size >= n else np.pad(arr, (0, n - arr.size))
            acc = a.copy() if acc is None else (acc | a)
        if acc is None:
            return np.empty(0, np.int64)
        return np.flatnonzero(acc)

    # ---------------------------------------------------- host value mirror
    def _phase(self, name: str):
        """Accumulating timer: ``with self._phase("mirror"): ...``."""
        return _PhaseTimer(self.phase_ns, name)

    def _try_native_mirror(self) -> None:
        """Bind the C++ WinMirror to the (fresh) key index, if eligible.
        Called once per key-index lifetime; ineligible configs (object keys,
        non-scalar leaves, no compiler) keep the numpy mirror."""
        if self._nm_tried or self.emit_tier != "host" or not self.native_emit:
            return
        self._nm_tried = True
        from flink_tpu.state.native_mirror import (NativeWindowMirror,
                                                   calibrated_shards)
        self._nm = NativeWindowMirror.try_create(
            self.key_index, self.spec, self.kinds, self._mirror_dtypes)
        if self._nm is not None:
            # 0 = auto: MEASURED once per process (steal-heavy vCPUs often
            # lose with extra shards — calibrated_shards A/Bs it)
            self._nm_shards = self.native_shards or calibrated_shards()

    def _probe_shards(self):
        """(shards, shard_div, shard_ns) for the fused native probe:
        shard count, contiguous-range ownership divisor (0 = slot %% S
        classes), and an optional int64 per-shard timing buffer.  The mesh
        subclass aligns these with the device mesh (shard t owns the
        key-group range whose state block lives on device t) and collects
        the per-shard breakdown."""
        return self._nm_shards, 0, None

    def _record_shard_ns(self, phase: str, shard_ns) -> None:
        if shard_ns is None:
            return
        acc = self.phase_shard_ns.get(phase)
        if acc is None or acc.size < shard_ns.size:
            grown = np.zeros(shard_ns.size, np.int64)
            if acc is not None:
                grown[:acc.size] = acc
            acc = self.phase_shard_ns[phase] = grown
        acc[:shard_ns.size] += shard_ns

    # ----------------------------------------------- device-resident probe
    def _devprobe_table_sharding(self):
        """Placement for the device probe table (None = default device);
        the mesh subclass keeps it unsharded too (the probe runs as one
        plain dispatch; only the fold rides the exchange)."""
        return None

    def _devprobe_eligible(self) -> bool:
        """Static eligibility of the device-resident key probe: the host
        emit tier (the probe_mirror wall lives there), int64 keys, scalar
        add/min/max accumulator leaves (the delta fold + wm_apply_delta
        contract), and no paging — the pager needs every record's global
        id ON THE HOST to translate gid -> resident row per batch, so a
        device-resolved slot would be pulled straight back; the probe is
        not the wall there (there is no host mirror fold to fuse with)."""
        return (self.device_probe != "off"
                and self.emit_tier == "host"
                and self._pager is None
                and self.kinds is not None
                and all(tuple(s) == () for s in self.spec.leaf_shapes)
                and isinstance(self.key_index, KeyIndex)
                and not self.trigger.fires_on_count)

    def _devprobe_active(self, sync: str) -> bool:
        """Per-batch gate: resolved once per key-index lifetime ("on"
        forces, "auto" asks the measured A/B calibration), then cheap."""
        if self._degraded or sync not in ("scatter", "deferred"):
            return False
        if self._devprobe_resolved is None:
            if not self._devprobe_eligible():
                self._devprobe_resolved = False
            elif self.device_probe == "on":
                self._devprobe_resolved = True
            else:
                from flink_tpu.state.device_keyindex import \
                    calibrated_device_probe
                self._devprobe_resolved = calibrated_device_probe()
        return self._devprobe_resolved

    def device_probe_stats(self) -> Dict[str, Any]:
        """Device-probe counters (monitoring-grade, no pipeline barrier):
        hits/misses resolve the warm-key story (steady state ~= 100% hit
        rate ⇒ the host C fold touches only miss rows), ``miss_inserts``
        counts table scatters, ``delta_d2h_bytes`` the pane-granular
        mirror catch-up pulls."""
        s = dict(self._dp_stats)
        total = s["probe_hits"] + s["probe_misses"]
        s["enabled"] = int(bool(self._devprobe_resolved))
        s["probe_hit_rate"] = (s["probe_hits"] / total) if total else None
        s["delta_d2h_bytes"] = int(self.phase_bytes.get("delta_d2h", 0))
        return s

    def _drop_delta(self) -> None:
        self._delta_leaves = None
        self._delta_counts = None
        self._delta_panes = set()

    def _ensure_delta(self) -> None:
        """Allocate the device-resident DELTA ring [K, P] in the MIRROR
        dtypes (f64/i64 — the higher-precision twins, so warm-row folds
        carry exactly the precision the host mirror fold would have)."""
        if self._delta_counts is not None \
                and self._delta_counts.shape == (self._K, self._P):
            return
        with _x64():
            leaves = []
            for init, mdt in zip(self.spec.leaf_inits, self._mirror_dtypes):
                iv = np.asarray(init).astype(mdt)
                leaves.append(jnp.broadcast_to(
                    jnp.asarray(iv), (self._K, self._P)).copy())
            counts = jnp.zeros((self._K, self._P), jnp.int32)
            if self.sharding is not None:
                leaves = [jax.device_put(l, self.sharding) for l in leaves]
                counts = jax.device_put(counts, self.sharding)
        self._delta_leaves = tuple(leaves)
        self._delta_counts = counts
        self._delta_panes = set()

    def _delta_fold(self, dleaves, dcounts, flat, lifted):
        """Traced helper: scatter-combine one batch's (flat id, value)
        pairs into the delta ring (scatter_fast casts the f32 lifted
        leaves up to the delta's f64/i64 dtypes)."""
        K, P = dcounts.shape
        dflat = tuple(l.reshape(K * P) for l in dleaves)
        new, ndc = scatter_fold_counts(dflat, dcounts.reshape(K * P),
                                       flat, lifted, self.kinds)
        return tuple(l.reshape(K, P) for l in new), ndc.reshape(K, P)

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(3, 4, 5, 6))
    def _probed_update_step(self, tab, b, leaves, counts, dleaves, dcounts,
                            key_lo, key_hi, start, pane_slots, values):
        """Scatter-sync micro-batch with the key probe INSIDE the jitted
        step: probe the device table, fold warm (hit) rows into both the
        device state (device precision) and the delta ring (mirror
        precision), and return a compact miss list for the host.  Miss and
        pad rows carry the dropped _PAD_ID.  The scalar miss count is the
        host's only mandatory read-back."""
        from flink_tpu.state.device_keyindex import probe_impl
        _name, probe = probe_impl(int(tab[0].shape[0]))
        slot = probe(*tab, key_lo, key_hi, start)
        Bp = key_lo.shape[0]
        valid = jnp.arange(Bp, dtype=jnp.int32) < b
        hit = valid & (slot >= 0)
        K, P = counts.shape
        flat = jnp.where(hit, slot * P + pane_slots, _PAD_ID)
        lifted = tuple(jax.tree_util.tree_leaves(self.agg.lift(values)))
        flat_leaves = tuple(l.reshape((K * P,) + l.shape[2:])
                            for l in leaves)
        new_flat = scatter_fast(flat_leaves, flat, lifted, self.kinds)
        new_leaves = tuple(l.reshape((K, P) + l.shape[1:]) for l in new_flat)
        ones = jnp.ones(flat.shape, jnp.int32)
        new_counts = counts.reshape(K * P).at[flat].add(
            ones, mode="drop").reshape(K, P)
        ndl, ndc = self._delta_fold(dleaves, dcounts, flat, lifted)
        miss = valid & (slot < 0)
        miss_idx = jnp.nonzero(miss, size=Bp,
                               fill_value=Bp)[0].astype(jnp.int32)
        miss_count = jnp.sum(miss, dtype=jnp.int32)
        return new_leaves, new_counts, ndl, ndc, miss_idx, miss_count

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(3, 4))
    def _probed_delta_step(self, tab, b, dleaves, dcounts,
                           key_lo, key_hi, start, pane_slots, values):
        """Deferred-sync twin of :meth:`_probed_update_step`: the mirror is
        authoritative, so warm rows fold into the delta ring ONLY (the
        device state replica catches up at device_refresh, as before)."""
        from flink_tpu.state.device_keyindex import probe_impl
        _name, probe = probe_impl(int(tab[0].shape[0]))
        slot = probe(*tab, key_lo, key_hi, start)
        Bp = key_lo.shape[0]
        valid = jnp.arange(Bp, dtype=jnp.int32) < b
        hit = valid & (slot >= 0)
        P = dcounts.shape[1]
        flat = jnp.where(hit, slot * P + pane_slots, _PAD_ID)
        lifted = tuple(jax.tree_util.tree_leaves(self.agg.lift(values)))
        ndl, ndc = self._delta_fold(dleaves, dcounts, flat, lifted)
        miss = valid & (slot < 0)
        miss_idx = jnp.nonzero(miss, size=Bp,
                               fill_value=Bp)[0].astype(jnp.int32)
        miss_count = jnp.sum(miss, dtype=jnp.int32)
        return ndl, ndc, miss_idx, miss_count

    def _fused_scan_body(self, tab, Pn, pad_id, treedef, carry_is_state,
                         flat_state: int = 0):
        """One scan step of the fused megastep: probe the device table,
        fold warm rows, emit the compact miss list.  Shared by the scatter
        and deferred scan steps; ``carry_is_state`` distinguishes the
        (state, delta) carry from the delta-only carry.  The probe — and,
        when capable, the fused Pallas probe+FOLD kernel (the Pallas path
        extended beyond the probe: one kernel resolves slots and scatters
        the delta without a round trip through HBM) — is chosen at trace
        time like every probed step."""
        from flink_tpu.state.device_keyindex import (
            pallas_probe_fold, pallas_probe_fold_available, probe_impl)
        _name, probe = probe_impl(int(tab[0].shape[0]))
        fused_pallas = (not carry_is_state and flat_state > 0
                        and pallas_probe_fold_available(
                            int(tab[0].shape[0]), flat_state, self.kinds))

        def fold(flat, lifted, flat_leaves, flat_counts):
            return scatter_fold_counts(flat_leaves, flat_counts, flat,
                                       lifted, self.kinds)

        def body(carry, xs):
            b, klo, khi, stt, ps = xs[:5]
            vals = xs[5:]
            Bp = klo.shape[0]
            valid = jnp.arange(Bp, dtype=jnp.int32) < b
            values = jax.tree_util.tree_unflatten(treedef, list(vals))
            lifted = tuple(jax.tree_util.tree_leaves(self.agg.lift(values)))
            if fused_pallas:
                dl, dc = carry
                slot, nds, ndc = pallas_probe_fold(
                    *tab, klo, khi, stt, ps, jnp.reshape(b, (1,)),
                    lifted[0], dl[0], dc, Pn)
                out = ((nds,), ndc)
            else:
                slot = probe(*tab, klo, khi, stt)
                hit = valid & (slot >= 0)
                flat = jnp.where(hit, slot * Pn + ps, pad_id)
                if carry_is_state:
                    fl, fc, dl, dc = carry
                    fl, fc = fold(flat, lifted, fl, fc)
                    dl, dc = fold(flat, lifted, dl, dc)
                    out = (fl, fc, dl, dc)
                else:
                    dl, dc = carry
                    dl, dc = fold(flat, lifted, dl, dc)
                    out = (dl, dc)
            miss = valid & (slot < 0)
            mi = jnp.nonzero(miss, size=Bp,
                             fill_value=Bp)[0].astype(jnp.int32)
            return out, (mi, jnp.sum(miss, dtype=jnp.int32))

        return body

    @partial(jax.jit, static_argnums=(0, 12), donate_argnums=(2, 3, 4, 5))
    def _fused_scan_update_step(self, tab, leaves, counts, dleaves, dcounts,
                                bs, key_lo, key_hi, start, pane_slots,
                                vplanes, treedef):
        """Scatter-sync scan megastep: ONE dispatch advances every staged
        micro-batch — per step, probe + device-state fold (device
        precision) + delta fold (mirror precision) — over donated state
        buffers, so steady-state warm-key super-batches cost exactly one
        dispatch.  Returns the per-step compact miss lists; the scalar
        miss total is the host's only mandatory read-back."""
        K, Pn = counts.shape
        fl = tuple(l.reshape((K * Pn,) + l.shape[2:]) for l in leaves)
        fc = counts.reshape(K * Pn)
        dl = tuple(l.reshape(K * Pn) for l in dleaves)
        dc = dcounts.reshape(K * Pn)
        body = self._fused_scan_body(tab, Pn, _PAD_ID, treedef, True)
        (fl, fc, dl, dc), (miss_idx, miss_counts) = jax.lax.scan(
            body, (fl, fc, dl, dc),
            (bs, key_lo, key_hi, start, pane_slots) + tuple(vplanes))
        new_leaves = tuple(l.reshape((K, Pn) + l.shape[1:]) for l in fl)
        new_dl = tuple(l.reshape(K, Pn) for l in dl)
        return (new_leaves, fc.reshape(K, Pn), new_dl, dc.reshape(K, Pn),
                miss_idx, miss_counts)

    @partial(jax.jit, static_argnums=(0, 10), donate_argnums=(2, 3))
    def _fused_scan_delta_step(self, tab, dleaves, dcounts, bs, key_lo,
                               key_hi, start, pane_slots, vplanes, treedef):
        """Deferred-sync scan megastep: the mirror is authoritative, so
        warm rows fold into the delta ring ONLY (the device replica
        catches up at device_refresh) — still one dispatch per
        super-batch."""
        K, Pn = dcounts.shape
        dl = tuple(l.reshape(K * Pn) for l in dleaves)
        dc = dcounts.reshape(K * Pn)
        body = self._fused_scan_body(tab, Pn, _PAD_ID, treedef, False,
                                     flat_state=K * Pn)
        (dl, dc), (miss_idx, miss_counts) = jax.lax.scan(
            body, (dl, dc),
            (bs, key_lo, key_hi, start, pane_slots) + tuple(vplanes))
        return (tuple(l.reshape(K, Pn) for l in dl), dc.reshape(K, Pn),
                miss_idx, miss_counts)

    @partial(jax.jit, static_argnums=(0, 3))
    def _delta_pull_step(self, dleaves, dcounts, rows: int, pane_slots):
        """Bounded d2h pull: the delta columns of the panes about to be
        read (fire/snapshot/verify), first ``rows`` key rows only — the
        download scales with live keys x syncing panes, never the ring."""
        cnt = jnp.take(dcounts[:rows], pane_slots, axis=1,
                       mode="fill", fill_value=0)
        sel = tuple(jnp.take(l[:rows], pane_slots, axis=1,
                             mode="fill", fill_value=0)
                    for l in dleaves)
        return cnt, sel

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2))
    def _delta_clear_step(self, dleaves, dcounts, pane_slots):
        """Reset synced (or expired) delta columns back to identity."""
        new_leaves = []
        for l, init, mdt in zip(dleaves, self.spec.leaf_inits,
                                self._mirror_dtypes):
            iv = np.asarray(init).astype(mdt)
            fill = jnp.broadcast_to(jnp.asarray(iv),
                                    (l.shape[0], pane_slots.shape[0]))
            new_leaves.append(l.at[:, pane_slots].set(fill, mode="drop"))
        return tuple(new_leaves), dcounts.at[:, pane_slots].set(
            0, mode="drop")

    def _devprobe_sync_mirror(self, panes=None) -> None:
        """Pane-granular mirror catch-up: pull the delta columns of
        ``panes`` (None = every unsynced pane), fold them into the host
        value mirror (``wm_apply_delta`` / numpy twin), and reset those
        delta columns on device.  Identity delta rows fold as no-ops, so
        no mask rides the transfer."""
        if self._delta_counts is None or not self._delta_panes:
            return
        if panes is None:
            sync = sorted(self._delta_panes)
        else:
            want = {int(p) for p in np.asarray(panes).reshape(-1).tolist()}
            sync = sorted(self._delta_panes & want)
        if not sync:
            return
        n = self.key_index.num_keys if self.key_index is not None else 0
        if n == 0:
            self._delta_panes.difference_update(sync)
            return
        with self._phase("delta_sync"):
            rows = min(_next_pow2(max(n, 1), 1024), self._K)
            m = len(sync)
            mp = _next_pow2(m, 1)
            slots_np = np.full(mp, self._P, np.int32)   # pads: dropped
            slots_np[:m] = np.asarray(sync, np.int64) % self._P
            with _x64():
                slots_d = jnp.asarray(slots_np)
                cnt, sel = self._delta_pull_step(
                    self._delta_leaves, self._delta_counts, rows, slots_d)
                cnt_np = np.asarray(cnt)
                sel_np = [np.asarray(l) for l in sel]
                self._delta_leaves, self._delta_counts = \
                    self._delta_clear_step(self._delta_leaves,
                                           self._delta_counts, slots_d)
            self.phase_bytes["delta_d2h"] = \
                self.phase_bytes.get("delta_d2h", 0) + cnt_np.nbytes + \
                sum(l.nbytes for l in sel_np)
            for j, p in enumerate(sync):
                col_cnt = cnt_np[:n, j]
                if not col_cnt.any():
                    continue
                if self._nm is not None:
                    self._nm.apply_delta(int(p), col_cnt.astype(np.int64),
                                         [l[:n, j] for l in sel_np])
                else:
                    entry = self._vmirror_pane(int(p))
                    entry[0][:n] += col_cnt
                    for k, kind in enumerate(self.kinds):
                        ufunc = SCATTER_UFUNCS[kind]
                        entry[k + 1][:n] = ufunc(
                            entry[k + 1][:n],
                            sel_np[k][:n, j].astype(self._mirror_dtypes[k],
                                                    copy=False))
            self._delta_panes.difference_update(sync)
            self._dp_stats["delta_syncs"] += 1

    def _hot_stage_devprobe(self, keys: np.ndarray, panes: np.ndarray,
                            values, B: int, sync: str) -> None:
        """Device-probe variant of the hot stage: one guarded dispatch
        probes + folds the warm rows; the host pass then touches ONLY the
        compact miss list (insert into the keydict, C-fold into the
        mirror, one scatter to keep the device table current)."""
        from flink_tpu.runtime import device_health
        self._ensure_alloc()
        self._ensure_delta()
        if self._dki is None:
            from flink_tpu.state.device_keyindex import DeviceKeyIndex
            self._dki = DeviceKeyIndex(
                initial_capacity=max(1 << 16, 2 * self._K),
                sharding=self._devprobe_table_sharding())
        self._dki.ensure_loaded(self.key_index)   # bulk/restore load
        with self._phase("device_probe"):
            key_lo, key_hi, start = self._dki.prepare_batch(keys)
            Bp = _next_pow2(B, 64)

            def pad32(a, fill=0):
                out = np.full(Bp, fill, np.int32)
                out[:B] = a
                return out

            klo_p, khi_p, st_p = pad32(key_lo), pad32(key_hi), pad32(start)
            ps_p = pad32((panes % self._P).astype(np.int32))
            vleaves = [np.asarray(a) for a in
                       jax.tree_util.tree_leaves(values)]
            treedef = jax.tree_util.tree_structure(values)
            values_p = jax.tree_util.tree_unflatten(
                treedef, [_pad_rows(a, Bp) for a in vleaves])
            mb = (16 * Bp + sum(a.nbytes for a in vleaves)) / 1e6
            tab = self._dki.table()
            b_arr = np.int32(B)
            geom = ("devprobe", self._dki.capacity, self._K, self._P, Bp,
                    tuple((a.dtype.str, a.shape[1:]) for a in vleaves))
            fresh_geom = geom != getattr(self, "_last_dispatch_geom", None)
            self._last_dispatch_geom = geom

            def thunk():
                with _x64():
                    if sync == "deferred":
                        out = self._probed_delta_step(
                            tab, b_arr, self._delta_leaves,
                            self._delta_counts, klo_p, khi_p, st_p, ps_p,
                            values_p)
                    else:
                        out = self._probed_update_step(
                            tab, b_arr, self._leaves, self._counts,
                            self._delta_leaves, self._delta_counts,
                            klo_p, khi_p, st_p, ps_p, values_p)
                # the scalar miss count is the dispatch's sync point: a
                # wedged device must surface HERE, under the watchdog
                return out, int(out[-1])

            try:
                self._hot_dispatches += 1
                res, mc = device_health.guarded_dispatch(
                    thunk, mb=mb, on_oom=None,
                    label=f"{self.name}.device_probe",
                    compile_grace=fresh_geom)
            except DeviceQuarantinedError as err:
                self._devprobe_degrade(err, keys, panes, values)
                return
            if sync == "deferred":
                (self._delta_leaves, self._delta_counts,
                 miss_idx, _mcnt) = res
                self._device_stale = True
            else:
                (self._leaves, self._counts, self._delta_leaves,
                 self._delta_counts, miss_idx, _mcnt) = res
                self.phase_bytes["h2d"] = \
                    self.phase_bytes.get("h2d", 0) + mb
            self._delta_panes.update(
                int(p) for p in np.unique(panes).tolist())
            self._dp_stats["probe_hits"] += B - mc
            self._dp_stats["probe_misses"] += mc
        if mc:
            self._devprobe_handle_misses(keys, panes, values, miss_idx, mc,
                                         sync)

    def _devprobe_absorb_misses(self, mkeys, mpanes, mvalues) -> np.ndarray:
        """Shared miss-list host pass (single-chip AND mesh): fused C
        probe+mirror fold over the miss rows only (numpy twin when the
        native mirror is off), key growth with a delta drain/rebuild, and
        one scatter to bring the device table current.  Returns the miss
        rows' slot ids."""
        with self._phase("probe_mirror"):
            if self._nm is not None:
                lifted = [np.asarray(l) for l in jax.tree_util.tree_leaves(
                    self.agg.host_lift(mvalues))]
                nshards, shard_div, shard_ns = self._probe_shards()
                mslots = self._nm.probe_update(mkeys, mpanes, lifted,
                                               shards=nshards,
                                               shard_div=shard_div,
                                               shard_ns=shard_ns)
                self._record_shard_ns("probe_mirror", shard_ns)
            else:
                mslots = self.key_index.lookup_or_insert(mkeys)
        if self.key_index.num_keys > self._K:
            # growth reallocates the delta ring: drain it into the mirror
            # first so no warm contribution is lost, then rebuild at newK
            self._devprobe_sync_mirror(None)
            self._drop_delta()
            self._grow_keys(self.key_index.num_keys)
            self._ensure_delta()
        if self._nm is None:
            # numpy value mirror: fold AFTER growth (the pane entries must
            # already be sized for the new key count)
            with self._phase("mirror"):
                self._vmirror_update(mslots, mpanes, mvalues)
        self._dp_stats["miss_inserts"] += \
            self._dki.ensure_loaded(self.key_index)
        return mslots

    def _devprobe_handle_misses(self, keys, panes, values, miss_idx,
                                mc: int, sync: str) -> None:
        """The host pass over the compact miss list, plus — under scatter
        sync — the miss rows' device-state fold."""
        mi = np.asarray(miss_idx)[:mc].astype(np.int64)
        mkeys = np.ascontiguousarray(keys[mi])
        mpanes = np.ascontiguousarray(panes[mi])
        mvalues = jax.tree_util.tree_map(lambda a: np.asarray(a)[mi],
                                         values)
        mslots = self._devprobe_absorb_misses(mkeys, mpanes, mvalues)
        if sync != "deferred":
            self._miss_replica_update(
                mslots, mpanes, jax.tree_util.tree_structure(mvalues),
                [np.asarray(a)
                 for a in jax.tree_util.tree_leaves(mvalues)])

    def _miss_replica_update(self, mslots, mpanes, treedef,
                             vleaves) -> None:
        """Scatter-sync replica catch-up for probe-miss rows (the shared
        tail of the per-batch and fused miss paths): the device replica
        must see every record, so fold the miss rows through the plain
        (guarded) update step — host-built flat ids, the same
        watchdog/OOM/quarantine path as every other hot-path dispatch.
        Callers reach here only after every record is accounted for in
        mirror-land (warm rows in the delta, miss rows C-folded), so a
        quarantine degrades without refolding anything."""
        Bm = int(mslots.size)
        Bmp = _next_pow2(Bm, 64)
        flat = np.full(Bmp, _PAD_ID, np.int32)
        flat[:Bm] = (mslots.astype(np.int64) * self._P
                     + (mpanes % self._P)).astype(np.int32)
        values_p = jax.tree_util.tree_unflatten(
            treedef, [_pad_rows(a, Bmp) for a in vleaves])
        mb = (flat.nbytes + sum(a.nbytes for a in vleaves)) / 1e6
        try:
            with self._phase("device_dispatch"):
                res = self._guarded_update(flat, values_p, mb)
        except DeviceQuarantinedError as err:
            self._devprobe_degrade(err)
            return
        self._leaves, self._counts = res[0], res[1]

    def _devprobe_degrade(self, err: BaseException, keys=None, panes=None,
                          values=None) -> None:
        """Quarantine mid-batch with the device probe active: salvage the
        unsynced delta into the mirror (under the monitor's bounded
        salvage deadline — a REALLY wedged device fails the pull and the
        task restarts from the last checkpoint, whose snapshot always
        drained the delta first), drop the probe state, degrade the tier,
        and — when ``keys`` is given — fold those not-yet-accounted rows
        through the host pass so no record is lost.  Call sites that fail
        AFTER every record reached mirror-land (warm rows in the delta,
        misses C-folded) pass no rows."""
        from flink_tpu.runtime import device_health
        try:
            if self._delta_counts is not None and self._delta_panes:
                # donated-buffer safety (PR-4's _enter_degraded guard,
                # extended to the probe/scan lanes' delta planes): a
                # genuinely timed-out dispatch may already have CONSUMED
                # the donated delta arrays — salvaging a deleted buffer is
                # a use-after-free, so fail the salvage up front and take
                # the restart path (the last checkpoint always drained the
                # delta first)
                if any(getattr(a, "is_deleted", lambda: False)()
                       for a in (self._delta_counts,
                                 *(self._delta_leaves or ()))):
                    raise RuntimeError(
                        "delta planes were donated into the abandoned "
                        "dispatch (consumed); in-process salvage is "
                        "impossible")
                mon = device_health.get_monitor(create=False)
                if mon is not None:
                    mon.run_salvage(
                        lambda: self._devprobe_sync_mirror(None),
                        label=f"{self.name} delta salvage")
                else:
                    self._devprobe_sync_mirror(None)
        except Exception as serr:  # noqa: BLE001 — delta unrecoverable
            raise err from serr
        self._drop_delta()
        self._dki = None
        self._devprobe_resolved = None   # re-resolve after a heal
        self._enter_degraded(err)        # host tier: flags only
        if keys is None or len(keys) == 0:
            return
        with self._phase("probe_mirror"):
            if self._nm is not None:
                lifted = [np.asarray(l) for l in jax.tree_util.tree_leaves(
                    self.agg.host_lift(values))]
                nshards, shard_div, shard_ns = self._probe_shards()
                self._nm.probe_update(keys, panes, lifted, shards=nshards,
                                      shard_div=shard_div,
                                      shard_ns=shard_ns)
            else:
                slots = self.key_index.lookup_or_insert(keys)
                self._vmirror_update(slots, panes, values)

    def devprobe_step_cache_size(self) -> Dict[str, int]:
        """Compiled-variant counts of the probed steps (the tier-1
        sticky-capacity recompile smoke, like PR 6's exchange test):
        steady state must be exactly one compile per (table capacity,
        K_cap, batch geometry)."""
        out = {}
        for name in ("_probed_update_step", "_probed_delta_step"):
            fn = getattr(type(self), name)
            try:
                out[name] = int(fn._cache_size())
            except Exception:  # noqa: BLE001 — jax without the cache probe
                out[name] = -1
        return out

    # ------------------------------------------------- fused megastep lane
    def _fused_depth(self, sync: str) -> int:
        """Resolved super-batch staging depth for this batch (1 = off).
        Resolution happens once per operator (like the sync cadence and the
        device-probe verdict): forced by ``superbatch > 1``, measured by
        ``calibrated_superbatch`` on auto.  Only the host emit tier stages —
        its f64/i64 mirror makes regrouped accumulation bit-exact, and its
        fires/snapshots already funnel through the flush barrier.  While
        the sync cadence is still calibrating, batches run unfused (the
        calibration measures per-batch dispatch cost)."""
        if sync not in ("scatter", "deferred"):
            return 1
        if self._fused_resolved is None:
            if (self.emit_tier != "host" or self._pager is not None
                    or self.trigger.fires_on_count
                    or self.superbatch == 1):
                self._fused_resolved = 1
            elif self.superbatch > 1:
                self._fused_resolved = self.superbatch
            else:
                from flink_tpu.operators.fused_step import \
                    calibrated_superbatch
                self._fused_resolved = calibrated_superbatch()
        return self._fused_resolved

    def _fused_pending(self) -> bool:
        return bool(self._fused_stage)

    def fused_stats(self) -> Dict[str, Any]:
        """Fused-lane counters (monitoring-grade, no pipeline barrier —
        the ``paging_stats`` contract): staging depth, flush/dispatch
        counts, and the guarded hot-path dispatch total the bench divides
        into dispatches/batch."""
        s = dict(self._fused_counters)
        depth = self._fused_resolved or (self.superbatch
                                         if self.superbatch > 1 else 0)
        s["enabled"] = int((self._fused_resolved or 1) > 1)
        s["depth"] = depth
        s["staged_pending"] = len(self._fused_stage)
        s["hot_dispatches"] = self._hot_dispatches
        return s

    def fused_step_cache_size(self) -> Dict[str, int]:
        """Compiled-variant counts of the scan megasteps (the tier-1
        sticky-geometry recompile smoke, the ``_cache_size`` pattern of
        PR 6/7): steady state must be exactly one compile per (table
        capacity, K_cap, P, scan depth, step width, value spec)."""
        out = {}
        for name in ("_fused_scan_update_step", "_fused_scan_delta_step"):
            fn = getattr(type(self), name)
            try:
                out[name] = int(fn._cache_size())
            except Exception:  # noqa: BLE001 — jax without the cache probe
                out[name] = -1
        return out

    def _fused_flush(self) -> None:
        """Advance every staged micro-batch in ONE pass.  Scan-capable
        operators with the device probe active take the single-dispatch
        ``lax.scan`` lane; everything else concatenates and runs the fused
        host pass once (still one replica dispatch per super-batch under
        scatter sync).  Runs wherever the stage filled (pipeline worker or
        task thread) — never concurrently, see ``SuperBatchStage``."""
        if not self._fused_stage:
            return
        st = self._fused_stage.take()
        self._fused_counters["flushes"] += 1
        sync = self.device_sync_mode or "deferred"
        if self._degraded:
            sync = "deferred"
        if (self._FUSED_SCAN and len(st) > 1
                and self._devprobe_active(sync)):
            self._fused_flush_scan(st, sync)
            return
        from flink_tpu.operators.fused_step import concat_staged
        if len(st) == 1:
            # a fire boundary (or state read) drained a single staged
            # batch: that is the plain per-batch path, not a super pass
            keys, panes, values, B = st[0]
        else:
            self._fused_counters["host_super_passes"] += 1
            with self._phase("fused_scan"):
                keys, panes, values, B = concat_staged(st)
        if self._devprobe_active(sync):
            # scan-incapable subclass (mesh): one per-super-batch probe
            # pass — the probe, exchange, and miss fold each amortize
            # across the staged batches
            self._hot_stage_devprobe(keys, panes, values, B, sync)
            return
        self._hot_stage_fold(keys, panes, values, B, sync,
                             super_pass=len(st) > 1)

    def _fused_super_shards(self):
        """(shards, shard_div, shard_ns) for the fused host SUPER pass:
        the per-batch calibration measured thread-pool wake against one
        micro-batch — re-measure at super-batch size (fused_step.
        calibrated_super_shards) and take whichever is larger.  Mesh
        subclasses keep their device-aligned contiguous ranges."""
        nshards, shard_div, shard_ns = self._probe_shards()
        if shard_div == 0 and self.native_shards == 0:
            if not self._fused_shards:
                from flink_tpu.operators.fused_step import \
                    calibrated_super_shards
                self._fused_shards = calibrated_super_shards()
            nshards = max(nshards, self._fused_shards)
        return nshards, shard_div, shard_ns

    def _fused_flush_scan(self, st, sync: str) -> None:
        """The scan lane: stage the super-batch as padded [N, B] planes
        (sticky pow2 high-water on both axes) and advance all N steps in
        ONE jitted dispatch over donated state buffers.  Only the per-step
        compact miss lists and the scalar miss total (the sync point) come
        back; the host pass then touches misses only, in step order — the
        same slot-assignment order as the per-batch path."""
        from flink_tpu.runtime import device_health
        self._ensure_alloc()
        self._ensure_delta()
        if self._dki is None:
            from flink_tpu.state.device_keyindex import DeviceKeyIndex
            self._dki = DeviceKeyIndex(
                initial_capacity=max(1 << 16, 2 * self._K),
                sharding=self._devprobe_table_sharding())
        self._dki.ensure_loaded(self.key_index)
        with self._phase("fused_scan"):
            N = len(st)
            bp = max(_next_pow2(int(s[3]), 64) for s in st)
            self._fused_bp_hw = bp = max(self._fused_bp_hw, bp)
            self._fused_n_hw = nhw = max(self._fused_n_hw,
                                         _next_pow2(N, 1))
            klo = np.zeros((nhw, bp), np.int32)
            khi = np.zeros((nhw, bp), np.int32)
            stt = np.zeros((nhw, bp), np.int32)
            ps = np.zeros((nhw, bp), np.int32)
            bs = np.zeros(nhw, np.int32)   # pad steps: b=0, all rows dropped
            treedef = jax.tree_util.tree_structure(st[0][2])
            leaves0 = [np.asarray(a)
                       for a in jax.tree_util.tree_leaves(st[0][2])]
            vplanes = [np.zeros((nhw, bp) + a.shape[1:], a.dtype)
                       for a in leaves0]
            for i, (keys, panes, values, B) in enumerate(st):
                lo, hi, start = self._dki.prepare_batch(keys)
                klo[i, :B] = lo
                khi[i, :B] = hi
                stt[i, :B] = start
                ps[i, :B] = (panes % self._P).astype(np.int32)
                bs[i] = B
                for j, a in enumerate(jax.tree_util.tree_leaves(values)):
                    vplanes[j][i, :B] = np.asarray(a)
            mb = (16 * nhw * bp + sum(v.nbytes for v in vplanes)) / 1e6
            tab = self._dki.table()
            geom = ("fused_scan", sync, self._dki.capacity, self._K,
                    self._P, nhw, bp,
                    tuple((v.dtype.str, v.shape[2:]) for v in vplanes))
            fresh_geom = geom != getattr(self, "_last_dispatch_geom", None)
            self._last_dispatch_geom = geom

            def thunk():
                with _x64():
                    if sync == "deferred":
                        out = self._fused_scan_delta_step(
                            tab, self._delta_leaves, self._delta_counts,
                            bs, klo, khi, stt, ps, tuple(vplanes), treedef)
                    else:
                        out = self._fused_scan_update_step(
                            tab, self._leaves, self._counts,
                            self._delta_leaves, self._delta_counts,
                            bs, klo, khi, stt, ps, tuple(vplanes), treedef)
                # the scalar miss total is the dispatch's sync point: a
                # wedged device must surface HERE, under the watchdog
                return out, int(np.asarray(out[-1]).sum())

            try:
                self._hot_dispatches += 1
                res, total_miss = device_health.guarded_dispatch(
                    thunk, mb=mb, on_oom=None,
                    label=f"{self.name}.fused_scan",
                    compile_grace=fresh_geom)
            except DeviceQuarantinedError as err:
                self._fused_scan_degrade(err, st)
                return
            self._fused_counters["scan_dispatches"] += 1
            self._fused_counters["scan_steps"] += N
            if sync == "deferred":
                (self._delta_leaves, self._delta_counts,
                 miss_idx, miss_counts) = res
                self._device_stale = True
            else:
                (self._leaves, self._counts, self._delta_leaves,
                 self._delta_counts, miss_idx, miss_counts) = res
                self.phase_bytes["h2d"] = \
                    self.phase_bytes.get("h2d", 0) + mb
            for _keys, panes, _values, _B in st:
                self._delta_panes.update(
                    int(p) for p in np.unique(panes).tolist())
            total_rows = int(sum(s[3] for s in st))
            self._dp_stats["probe_hits"] += total_rows - total_miss
            self._dp_stats["probe_misses"] += total_miss
        if total_miss:
            self._fused_handle_misses(st, np.asarray(miss_idx),
                                      np.asarray(miss_counts), sync)

    def _fused_handle_misses(self, st, miss_idx, miss_counts,
                             sync: str) -> None:
        """Post-scan host pass over the compact per-step miss lists, in
        step (= batch) order, so new keys get exactly the slot ids the
        per-batch path would assign.  A key first seen mid-super-batch
        misses on every later step too (the device table is immutable
        during the scan); its rows all land here, folding into the SAME
        mirror cells the warm path would have used — bit-identical under
        the mirror's exact accumulation."""
        parts = []
        for i, (keys, panes, values, _B) in enumerate(st):
            mc = int(miss_counts[i])
            if not mc:
                continue
            mi = miss_idx[i, :mc].astype(np.int64)
            mkeys = np.ascontiguousarray(keys[mi])
            mpanes = np.ascontiguousarray(panes[mi])
            mvalues = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[mi], values)
            mslots = self._devprobe_absorb_misses(mkeys, mpanes, mvalues)
            if sync != "deferred":
                parts.append((mslots, mpanes, mvalues))
        if sync == "deferred" or not parts:
            return
        # ONE guarded update folds every step's miss rows (the mirror-
        # precision story already landed above, so concatenation order
        # here only moves replica low bits — verify_mirror tolerance
        # territory)
        self._miss_replica_update(
            np.concatenate([p[0] for p in parts]),
            np.concatenate([p[1] for p in parts]),
            jax.tree_util.tree_structure(parts[0][2]),
            [np.concatenate([np.asarray(l) for l in ls])
             for ls in zip(*[jax.tree_util.tree_leaves(p[2])
                             for p in parts])])

    def _fused_scan_degrade(self, err: BaseException, st) -> None:
        """A quarantined scan dispatch.  The scan is transactional — one
        ``guarded_dispatch``, like PR-8's ``cep.vectorized_drain``: the
        watchdog's failure modes precede execution (the chaos point fires
        before the thunk; an abandoned lane skips it), so NO staged row
        reached any state plane.  Salvage the PRIOR delta into the mirror
        (with the donated-buffer guard — planes a genuinely timed-out
        dispatch already consumed fail the salvage and take the restart
        path), degrade the tier, and refold EVERY staged batch through the
        host pass so no record is lost."""
        from flink_tpu.operators.fused_step import concat_staged
        keys, panes, values, _B = concat_staged(st)
        self._devprobe_degrade(err, keys, panes, values)

    # ------------------------------------------------------------- pipeline
    def _pipe_active(self) -> bool:
        """Pipelining applies to the time-triggered hot path only: count
        triggers read device counts inside ``process_batch`` itself, which
        would force a barrier per batch (i.e. the serial path anyway)."""
        return self.pipeline_depth > 0 and not self.trigger.fires_on_count

    def _pipe_pending(self) -> bool:
        return self._pipe is not None and self._pipe.pending()

    def flush_pipeline(self) -> List[StreamElement]:
        """Pipeline barrier: complete every in-flight hot stage AND fold
        any staged super-batch (the fused lane's flush boundary).  Called
        internally before any state read (fires, snapshots, verification)
        and by task drivers at idle points so pipelined/staged results
        never wait on the NEXT batch's arrival.  Safe no-op when both
        lanes are off."""
        if self._pipe is not None:
            self._pipe.flush()
        self._fused_flush()
        return []

    def _staging_acquire(self, Bp: int, leaves, treedef) -> _Staging:
        key = (Bp, treedef,
               tuple((a.dtype.str, a.shape[1:]) for a in leaves))
        pool = self._staging_pool.setdefault(key, [])
        for st in pool:
            if st.ready():
                st.token = None
                return st
        st = _Staging(Bp, leaves, treedef)
        if len(pool) < 4:  # bounded: beyond that, dispatch is the backlog
            pool.append(st)
        return st

    def _resolve_device_sync(self) -> str:
        """Resolved sync cadence for this batch: "scatter", "deferred", or
        "calibrating" (= scatter + measure this batch's dispatch cost)."""
        if self.device_sync_mode is not None:
            return self.device_sync_mode
        if (self.device_sync == "scatter" or self.emit_tier != "host"
                or (self.sharding is not None
                    and not self._SHARDED_HOST_TIER)
                or self.snapshot_source != "mirror"):
            self.device_sync_mode = "scatter"
        elif self.device_sync == "deferred":
            self.device_sync_mode = "deferred"
        else:  # auto
            # EVERY backend calibrates, the CPU backend included: there the
            # "transport" is the XLA dispatch compute itself — a CPU scatter
            # costs ~0.5µs/update (measured; independent of state size), so
            # on slow boxes the per-batch replica sync dwarfs the entire
            # native mirror fold.  Small-batch workloads never produce a
            # calibration sample (transport.MIN_SAMPLE_MB) and settle on
            # scatter — deterministic for unit-test-sized traffic.
            from flink_tpu.utils import transport
            taxed = transport.dispatch_taxed()
            if taxed is None:
                if self._calib_batches < 8:
                    self._calib_batches += 1
                    return "calibrating"
                # batches too small to ever yield a calibration sample
                # (transport.MIN_SAMPLE_MB): stop probing — scatter,
                # without the per-batch measurement block
                self.device_sync_mode = "scatter"
            else:
                self.device_sync_mode = ("deferred" if taxed
                                         else "scatter")
        return self.device_sync_mode

    def _mirror_columns(self, panes, rows: int,
                        ncols: Optional[int] = None):
        """Dense device-dtype columns of the host mirror: counts int32
        [rows, ncols] plus one [rows, ncols, *shape] array per leaf, column
        j holding pane ``panes[j]`` (missing panes and pad columns =
        identity).  The single source of the mirror export semantics —
        identity fill, int64->int32 counts, mirror->device dtype casts —
        shared by mirror-sourced snapshots and the deferred-sync refresh."""
        # device-probe delta: every mirror READER lands here (snapshots,
        # refresh, re-promotion) — drain ALL unsynced panes first
        self._devprobe_sync_mirror(None)
        ncols = len(panes) if ncols is None else ncols
        counts = np.zeros((rows, ncols), np.int32)
        leaves = []
        for init, shape, d in zip(self.spec.leaf_inits,
                                  self.spec.leaf_shapes,
                                  self.spec.leaf_dtypes):
            arr = np.empty((rows, ncols) + tuple(shape), d)
            arr[...] = np.asarray(init).astype(d)
            leaves.append(arr)
        for j, p in enumerate(panes):
            if self._nm is not None:
                ex, cnts, lvs = self._nm.export_pane(int(p), rows)
                if not ex:
                    continue
                counts[:, j] = cnts  # int64 -> int32 cast
                for dst, src in zip(leaves, lvs):
                    dst[:, j] = src  # mirror -> device dtype cast
            else:
                e = self._vmirror.get(int(p))
                if e is None:
                    continue
                counts[:, j] = e[0][:rows]
                for k, dst in enumerate(leaves):
                    dst[:, j] = e[k + 1][:rows].astype(
                        self.spec.leaf_dtypes[k], copy=False)
        return counts, leaves

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2))
    def _refresh_step(self, leaves, counts, slots, counts_cols, leaf_cols):
        """Replace the whole ring from live-pane COLUMNS: slots i32[m] are
        the live ring slots (pads = P, dropped), counts_cols [rows, m] with
        rows <= K covering the live keys, each leaf col [rows, m, *shape].
        Every other cell resets to identity — the upload scales with live
        panes x live keys, not ring/key capacity."""
        rows = counts_cols.shape[0]
        new_counts = jnp.zeros_like(counts).at[:rows, slots].set(
            counts_cols, mode="drop")
        new_leaves = tuple(
            jnp.broadcast_to(jnp.asarray(init, l.dtype), l.shape)
            .at[:rows, slots].set(col, mode="drop")
            for l, init, col in zip(leaves, self.spec.leaf_inits, leaf_cols))
        if self.sharding is not None:
            # the refresh must hand back PRE-PARTITIONED state (out
            # shardings == the update step's in shardings): without the
            # constraint XLA commits the scatter of replicated host
            # columns onto one device and the next dispatch pays a
            # reshard (the compile-once smoke's failure mode)
            new_counts = jax.lax.with_sharding_constraint(new_counts,
                                                          self.sharding)
            new_leaves = tuple(jax.lax.with_sharding_constraint(
                l, self.sharding) for l in new_leaves)
        return new_leaves, new_counts

    def device_refresh(self) -> None:
        """Rebuild the device replica from the authoritative host mirror
        (deferred sync's sync point — restore, verification, idle, or an
        explicit pre-mesh handoff).  Set semantics over the whole ring:
        slots without a live pane reset to identity, which also folds in
        any expirations skipped while deferred; uploaded bytes scale with
        live panes.  No-op when the replica is already current."""
        self.flush_pipeline()
        if self._degraded:
            return  # no refresh while quarantined; re-promotion rebuilds
        if not self._device_stale:
            return
        self._device_stale = False
        if self.key_index is None or self.pane_base is None:
            return
        self._ensure_alloc()
        n = self.key_index.num_keys
        present = (set(self._nm.live_panes().tolist()) if self._nm is not None
                   else set(self._vmirror))
        hi = self.pane_base if self.max_pane is None else self.max_pane
        live = [int(p) for p in range(self.pane_base, hi + 1)
                if int(p) in present]
        m = _next_pow2(max(len(live), 1), 1)  # pad: bounded compile count
        # rows cover live keys only (pow2-quantized for a bounded compile
        # count), not key capacity: a 1M-capacity operator holding 10k keys
        # refreshes ~80KB columns, not ~8MB
        rows = min(_next_pow2(max(n, 1), 1024), self._K)
        slots = np.full(m, self._P, np.int32)  # P = out of range, dropped
        slots[:len(live)] = [p % self._P for p in live]
        counts_cols, leaf_cols = self._mirror_columns(live, rows, ncols=m)
        self._leaves, self._counts = self._refresh_step(
            self._leaves, self._counts, slots, counts_cols, tuple(leaf_cols))
        self.phase_bytes["h2d_refresh"] = (
            self.phase_bytes.get("h2d_refresh", 0) + counts_cols.nbytes
            + sum(l.nbytes for l in leaf_cols))

    def _vmirror_pane(self, pane: int) -> list:
        """[counts, *leaves] arrays for a pane, allocated/grown to >=
        max(_K, live keys) — a DEGRADED paged operator holds every key in
        the mirror, not just the K_cap-resident prefix."""
        need = self._K
        if self._degraded and self.key_index is not None:
            need = max(need, _next_pow2(max(self.key_index.num_keys, 1)))
        entry = self._vmirror.get(pane)
        if entry is None or entry[0].size < need:
            fresh = [np.zeros(need, np.int64)]
            for init, shape, mdt in zip(self.spec.leaf_inits,
                                        self.spec.leaf_shapes,
                                        self._mirror_dtypes):
                arr = np.empty((need,) + tuple(shape), mdt)
                arr[...] = np.asarray(init).astype(mdt)
                fresh.append(arr)
            if entry is not None:
                n = entry[0].size
                for f, o in zip(fresh, entry):
                    f[:n] = o
            entry = self._vmirror[pane] = fresh
        return entry

    @staticmethod
    def _host_scatter(kind: str, arr: np.ndarray, slots: np.ndarray,
                      vals: np.ndarray) -> None:
        """In-place segment combine ``arr[slots] op= vals`` (numpy twin of
        ops/scatter.py).  add on scalar leaves: one bincount; min/max and
        non-scalar leaves: sort + ufunc.reduceat (ufunc.at is ~50x slower)."""
        if kind == "add" and vals.ndim == 1:
            arr += np.bincount(slots, weights=vals,
                               minlength=arr.size).astype(arr.dtype,
                                                          copy=False)
            return
        ufunc = SCATTER_UFUNCS[kind]
        order = np.argsort(slots, kind="stable")
        ss = slots[order]
        vv = vals[order]
        starts = np.flatnonzero(np.r_[True, ss[1:] != ss[:-1]])
        red = ufunc.reduceat(vv, starts, axis=0)
        uniq = ss[starts]
        arr[uniq] = ufunc(arr[uniq], red)

    def _vmirror_update(self, slots: np.ndarray, panes: np.ndarray,
                        values) -> None:
        """Fold this batch into the host mirror — same (slot, pane, value)
        triples as the device scatter, evaluated with the agg's numpy twins."""
        lifted = jax.tree_util.tree_leaves(self.agg.host_lift(values))
        lifted = [np.asarray(l) for l in lifted]
        for p in np.unique(panes).tolist():
            m = panes == p
            s = slots[m] if not m.all() else slots
            entry = self._vmirror_pane(int(p))
            entry[0] += np.bincount(s, minlength=entry[0].size)
            for j, (kind, leaf) in enumerate(zip(self.kinds, lifted)):
                self._host_scatter(kind, entry[j + 1], s,
                                   leaf[m] if not m.all() else leaf)

    def _fire_window_host(self, window_id: int,
                          panes: np.ndarray) -> List[StreamElement]:
        """Serve a window fire ENTIRELY from the host mirror: no device op,
        no download — the emit path for egress-constrained links.  With
        the device probe active the mirror first catches up on exactly the
        panes about to fire (the bounded pane-granular delta pull)."""
        self._devprobe_sync_mirror(panes)
        n = self.key_index.num_keys if self.key_index is not None else 0
        if n == 0:
            return []
        if self._nm is not None:
            # one C sweep: combine panes, compact non-empty rows, resolve keys
            keys, _counts, leaves = self._nm.fire(panes)
            if keys.size == 0:
                return []
            result = self.agg.host_get_result(self.spec.unflatten(leaves))
            return self._rows_for_keys(
                keys, result, self.assigner.window_bounds(window_id))
        entries = [self._vmirror[int(p)] for p in panes.tolist()
                   if int(p) in self._vmirror]
        if not entries:
            return []
        total = entries[0][0][:n].copy()
        for e in entries[1:]:
            total += e[0][:n]
        idx = np.flatnonzero(total > 0)
        if idx.size == 0:
            return []
        acc_leaves = []
        for j, kind in enumerate(self.kinds):
            ufunc = SCATTER_UFUNCS[kind]
            leaf = entries[0][j + 1][idx]
            for e in entries[1:]:
                leaf = ufunc(leaf, e[j + 1][idx])
            acc_leaves.append(leaf)
        result = self.agg.host_get_result(self.spec.unflatten(acc_leaves))
        return self._rows_for(idx, result,
                              self.assigner.window_bounds(window_id))

    def verify_mirror(self, atol: float = 1e-3, rtol: float = 1e-4) -> bool:
        """Consistency check: download the device state for live panes and
        compare against the host mirror (the device is the authoritative
        replica; the mirror must be its higher-precision twin).  Costly on
        slow links — meant for tests and sampled bench validation.

        Under deferred sync the replica is refreshed first, so the check
        validates the refresh round trip (mirror -> upload -> download ->
        compare: ring mapping, dtype casts, expiry folds) rather than
        continuous per-batch equality — which deferred mode by design does
        not maintain between sync points."""
        self.flush_pipeline()
        if self._degraded:
            return True  # replica intentionally stale/absent in quarantine
        if self.device_sync_mode == "deferred":
            self.device_refresh()
        self._devprobe_sync_mirror(None)   # mirror must be caught up
        if self.emit_tier != "host" or self._leaves is None \
                or self.pane_base is None:
            return True
        n = self.key_index.num_keys if self.key_index else 0
        for p in range(self.pane_base, (self.max_pane or 0) + 1):
            slot = int(p) % self._P
            dev_counts = np.asarray(self._counts[:n, slot])
            if self._nm is not None:
                _ex, cnts, lvs = self._nm.export_pane(p, n)
                host = [cnts] + lvs
            else:
                host = self._vmirror.get(p)
            host_counts = (host[0][:n] if host is not None
                           else np.zeros(n, np.int64))
            if not np.array_equal(dev_counts, host_counts):
                return False
            for j in range(self.spec.num_leaves):
                dev = np.asarray(self._leaves[j][:n, slot], np.float64)
                hst = (np.asarray(host[j + 1][:n], np.float64)
                       if host is not None
                       else np.broadcast_to(np.asarray(
                           self.spec.leaf_inits[j], np.float64), dev.shape))
                # compare in DEVICE precision: the mirror carries more bits
                hst32 = hst.astype(self.spec.leaf_dtypes[j]).astype(np.float64)
                if not np.allclose(dev, hst32, atol=atol, rtol=rtol,
                                   equal_nan=True):
                    return False
        return True

    def _round_key_capacity(self, needed: int) -> int:
        """pow2 growth; subclasses may strengthen (e.g. mesh divisibility).
        Paged state never grows: overflow pages out instead."""
        if self._pager is not None:
            return self._K
        return _next_pow2(needed, self._K)

    def _grow_keys(self, needed: int):
        newK = self._round_key_capacity(needed)
        if newK == self._K and self._leaves is not None:
            return
        old_leaves, old_counts = self._leaves, self._counts
        self._K = newK
        # grow EVERY live mirror pane with the capacity: a pane untouched
        # after the growth must still serve fires/snapshots at the new key
        # count (the lazy per-touch grow only covers touched panes)
        for p in list(self._vmirror):
            self._vmirror_pane(p)
        fresh, fresh_counts = self._alloc(self._K, self._P)
        if old_leaves is not None:
            n = old_counts.shape[0]
            self._leaves = tuple(f.at[:n].set(o) for f, o in zip(fresh, old_leaves))
            self._counts = fresh_counts.at[:n].set(old_counts)
        else:
            self._leaves, self._counts = fresh, fresh_counts

    def _grow_panes(self, span: int):
        """Double the pane ring until it holds ``span`` live panes, remapping
        slot = pane % P_old -> pane % P_new for retained panes."""
        newP = self._P
        while newP < span:
            newP <<= 1
        if newP == self._P:
            return
        old_leaves, old_counts, oldP = self._leaves, self._counts, self._P
        self._P = newP
        fresh, fresh_counts = self._alloc(self._K, newP)
        if old_leaves is not None and self.pane_base is not None:
            panes = np.arange(self.pane_base, self.max_pane + 1, dtype=np.int64)
            src = jnp.asarray(panes % oldP, jnp.int32)
            dst = jnp.asarray(panes % newP, jnp.int32)
            self._leaves = tuple(
                f.at[:, dst].set(jnp.take(o, src, axis=1))
                for f, o in zip(fresh, old_leaves))
            self._counts = fresh_counts.at[:, dst].set(jnp.take(old_counts, src, axis=1))
        else:
            self._leaves, self._counts = fresh, fresh_counts

    # ------------------------------------------------------------- device ops
    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2))
    def _update_step(self, leaves, counts, flat_ids, values):
        """One micro-batch fold: lift + scatter-combine. flat_ids ∈ [0, K*P]
        with K*P meaning 'dropped padding row'."""
        K, P = counts.shape
        lifted = tuple(jax.tree_util.tree_leaves(self.agg.lift(values)))
        flat_leaves = tuple(l.reshape((K * P,) + l.shape[2:]) for l in leaves)
        if self.kinds is not None:
            new_flat = scatter_fast(flat_leaves, flat_ids, lifted, self.kinds)
        else:
            new_flat = scatter_generic(flat_leaves, flat_ids, lifted,
                                       self.agg.combine_leaves, K * P)
        new_leaves = tuple(l.reshape((K, P) + l.shape[1:]) for l in new_flat)
        ones = jnp.ones(flat_ids.shape, jnp.int32)  # device-side: keeps the
        # host→device upload to ids+values only (tunnel bandwidth-bound)
        new_counts = counts.reshape(K * P).at[flat_ids].add(ones, mode="drop").reshape(K, P)
        # scalar completion token: ready exactly when THIS execution
        # finished — the staging-reuse gate (new_counts itself is donated
        # into the next step, so its own readiness is unobservable)
        return new_leaves, new_counts, new_counts[0, 0]

    def _fire_core(self, leaves, counts, pane_slots, k_active: int):
        """Shared fire body: slice live rows, gather window panes, combine,
        get_result.  k_active (static): only the first k_active key rows are
        live — slicing inside the jit lets XLA fuse slice+gather, so fire cost
        scales with live keys, not allocated capacity."""
        if k_active and k_active < counts.shape[0]:
            leaves = tuple(jax.lax.slice_in_dim(l, 0, k_active, axis=0)
                           for l in leaves)
            counts = jax.lax.slice_in_dim(counts, 0, k_active, axis=0)
        sel = tuple(jnp.take(l, pane_slots, axis=1) for l in leaves)
        total = jnp.take(counts, pane_slots, axis=1).sum(axis=1)
        combined = combine_along_axis(sel, self.agg.combine_leaves, axis=1)
        result = self.agg.get_result(self.spec.unflatten(combined))
        return total > 0, result

    @partial(jax.jit, static_argnums=(0, 4))
    def _fire_step(self, leaves, counts, pane_slots, k_active: int):
        return self._fire_core(leaves, counts, pane_slots, k_active)

    @partial(jax.jit, static_argnums=(0, 4))
    def _fire_acc_step(self, leaves, counts, pane_slots, k_active: int):
        """Like ``_fire_step`` but returns the combined ACCUMULATOR leaves
        (pre-``get_result``): the purging-count-trigger path subtracts the
        per-window value baseline from the acc before producing output."""
        if k_active and k_active < counts.shape[0]:
            leaves = tuple(jax.lax.slice_in_dim(l, 0, k_active, axis=0)
                           for l in leaves)
            counts = jax.lax.slice_in_dim(counts, 0, k_active, axis=0)
        sel = tuple(jnp.take(l, pane_slots, axis=1) for l in leaves)
        total = jnp.take(counts, pane_slots, axis=1).sum(axis=1)
        combined = combine_along_axis(sel, self.agg.combine_leaves, axis=1)
        return total > 0, combined

    def _k_active(self) -> int:
        """Static pow2 bound on live key rows (0 = use full capacity).
        Sharded state skips slicing: the slice would break even row
        distribution across devices."""
        if self.sharding is not None or self.key_index is None:
            return 0
        # ×4 growth steps: every distinct value is one XLA compile of the fire
        # step — coarse quantization caps the compile count at ~5 per run
        # (paged: live rows are bounded by the assigned-row high-water mark,
        # not by key cardinality)
        n = (self._pager.row_high_water if self._pager is not None
             else self.key_index.num_keys)
        ka = 4096
        while ka < n:
            ka <<= 2
        return min(ka, self._K)

    @partial(jax.jit, static_argnums=(0,))
    def _fire_gather_step(self, leaves, pane_slots, idx):
        """Fire for a host-known emit set: gather the ``idx`` key rows FIRST
        (compute and download scale with rows *emitted*, not key capacity),
        combine their window panes, ``get_result``.  The emit index is
        host-derived from the mirror — nothing but result values ever rides
        the (slow) device->host direction.  The batched analog of the
        reference emitting only non-empty windows
        (``WindowOperator.emitWindowContents:574``)."""
        sel = tuple(jnp.take(jnp.take(l, idx, axis=0), pane_slots, axis=1)
                    for l in leaves)
        combined = combine_along_axis(sel, self.agg.combine_leaves, axis=1)
        return self.agg.get_result(self.spec.unflatten(combined))

    def _fire_window_gather(self, window_id: int,
                            panes: np.ndarray) -> List[StreamElement]:
        """Mirror-indexed fire (unsharded state): exact emit set from the
        host mirror, one values-only download."""
        idx = self._mirror_emit_idx(panes)
        n = idx.size
        if n == 0:
            return []
        cap = _quantize_cap(n)
        idx_p = np.zeros(cap, np.int32)
        idx_p[:n] = idx
        pane_slots = jnp.asarray(panes % self._P, jnp.int32)
        result = self._fire_gather_step(self._leaves, pane_slots,
                                        jnp.asarray(idx_p))
        handle = _fetch_enqueue(jax.tree_util.tree_leaves(result))
        treedef = jax.tree_util.tree_structure(result)
        if self._pager is not None:
            # rows -> global ids NOW: by the time an async fire drains, a
            # row may have been evicted and reassigned to another key
            idx = self._pager.gid_of[idx]
        if self.async_fire:
            self._pending_fires.append((window_id, idx, handle, treedef))
            return []
        return self._finish_gather_fire(window_id, idx, handle, treedef)

    def drain_pending_fires(self, force: bool = False) -> List[StreamElement]:
        """Materialize async fire downloads IN ORDER, but only those whose
        transfers completed (unless ``force``): blocking on an in-flight
        download would re-serialize it with the next batch's device work —
        the whole point of async_fire is that fires stream out in the
        background.  Depth is bounded so memory stays bounded."""
        if not self._pending_fires:
            return []
        if len(self._pending_fires) > 3:
            force = True
        out: List[StreamElement] = []
        while self._pending_fires:
            window_id, idx, handle, treedef = self._pending_fires[0]
            if not force and not _handle_ready(handle):
                break
            self._pending_fires.pop(0)
            out.extend(self._finish_gather_fire(window_id, idx, handle,
                                                treedef))
        return out

    def _finish_gather_fire(self, window_id: int, idx: np.ndarray, handle,
                            treedef) -> List[StreamElement]:
        fetched = _fetch_collect(handle)
        self.phase_bytes["d2h"] = self.phase_bytes.get("d2h", 0) + \
            sum(f.nbytes for f in fetched)
        n = idx.size
        picked = jax.tree_util.tree_unflatten(
            treedef, [r[:n] for r in fetched])
        return self._rows_for(idx, picked,
                              self.assigner.window_bounds(window_id))

    def _rows_for(self, idx: np.ndarray, result,
                  window) -> List[StreamElement]:
        """Shared emit-row assembly (dense/packed/fallback fire paths).
        ``idx`` are global key-index slots; paged fire paths translate
        their HBM rows to global ids AT FIRE TIME (an eviction between an
        async fire and its drain must not re-attribute the emissions)."""
        keys = np.asarray(self.key_index.reverse_keys())[idx]
        return self._rows_for_keys(keys, result, window)

    def _rows_for_keys(self, keys: np.ndarray, result,
                       window) -> List[StreamElement]:
        n = len(keys)
        cols: Dict[str, Any] = {self.key_column: keys}
        if isinstance(result, dict):
            cols.update(result)
        else:
            cols[self.output_column] = result
        if self._qview is not None:
            # queryable live view: retain this fire's (keys, values) arrays
            # — every fire path (host mirror, device gather, spilled
            # chunks, degraded tier, mesh) funnels through here, so live
            # reads are bit-equal to fire-time values by construction
            self._qview.publish(
                keys, {c: v for c, v in cols.items()
                       if c != self.key_column},
                window, self.watermark, self._last_completed_checkpoint)
        if self.emit_window_bounds:
            # constant columns as 0-strided broadcast views: a 1M-row fire
            # would otherwise first-touch ~24MB of np.full pages per window
            cols["window_start"] = np.broadcast_to(
                np.int64(window.start), (n,))
            cols["window_end"] = np.broadcast_to(np.int64(window.end), (n,))
        ts = np.broadcast_to(np.int64(window.max_timestamp), (n,))
        return [RecordBatch(cols, timestamps=ts)]

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2))
    def _clear_panes_step(self, leaves, counts, pane_slots):
        new_leaves = []
        for l, init in zip(leaves, self.spec.leaf_inits):
            fill = jnp.broadcast_to(jnp.asarray(init, l.dtype),
                                    (l.shape[0], pane_slots.shape[0]) + l.shape[2:])
            new_leaves.append(l.at[:, pane_slots].set(fill))
        return tuple(new_leaves), counts.at[:, pane_slots].set(0)

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2))
    def _purge_keys_step(self, leaves, counts, key_mask):
        """Count-trigger purge: reset fired keys' state (FIRE_AND_PURGE)."""
        new_leaves = []
        for l, init in zip(leaves, self.spec.leaf_inits):
            fill = jnp.broadcast_to(jnp.asarray(init, l.dtype), l.shape)
            m = key_mask.reshape((-1,) + (1,) * (l.ndim - 1))
            new_leaves.append(jnp.where(m, fill, l))
        return tuple(new_leaves), jnp.where(key_mask[:, None], 0, counts)

    # --------------------------------------------------------------- batching
    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        pending = self.drain_pending_fires() if self.async_fire else []
        if len(batch) == 0:
            return pending
        step = max(self._K // 2, 1) if self._pager is not None else 0
        if step and len(batch) > step:
            # a batch's distinct keys (plus their eviction protections) must
            # fit the resident capacity: split oversized batches (comparing
            # against the clamped step keeps K_cap=1 from recursing forever)
            out = list(pending)
            for lo in range(0, len(batch), step):
                out.extend(self.process_batch(
                    batch.take(np.arange(lo, min(lo + step, len(batch))))))
            return out
        cols = batch.columns
        keys = np.asarray(cols[self.key_column])
        if self.key_index is None:
            self.key_index = make_key_index(keys[0] if keys.ndim else keys,
                                            capacity_hint=self._K)
        if self.assigner.is_event_time:
            if batch.timestamps is None:
                raise ValueError(
                    "event-time window requires timestamps "
                    "(assign_timestamps_and_watermarks upstream)")
            ts = np.asarray(batch.timestamps, np.int64)
        else:
            ts = np.full(len(batch), self._now_ms(), np.int64)
        panes = self.assigner.pane_of(ts)

        # ---- late-beyond-lateness drop, judged EXACTLY like the reference
        # (``WindowOperator.isElementLate``): a record is late iff its pane's
        # last covering window's cleanup time (end - 1 + lateness) has been
        # passed by time — NEVER by arrival order, so a parallel source
        # racing ahead cannot make slower sources' records unstorable
        # the gate's clock follows the assigner's time DOMAIN: wall-clock
        # _proc_time ticks even on event-time operators (periodic timer
        # service) and must never be compared against event-time panes
        gate_now = (self.watermark if self.assigner.is_event_time
                    else self._proc_time)
        if gate_now != LONG_MIN and not isinstance(self.assigner,
                                                   GlobalWindows):
            # candidate panes via [min, max] arange (batch panes are a few
            # contiguous values; np.unique over the batch costs ~ms each).
            # A wide span (straggler records) would turn the per-candidate
            # Python lateness calls below into the cost, so fall back to
            # the distinct panes then.
            p0, p1 = int(panes.min()), int(panes.max())
            cand = (np.arange(p0, p1 + 1, dtype=np.int64)
                    if p1 - p0 < 64 else np.unique(panes))
            is_late = np.asarray(
                [self.assigner.last_window_end_of_pane(int(p)) - 1
                 + self.lateness <= gate_now for p in cand.tolist()])
            if not is_late.any():
                live = np.ones(0, bool)  # nothing late: skip the gate body
            elif np.all(is_late[:-1] >= is_late[1:]):
                # lateness is a prefix of ascending panes (monotone cleanup
                # times): one vector compare instead of isin
                live = panes > int(cand[int(is_late.sum()) - 1])
            else:
                live = ~np.isin(panes, cand[is_late])
            if live.size and not live.all():
                if self.late_output_tag is not None:
                    # sideOutputLateData: rows are shipped, NOT dropped —
                    # the drop counter must stay at the reference semantics
                    # (WindowOperator.java:437 increments only when no side
                    # output consumes the element)
                    pending = list(pending) + [TaggedBatch(
                        self.late_output_tag, batch.select(~live))]
                else:
                    self.late_dropped += int(np.count_nonzero(~live))
                batch = batch.select(live)
                if len(batch) == 0:
                    return pending
                cols = batch.columns
                keys = np.asarray(cols[self.key_column])
                ts = ts[live]
                panes = panes[live]

        pmin, pmax = int(panes.min()), int(panes.max())
        values = self._select(cols)
        if self.incremental_state:
            # delta checkpoints: every (key, pane) this batch touches stays
            # dirty until a checkpoint containing it is CONFIRMED; raw keys
            # resolve to gids lazily at cut time (the index is append-only)
            self._incr_mark_batch(keys, panes)
        if self._pipe_active():
            # two-stage software pipeline: the hot stage (probe/mirror +
            # paging + device dispatch) runs on the background worker while
            # the main thread returns to the driver and the device executes
            # earlier dispatches.  Every state READ barriers through
            # flush_pipeline() (fires, snapshots, expiry, verification), so
            # observable behaviour is bit-identical to the serial path.
            if self._pipe is None:
                self._pipe = _HotPipeline(self.pipeline_depth)
            B = len(batch)
            self._pipe.submit(lambda: self._hot_stage(keys, panes, values,
                                                      B, pmin, pmax))
        else:
            self._hot_stage(keys, panes, values, len(batch), pmin, pmax)

        out: List[StreamElement] = list(pending)
        # ---- count-trigger (GlobalWindows / countWindow path)
        if self.trigger.fires_on_count:
            if isinstance(self.assigner, GlobalWindows):
                out.extend(self._fire_by_count())
            else:
                # CountTrigger over tumbling time windows: fire (key, window)
                # cells whose element count crossed the threshold
                out.extend(self._fire_count_in_panes(np.unique(panes)))
        # ---- late re-fire: windows already passed by the watermark that this
        # batch updated fire again immediately (EventTimeTrigger.onElement FIRE)
        if (self.trigger.fires_on_time and self.assigner.is_event_time
                and self.last_fired_window is not None
                # refire needs a touched pane of an already-fired window:
                # impossible when even the OLDEST touched pane's first window
                # is beyond the fired horizon (the common in-order case) —
                # skips the np.unique below, ~ms per hot-path batch
                and self.assigner.windows_of_pane(pmin)[0]
                <= self.last_fired_window):
            # re-fires read the mirror/device state: barrier first (rare —
            # only batches touching already-fired windows land here)
            self.flush_pipeline()
            touched = np.unique(panes)
            refire: List[int] = []
            for p in touched.tolist():
                w0, w1 = self.assigner.windows_of_pane(int(p))
                for w in range(w0, w1 + 1):
                    max_ts = self.assigner.window_bounds(w).max_timestamp
                    # only windows whose OWN cleanup horizon is still open:
                    # a sliding pane can outlive an early covering window
                    # the reference would already have purged
                    if (w <= self.last_fired_window
                            and max_ts <= self.watermark
                            and max_ts + self.lateness > self.watermark):
                        refire.append(w)
            for w in sorted(set(refire)):
                out.extend(self._fire_window(w))
        return out

    def _hot_stage(self, keys: np.ndarray, panes: np.ndarray, values,
                   B: int, pmin: int, pmax: int) -> None:
        """The pipelined hot stage of one micro-batch: pane-ring
        bookkeeping/growth, the fused probe/mirror pass, key growth,
        paging, and the device dispatch.  Runs inline when pipelining is
        off, on the ``_HotPipeline`` worker when on — the SAME code in the
        SAME order either way, so fire digests, snapshots, and counters
        cannot diverge between the two modes."""
        if self.pane_base is None:
            self.pane_base = pmin
            self.max_pane = pmax
        else:
            # grow BEFORE extending the live range: the remap copies the
            # old [pane_base, max_pane], which is alias-free only in the
            # old ring geometry.  The range extends DOWNWARD too — a
            # parallel source racing ahead must not make earlier panes
            # unstorable (only truly expired panes drop in the gate).
            new_base = min(self.pane_base, pmin)
            span = max(self.max_pane, pmax) - new_base + 1
            if span > self._P:
                self._grow_panes_guarded(span)
            self.pane_base = new_base
            self.max_pane = max(self.max_pane, pmax)
        span = self.max_pane - self.pane_base + 1
        if span > self._P:
            self._grow_panes_guarded(span)

        if self._degraded and self.emit_tier != "host":
            # quarantined device tier: the host value mirror is the
            # authority — key probe + numpy fold only (no paging, no
            # device dispatch); fires and snapshots read the mirror until
            # re-promotion at a checkpoint-aligned safe point
            with self._phase("probe"):
                slots = self.key_index.lookup_or_insert(keys)
            with self._phase("mirror"):
                # grow EVERY live pane with the key count (the _grow_keys
                # invariant): the per-touch growth below only covers this
                # batch's panes, and an UNTOUCHED pane must still serve
                # fires/snapshots/re-promotion at the new key count —
                # mixed entry sizes would break the pane combine
                for p in list(self._vmirror):
                    self._vmirror_pane(p)
                self._vmirror_update(slots, panes, values)
            return

        self._try_native_mirror()
        sync = self._resolve_device_sync()
        if self._degraded:
            # quarantined HOST tier: the mirror is authoritative anyway —
            # skip the replica dispatch (deferred-sync semantics) until
            # re-promotion
            sync = "deferred"
        if self._fused_depth(sync) > 1:
            # one-dispatch fused megastep: park the batch; the whole
            # super-batch advances in ONE pass at the flush boundary
            # (depth/row bound here, fire boundary or any state read via
            # flush_pipeline)
            from flink_tpu.operators.fused_step import MAX_STAGED_ROWS
            self._fused_stage.push(keys, panes, values, B)
            self._fused_counters["staged_batches"] += 1
            if (len(self._fused_stage) >= self._fused_resolved
                    or self._fused_stage.rows >= MAX_STAGED_ROWS):
                self._fused_flush()
            return
        if self._devprobe_active(sync):
            # device-resident key probe: warm keys resolve INSIDE the
            # dispatched step, the host pass touches only misses
            return self._hot_stage_devprobe(keys, panes, values, B, sync)
        self._hot_stage_fold(keys, panes, values, B, sync)

    def _hot_stage_fold(self, keys: np.ndarray, panes: np.ndarray, values,
                        B: int, sync: str, super_pass: bool = False) -> None:
        """The fold half of the hot stage (probe/mirror pass, paging,
        device dispatch) for one batch — a micro-batch on the unfused
        path, a whole concatenated super-batch from ``_fused_flush``: the
        SAME code folding the SAME records in the SAME order either way,
        so fire digests, snapshots, and counters cannot diverge between
        the fused and unfused lanes."""
        staging = None
        flat_ready = False
        # flatten the value tree ONCE per batch: staging acquisition and
        # the padded fill both consume (leaves, treedef)
        val_leaves = None
        val_treedef = None

        def flat_values():
            nonlocal val_leaves, val_treedef
            if val_leaves is None:
                val_leaves = [np.asarray(a) for a in
                              jax.tree_util.tree_leaves(values)]
                val_treedef = jax.tree_util.tree_structure(values)
            return val_leaves, val_treedef
        if self._nm is not None:
            # fused C pass: key probe + mirror write-through + device scatter
            # ids (the triples are computed once and consumed twice —
            # VERDICT r3 next #1b), sharded across the native worker pool
            # when native_shards > 1.  Deferred sync needs no scatter ids.
            # Super-batches re-measure the shard verdict at their own size
            # (thread-pool wake amortizes over N× the rows).
            with self._phase("probe_mirror"):
                lifted = [np.asarray(l) for l in jax.tree_util.tree_leaves(
                    self.agg.host_lift(values))]
                if super_pass:
                    nshards, shard_div, shard_ns = \
                        self._fused_super_shards()
                else:
                    nshards, shard_div, shard_ns = self._probe_shards()
                if sync == "deferred":
                    slots = self._nm.probe_update(keys, panes, lifted,
                                                  shards=nshards,
                                                  shard_div=shard_div,
                                                  shard_ns=shard_ns)
                else:
                    # the C pass writes flat ids + padding tail straight
                    # into the reusable staging buffer — dispatch-ready
                    lv, td = flat_values()
                    staging = self._staging_acquire(_next_pow2(B, 64),
                                                    lv, td)
                    slots = self._nm.probe_update(
                        keys, panes, lifted, pane_mod=self._P,
                        flat_out=staging.flat, flat_fill=int(_PAD_ID),
                        shards=nshards, shard_div=shard_div,
                        shard_ns=shard_ns)
                    flat_ready = True
                self._record_shard_ns("probe_mirror", shard_ns)
        else:
            with self._phase("probe"):
                slots = self.key_index.lookup_or_insert(keys)
        if self._pager is None and self.key_index.num_keys > self._K:
            self._ensure_alloc()
            self._grow_keys(self.key_index.num_keys)

        self._ensure_alloc()
        gids = slots   # pre-paging GLOBAL ids: the quarantine-migration
        #                fold must be gid-indexed, not HBM-row-indexed
        if self._pager is not None:
            # translate global key ids -> resident HBM rows, paging cold
            # keys out / promoted keys in (batched device dispatches).
            # Pipelined or not, the pager sees this batch's slots BEFORE
            # any later batch can influence eviction decisions: stages are
            # strictly ordered on the single pipeline worker.
            with self._phase("paging"):
                slots = self._page_slots(slots)
        if sync == "deferred":
            # taxed transport: skip the per-batch dispatch; the mirror (the
            # authoritative copy in this mode) absorbs the batch above and
            # the device replica catches up at the next device_refresh()
            self._device_stale = True
        else:
            # ---- pad to pow2 batch size into REUSED staging buffers
            # (static shapes; pads dropped via the out-of-range _PAD_ID)
            lv, td = flat_values()
            if staging is None:
                staging = self._staging_acquire(_next_pow2(B, 64), lv, td)
            flat_p = staging.flat
            if not flat_ready:
                flat_p[:B] = slots.astype(np.int64) * self._P \
                    + (panes % self._P)
                flat_p[B:] = _PAD_ID
            values_p = staging.fill_values(lv, B)

            # np (not device) ids: the jit converts at dispatch, and the mesh
            # subclass re-routes them through the all_to_all exchange
            # host-side
            t_cal = time.perf_counter() if sync == "calibrating" else 0.0
            mb = (flat_p.nbytes + sum(a.nbytes for a in
                                      jax.tree_util.tree_leaves(values_p)))
            try:
                with self._phase("device_dispatch"):
                    with _device_trace():
                        res = self._guarded_update(flat_p, values_p,
                                                   mb / 1e6)
            except DeviceQuarantinedError as err:
                # the device tier wedged mid-batch: migrate to the host
                # tier and fold THIS batch there — no record is dropped
                self._enter_degraded(err)
                with self._phase("mirror"):
                    if self.emit_tier == "host":
                        if self._nm is None:  # nm already folded in probe
                            self._vmirror_update(slots, panes, values)
                    else:
                        self._vmirror_update(gids, panes, values)
                return
            if len(res) == 3:
                # the staging set frees once this execution's token is ready
                self._leaves, self._counts, staging.token = res
            else:
                # subclass override without a completion token (mesh): gate
                # reuse on the counts array itself — donated next step, so
                # ready() only passes when the execution provably finished
                self._leaves, self._counts = res
                staging.token = self._counts
            self.phase_bytes["h2d"] = self.phase_bytes.get("h2d", 0) + mb
            if sync == "calibrating":
                # self-calibration: dispatch-call PLUS until-ready wall of
                # this REAL step is the honest replica-sync cost — backends
                # whose dispatch is synchronous (CPU) pay inside the call,
                # async transports pay in the wait; measuring only the wait
                # would read a synchronous backend as free.  Compile/queue
                # noise is filtered by transport.py taking the min across
                # samples.
                from flink_tpu.utils import transport
                jax.block_until_ready(self._counts)
                transport.record_dispatch_cost(mb / 1e6,
                                               time.perf_counter() - t_cal)

        # host emit mirror: record which (key, pane) cells this batch filled
        # (unsharded device tier; the host tier's value mirror carries exact
        # counts, subsuming the boolean mirror; sharded fires read the
        # device mask instead)
        if self.emit_tier == "host":
            if self._nm is None:  # native path already folded in probe_mirror
                with self._phase("mirror"):
                    self._vmirror_update(slots, panes, values)
        elif self.sharding is None or self._pager is not None:
            # paged mesh state keeps the emit mirror too: the gather fire
            # and spilled-key fire both index it (gid-invariant host state)
            uniq_panes = np.unique(panes)
            if uniq_panes.size == 1:
                self._mirror_mark(int(uniq_panes[0]), slots)
            else:
                for p in uniq_panes.tolist():
                    self._mirror_mark(int(p), slots[panes == p])

    # ------------------------------------------- device-lane health (tiers)
    def _grow_panes_guarded(self, span: int) -> None:
        """Ring growth, degraded-aware: a quarantined DEVICE-tier operator
        has no device ring (state lives in the host value mirror, keyed by
        pane ID — no slot remap exists to run), so only ``_P`` advances;
        re-promotion allocates at the final geometry."""
        if self._degraded and self.emit_tier != "host":
            while self._P < span:
                self._P <<= 1
            return
        if self._delta_counts is not None and span > self._P:
            # the delta ring reallocates with P: drain it into the mirror
            # first (no warm contribution may be lost), rebuild at new P
            self._devprobe_sync_mirror(None)
            self._drop_delta()
        self._ensure_alloc()
        self._grow_panes(span)

    def _guarded_update(self, flat_p, values_p, mb: float):
        """The jitted update dispatch under the device-health watchdog
        (``runtime/device_health.py``): bounded deadline derived from the
        measured dispatch cost, transient-error retry with backoff, OOM ->
        forced page-out through the DevicePager, wedge -> process-wide
        quarantine (the caller migrates tiers).  Retry assumes the failure
        preceded buffer donation — true for the dispatch-level failures
        the monitor models (the chaos point fires before the thunk; real
        XLA dispatch rejections happen before execution consumes donated
        buffers)."""
        from flink_tpu.runtime import device_health
        # geometry change => this dispatch RECOMPILES (the jit keys on
        # K/P/batch shapes): grant the compile grace so state growth on a
        # slow host never reads as a wedge under a tight deadline floor
        leaves = jax.tree_util.tree_leaves(values_p)
        geom = (self._K, self._P, int(flat_p.shape[0]),
                tuple((a.dtype.str, a.shape[1:]) for a in leaves))
        fresh_geom = geom != getattr(self, "_last_dispatch_geom", None)
        self._last_dispatch_geom = geom
        self._hot_dispatches += 1
        return device_health.guarded_dispatch(
            lambda: self._update_step(self._leaves, self._counts, flat_p,
                                      values_p),
            mb=mb,
            on_oom=(self._forced_page_out if self._pager is not None
                    else None),
            label=f"{self.name}.update_step",
            compile_grace=fresh_geom)

    def _enter_degraded(self, err: BaseException) -> None:
        """Quarantine migration: leave the device tier MID-JOB.  Host-tier
        operators just stop dispatching (their mirror is already the
        authority); device-tier operators materialize the live pane ring
        through the dense gid-indexed snapshot path into the host value
        mirror (both pager tiers merged), then drop the device arrays.
        Operators with no host twin tier (no numpy twins, sharded state,
        count triggers) re-raise — the task fails and the normal restart
        strategy recovers it from the last checkpoint instead."""
        if (not self.agg.supports_host_emit()
                or (self.sharding is not None and not self._SHARDED_DEGRADE)
                or self.trigger.fires_on_count
                or isinstance(self.assigner, GlobalWindows)):
            raise err
        self._quarantine_migrations += 1
        if self.emit_tier == "host":
            self._degraded = True
            self._device_stale = True
            return
        n = self.key_index.num_keys if self.key_index is not None else 0
        if self._leaves is not None and self.pane_base is not None and n:
            panes = self._live_panes()

            def _salvage_gather():
                if self._pager is not None:
                    return self._paged_snapshot_rows(n, panes)
                slots = jnp.asarray(panes % self._P, jnp.int32)
                lv = [np.asarray(jnp.take(l, slots, axis=1))[:n]
                      for l in self._leaves]
                return np.asarray(jnp.take(self._counts, slots,
                                           axis=1))[:n], lv

            try:
                # the salvage runs under its own bounded deadline on the
                # monitor's lane: a REALLY wedged device hangs the read
                # too, and the migration must never hang the task thread
                from flink_tpu.runtime import device_health
                mon = device_health.get_monitor(create=False)
                if mon is not None:
                    counts, leaves = mon.run_salvage(
                        _salvage_gather, label=f"{self.name} migration")
                else:
                    counts, leaves = _salvage_gather()
            except Exception as gather_err:  # noqa: BLE001
                # a REAL watchdog timeout abandons the dispatch mid-flight
                # with the state buffers already DONATED into it, or the
                # wedged device cannot serve the download within the
                # salvage deadline: the resident state is genuinely
                # unrecoverable in-process — fail the task so the restart
                # strategy recovers from the last checkpoint instead of
                # silently losing panes (or hanging forever)
                raise err from gather_err
            self._degraded = True   # _vmirror_pane sizes past K_cap now
            self._vmirror = {}
            for j, p in enumerate(panes.tolist()):
                if not counts[:, j].any():
                    continue
                entry = self._vmirror_pane(int(p))
                entry[0][:n] = counts[:, j]
                for k, src in enumerate(leaves):
                    entry[k + 1][:n] = src[:, j].astype(
                        self._mirror_dtypes[k])
        self._degraded = True
        self._drop_device_arrays()

    def _drop_device_arrays(self) -> None:
        """Tear down the device tier's in-process state (the mirror stays
        authoritative).  Shared by the quarantine migration and the
        false-heal rollback — one copy of the teardown set."""
        with self._tier_lock:
            self._tier_epoch += 1   # fence any in-flight promotion
        self._leaves = None
        self._counts = None
        self._staging_pool = {}
        self._mirror = {}
        self._active_rows = None
        if self._pager is not None:
            self._pager.reset()

    def _forced_page_out(self) -> None:
        """Device-OOM pressure valve (monitor ``on_oom`` hook): spill the
        cold half of the resident rows so the retried dispatch has HBM
        headroom.  The current batch's rows stay protected — the in-flight
        flat scatter ids already reference them."""
        pager = self._pager
        if pager is None or self.pane_base is None:
            return
        rows, _gids = pager.resident_pairs()
        protected = getattr(self, "_active_rows", None)
        if protected is None:
            protected = np.empty(0, np.int64)
        evictable = int(rows.size) - int(protected.size)
        k = max(1, evictable // 2) if evictable > 0 else 0
        if k <= 0:
            return
        live = self._live_panes()
        victims = pager.pick_victims(k, protected)
        if victims.size == 0:
            return
        counts, leaves = self._gather_rows(victims, live)
        bits = self._mirror_bits_rows(victims, live)
        pager.spill_rows(victims, live, counts, leaves, bits)
        self._clear_mirror_rows(victims)

    def _maybe_repromote(self) -> bool:
        """Checkpoint-aligned safe point: if the process-wide monitor
        healed the device tier, re-promote this operator's state and leave
        degraded mode.  Returns True when a re-promotion happened."""
        if not self._degraded:
            return False
        from flink_tpu.runtime import device_health
        mon = device_health.get_monitor(create=False)
        if mon is None or not mon.healthy:
            return False
        self.flush_pipeline()

        def _promote():
            if self.emit_tier == "host":
                self._degraded = False   # device_refresh no-ops while degraded
                try:
                    self.device_refresh()  # stale replica: rebuild from mirror
                except BaseException:
                    self._degraded = True
                    raise
            else:
                self._repromote_device()   # device uploads only, no commits

        try:
            # GUARDED (with compile grace — the restore-path kernels
            # compile here): the healer probes in a throwaway subprocess,
            # i.e. a fresh client, which can read healthy while THIS
            # process's wedged grant still hangs every dispatch — a false
            # heal must not hang the task thread mid-re-promotion
            mon.run_guarded(_promote, label=f"{self.name} re-promotion",
                            compile_grace=True)
        except DeviceQuarantinedError:
            # false heal: stay on the host tier (the mirror — dropped
            # only after a COMMITTED promotion — is still the authority);
            # the teardown bumps the tier epoch, fencing the abandoned
            # attempt out of ever committing
            self._degraded = True
            self._device_stale = True
            if self.emit_tier != "host":
                self._drop_device_arrays()
            else:
                with self._tier_lock:
                    self._tier_epoch += 1
            return False
        if self.emit_tier != "host":
            # COMMIT on the TASK thread, after the guarded upload
            # returned: an abandoned (hung) promotion attempt can never
            # flip the tier or drop the mirror behind our back
            self._degraded = False
            self._vmirror = {}
            self._device_stale = False
        self._repromotions += 1
        return True

    def _repromote_device(self) -> None:
        """Quarantine exit for the device tier, UPLOAD HALF: rebuild the
        device pane ring (and pager residency) from the host value mirror
        through the restore path.  Deliberately commits NO tier flags and
        keeps ``_vmirror`` — the caller (``_maybe_repromote``) commits on
        the task thread only after this guarded upload returned, and the
        device-state writes are FENCED on the tier epoch captured at
        entry: an abandoned attempt that later limps to completion finds
        the epoch advanced (by the false-heal rollback or a re-degrade)
        and aborts instead of landing stale state."""
        n = self.key_index.num_keys if self.key_index is not None else 0
        if n == 0 or self.pane_base is None:
            return
        with self._tier_lock:
            epoch = self._tier_epoch
        panes = self._live_panes()
        counts, leaves = self._mirror_columns(panes.tolist(), n)
        counts = np.asarray(counts)
        if self._pager is not None:
            with self._tier_lock:
                if epoch != self._tier_epoch:
                    raise DeviceQuarantinedError("re-promotion superseded")
                self._paged_restore_rows(n, panes, counts, leaves)
        else:
            slots = jnp.asarray(panes % self._P, jnp.int32)
            with self._tier_lock:
                if epoch != self._tier_epoch:
                    raise DeviceQuarantinedError("re-promotion superseded")
                self._K = self._round_key_capacity(max(n, 1))
                self._ensure_alloc()
                self._leaves = tuple(
                    l.at[:n, slots].set(jnp.asarray(s))
                    for l, s in zip(self._leaves, leaves))
                self._counts = self._counts.at[:n, slots].set(
                    jnp.asarray(counts))
                self._mirror = {}
                for j, p in enumerate(panes.tolist()):
                    nz = np.flatnonzero(counts[:, j] > 0)
                    if nz.size:
                        self._mirror_mark(int(p), nz)

    def device_health_stats(self) -> Dict[str, int]:
        """Per-operator tier-degradation counters (monitoring-grade, no
        pipeline barrier — same contract as ``paging_stats``)."""
        return {"degraded": int(self._degraded),
                "quarantine_migrations": self._quarantine_migrations,
                "repromotions": self._repromotions}

    # ------------------------------------------------------------------ time
    def _fired_horizon(self, now: int) -> int:
        """Largest window id whose maxTimestamp (= end-1) has been passed —
        the EventTimeTrigger fire condition.  Pure assigner math (no state
        reads), so the pipelined watermark fast-path may call it while hot
        stages are still in flight."""
        a = self.assigner
        denom = a.pane_stride * a.pane_ms
        w_max = (now + 1 - a._offset - a.panes_per_window * a.pane_ms) // denom
        while a.window_bounds(w_max + 1).max_timestamp <= now:
            w_max += 1
        while a.window_bounds(w_max).max_timestamp > now:
            w_max -= 1
        return w_max

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        self.watermark = max(self.watermark, watermark.timestamp)
        if ((self._pipe_pending() or self._fused_pending())
                and not self.async_fire
                and self.lateness == 0
                and self.trigger.fires_on_time and self.assigner.is_event_time
                and not isinstance(self.assigner, GlobalWindows)
                and self.last_fired_window is not None
                and self._fired_horizon(self.watermark)
                <= self.last_fired_window):
            # pipelined fast path: the watermark passed no new window end,
            # and with lateness 0 pane expiry coincides with fires — so
            # nothing fires, nothing expires, no state is read, and the
            # in-flight hot stages STAY in flight.  This is where the
            # pipeline's overlap comes from on per-batch-watermark drivers.
            return []
        self.flush_pipeline()
        if not (self.trigger.fires_on_time and self.assigner.is_event_time):
            # count triggers don't FIRE on time, but window state still
            # retires at window end + lateness (the reference registers
            # cleanup timers regardless of the trigger) — otherwise the
            # pane ring grows without bound
            if (self.trigger.fires_on_count
                    and not isinstance(self.assigner, GlobalWindows)
                    and self._leaves is not None
                    and self.pane_base is not None):
                self._expire_panes(self.watermark)
            return []
        return self._advance_time(self.watermark)

    def on_processing_time(self, timestamp_ms: int) -> List[StreamElement]:
        self._proc_time = max(self._proc_time, timestamp_ms)
        if self.assigner.is_event_time or not self.trigger.fires_on_time:
            return []
        return self._advance_time(self._proc_time)

    def end_input(self) -> List[StreamElement]:
        """Bounded input: fire everything outstanding (MAX_WATERMARK analog).

        GlobalWindows: EventTimeTrigger fires at MAX_WATERMARK (GlobalWindow
        maxTimestamp == Long.MAX_VALUE); NeverTrigger and partial count
        windows emit nothing — matching the reference, where a trailing
        partial countWindow is dropped at end of input."""
        if isinstance(self.assigner, GlobalWindows):
            self.flush_pipeline()
            pending = self.drain_pending_fires() if self.async_fire else []
            if self.trigger.fires_on_time:
                return pending + self._fire_by_count(force=True)
            return pending
        out = self._advance_time(2 ** 62)
        # a 2^62 watermark can START async fires in the same call: drain them
        if self.async_fire:
            out.extend(self.drain_pending_fires(force=True))
        return out

    def _now_ms(self) -> int:
        from flink_tpu.utils import clock

        return clock.now_ms()

    def _advance_time(self, now: int) -> List[StreamElement]:
        self.flush_pipeline()  # fires/expiry below read state
        # async fires from earlier calls surface before any new ones
        _pending = self.drain_pending_fires() if self.async_fire else []
        if self.pane_base is None or (self._leaves is None
                                      and not self._degraded):
            return _pending
        a = self.assigner
        if isinstance(a, GlobalWindows):  # no time-bounded panes to fire
            return _pending
        out: List[StreamElement] = list(_pending)
        # largest w whose maxTimestamp (= end-1) has been passed — the fire
        # condition of EventTimeTrigger: watermark >= window.maxTimestamp
        w_max = self._fired_horizon(now)
        # bound firing to windows that can contain data ([pane_base, max_pane])
        lo_window = a.windows_of_pane(self.pane_base)[0]
        hi_window = a.windows_of_pane(self.max_pane)[1]
        start = (self.last_fired_window + 1 if self.last_fired_window is not None
                 else lo_window)
        start = max(start, lo_window)
        for w in range(start, min(w_max, hi_window) + 1):
            out.extend(self._fire_window(w))
        if self.last_fired_window is None or w_max > self.last_fired_window:
            self.last_fired_window = w_max
        # ---- retention: clear panes whose last window end + lateness passed
        self._expire_panes(now)
        return out

    def _expire_panes(self, now: int):
        if self.pane_base is None:
            return
        # cleanup time = window.maxTimestamp + allowedLateness (reference:
        # WindowOperator.cleanupTime); a pane expires once its LAST covering
        # window's cleanup time has been passed by the watermark.
        expired = []
        p = self.pane_base
        while (p <= self.max_pane
               and self.assigner.last_window_end_of_pane(p) - 1 + self.lateness <= now):
            expired.append(p)
            p += 1
        if not expired:
            return
        self.pane_base = p
        if self.device_sync_mode == "deferred" or self._degraded \
                or self._leaves is None:
            # no in-line device writes while deferred/degraded: the next
            # device_refresh / re-promotion rebuilds the whole ring
            # (identity for slots without a live pane), subsuming this
            # clear
            self._device_stale = True
        else:
            slots = jnp.asarray(np.asarray(expired, np.int64) % self._P,
                                jnp.int32)
            self._leaves, self._counts = self._clear_panes_step(
                self._leaves, self._counts, slots)
        for ep in expired:
            self._mirror.pop(ep, None)
            self._vmirror.pop(ep, None)
            if self._nm is not None:
                self._nm.drop_pane(ep)
        if self._delta_counts is not None and not self._degraded:
            # expired panes' unsynced delta is DISCARDED, exactly like the
            # mirror pane it would have folded into (reset, or a later
            # sync of the reused ring slot would resurrect dead data)
            dead = [p for p in expired if p in self._delta_panes]
            if dead:
                m = len(dead)
                mp2 = _next_pow2(m, 1)
                slots_np = np.full(mp2, self._P, np.int32)
                slots_np[:m] = np.asarray(dead, np.int64) % self._P
                with _x64():
                    self._delta_leaves, self._delta_counts = \
                        self._delta_clear_step(self._delta_leaves,
                                               self._delta_counts,
                                               jnp.asarray(slots_np))
                self._delta_panes.difference_update(dead)
        if self._pager is not None and not self._degraded:
            self._pager.drop_panes(expired)
        if self.pane_base > self.max_pane:
            self.max_pane = self.pane_base
        if self._count_baselines or self._value_baselines:
            # drop count-trigger registers of windows fully behind retention
            lo_w = self.assigner.windows_of_pane(self.pane_base)[0]
            for w in [w for w in self._count_baselines if w < lo_w]:
                del self._count_baselines[w]
                if self.incremental_state:
                    self._incr_cb_drops.add(w)
            for w in [w for w in self._value_baselines if w < lo_w]:
                del self._value_baselines[w]
                if self.incremental_state:
                    self._incr_vb_drops.add(w)

    # ------------------------------------------------------------------ fires
    def _fire_window(self, window_id: int) -> List[StreamElement]:
        if self._degraded and self.emit_tier != "host":
            # quarantined device tier: serve the fire from the host value
            # mirror (zero device ops), the same pane combine the host
            # emit tier runs
            if self.pane_base is None:
                return []
            first, last = self.assigner.window_panes(window_id)
            if last < self.pane_base or first > self.max_pane:
                return []
            panes = np.arange(max(first, self.pane_base),
                              min(last, self.max_pane) + 1, dtype=np.int64)
            with self._phase("fire"):
                return self._fire_window_host(window_id, panes)
        if self._leaves is None:
            return []
        first, last = self.assigner.window_panes(window_id)
        # skip windows entirely outside retained panes
        if last < self.pane_base or first > self.max_pane:
            return []
        # mirror-indexed fires serve unsharded state AND sharded state whose
        # host-side mirrors are maintained (mesh host tier: the value mirror
        # is gid-indexed and mesh-size independent; mesh paged state: the
        # emit mirror + spill maps drive the gather/spilled fire)
        mirror_fire = self.key_index is not None and (
            self.sharding is None or self.emit_tier == "host"
            or self._pager is not None)
        if mirror_fire:
            # clip to retained panes: expired slots are identity on device,
            # and the mirror only tracks live panes anyway
            panes = np.arange(max(first, self.pane_base),
                              min(last, self.max_pane) + 1, dtype=np.int64)
            if self.emit_tier == "host":
                with self._phase("fire"):
                    return self._fire_window_host(window_id, panes)
            with self._phase("fire"):
                out = self._fire_window_gather(window_id, panes)
                if self._pager is not None:
                    # spilled keys are first-class in fires: their cells
                    # upload and run the same pane combine
                    out = out + self._fire_window_spilled(window_id, panes)
                return out
        panes = np.arange(first, last + 1, dtype=np.int64)
        pane_slots = jnp.asarray(panes % self._P, jnp.int32)
        mask, result = self._fire_step(self._leaves, self._counts, pane_slots,
                                       self._k_active())
        return self._emit(mask, result, self.assigner.window_bounds(window_id))

    def _fire_by_count(self, force: bool = False) -> List[StreamElement]:
        if self._leaves is None:
            return []
        thr = 1 if force else self.trigger.count_threshold
        ka = self._k_active() or self._K
        counts0 = self._counts[:ka, 0]
        base = None
        if not force and not self.trigger.purges_on_fire:
            # FIRE-only trigger: state persists, so "n more elements" is
            # tracked by a baseline of already-fired counts per key
            counts_np = np.asarray(counts0, np.int64)
            base = self._count_baselines.get(0)
            if base is None or len(base) < ka:
                grown = np.zeros(ka, np.int64)
                if base is not None:
                    grown[:len(base)] = base
                base = grown
                self._count_baselines[0] = base
                if self.incremental_state:
                    # creation counts: a full snapshot packs the register
                    # even before its first fire
                    self._incr_cb_dirty.add(0)
            mask = jnp.asarray((counts_np - base[:ka]) >= thr)
        else:
            mask = counts0 >= thr
        if not bool(mask.any()):  # cheap pre-check: skip the K-wide assembly
            return []
        pane_slots = jnp.zeros((1,), jnp.int32)
        m, result = self._fire_step(self._leaves, self._counts, pane_slots,
                                    self._k_active())
        mask = mask & m
        out = self._emit(mask, result, self.assigner.window_bounds(0))
        if base is not None:
            fired = np.asarray(mask)
            base[:ka] = np.where(fired, np.asarray(counts0, np.int64),
                                 base[:ka])
            if self.incremental_state:
                self._incr_cb_dirty.add(0)
        if self.trigger.purges_on_fire and out:
            full_mask = jnp.zeros((self._K,), bool).at[:ka].set(mask)
            self._leaves, self._counts = self._purge_keys_step(
                self._leaves, self._counts, full_mask)
            fired_np = np.asarray(mask)
            for arr in self._mirror.values():  # whole key rows were purged
                arr[: fired_np.size][fired_np] = False
            if self.incremental_state:
                # purged rows are identity in EVERY retained pane now
                self._incr_mark_gids(np.flatnonzero(fired_np),
                                     self._live_panes())
        return out

    def _fire_count_in_panes(self, touched_panes) -> List[StreamElement]:
        """CountTrigger.onElement FIRE for time windows (tumbling: one pane
        per window): per touched pane, emit keys at/over the threshold, then
        purge those cells when the trigger purges."""
        if self.assigner.panes_per_window != 1 \
                or not self.trigger.purges_on_fire:
            # multi-pane windows and non-purging triggers both track fires
            # via per-(key, window) baselines instead of purging cells
            return self._fire_count_sliding(touched_panes)
        out: List[StreamElement] = []
        thr = self.trigger.count_threshold
        ka = self._k_active() or self._K
        for p in np.asarray(touched_panes).tolist():
            slot = int(p) % self._P
            counts_col = np.asarray(self._counts[:ka, slot])
            over = counts_col >= thr
            if not over.any():
                continue
            pane_slots = jnp.asarray([slot], jnp.int32)
            m, result = self._fire_step(self._leaves, self._counts,
                                        pane_slots, self._k_active())
            mask = jnp.asarray(over) & m
            window = self.assigner.window_bounds(
                self.assigner.windows_of_pane(int(p))[0])
            out.extend(self._emit(mask, result, window))
            if self.trigger.purges_on_fire:
                full = jnp.zeros((self._K,), bool).at[:ka].set(mask)
                self._leaves, self._counts = self._purge_cells_step(
                    self._leaves, self._counts, full, pane_slots)
                fired_np = np.asarray(mask)
                marr = self._mirror.get(int(p))
                if marr is not None:
                    marr[: fired_np.size][fired_np] = False
                if self.incremental_state:
                    self._incr_mark_gids(np.flatnonzero(fired_np), [int(p)])
        return out

    def _fire_count_sliding(self, touched_panes) -> List[StreamElement]:
        """CountTrigger.onElement FIRE for SLIDING (multi-pane) windows: a
        (key, window) fires when the sum of the window's pane counts has
        grown by >= n since its last fire.  The per-window baseline is the
        CountTrigger count register (``ReducingState<Long>`` per (key,
        window) namespace in the reference) — it clears on FIRE.

        FIRE_AND_PURGE: overlapping windows share panes, so the purge is
        LOGICAL — a per-(key, window) VALUE baseline of the fired
        accumulator is kept, and emissions subtract it (invertible
        aggregates only, enforced at construction).  The emitted rows are
        exactly what the reference's per-namespace purged state would
        produce, without touching the shared pane cells."""
        out: List[StreamElement] = []
        thr = self.trigger.count_threshold
        purging = self.trigger.purges_on_fire
        ka = self._k_active() or self._K
        wins: set = set()
        for p in np.asarray(touched_panes).tolist():
            w0, w1 = self.assigner.windows_of_pane(int(p))
            wins.update(range(w0, w1 + 1))
        for w in sorted(wins):
            first, last = self.assigner.window_panes(w)
            lo, hi = max(first, self.pane_base), min(last, self.max_pane)
            if lo > hi:
                continue
            panes = np.arange(lo, hi + 1, dtype=np.int64)
            slots = jnp.asarray(panes % self._P, jnp.int32)
            counts_w = np.asarray(
                jnp.take(self._counts[:ka], slots, axis=1).sum(axis=1),
                dtype=np.int64)
            base = self._count_baselines.get(w)
            if base is None or len(base) < ka:
                grown = np.zeros(ka, np.int64)
                if base is not None:
                    grown[:len(base)] = base
                base = grown
            over = (counts_w - base[:ka]) >= thr
            if over.any():
                if purging:
                    out.extend(self._emit_purging_sliding(w, slots, ka,
                                                          over))
                else:
                    m, result = self._fire_step(self._leaves, self._counts,
                                                slots, self._k_active())
                    mask = jnp.asarray(over) & m
                    out.extend(self._emit(mask, result,
                                          self.assigner.window_bounds(w)))
                base[:ka] = np.where(over, counts_w, base[:ka])
            self._count_baselines[w] = base
            if self.incremental_state:
                # the register exists (zero-grown included) — a full
                # snapshot would pack it, so the delta must ship it too
                self._incr_cb_dirty.add(w)
        return out

    def _emit_purging_sliding(self, w: int, slots, ka: int,
                              over: np.ndarray) -> List[StreamElement]:
        """One FIRE_AND_PURGE emission for sliding window ``w``: download
        the combined accumulator, subtract the value baseline (= contents
        already fired-and-purged), emit, advance the baseline for fired
        keys."""
        _m, combined = self._fire_acc_step(self._leaves, self._counts,
                                           slots, self._k_active())
        comb_np = [np.asarray(l) for l in combined]
        self.phase_bytes["d2h"] = self.phase_bytes.get("d2h", 0) + \
            sum(l.nbytes for l in comb_np)
        vb = self._value_baselines.get(w)
        if vb is None or vb[0].shape[0] < ka:
            grown = [np.zeros_like(c) for c in comb_np]
            if vb is not None:
                for g, o in zip(grown, vb):
                    g[:o.shape[0]] = o
            vb = grown
        emit_leaves = tuple(c - b[:ka] for c, b in zip(comb_np, vb))
        result = self.agg.get_result(self.spec.unflatten(emit_leaves))
        out = self._emit(np.asarray(over),
                         result, self.assigner.window_bounds(w))
        for b, c in zip(vb, comb_np):
            sel = over.reshape((-1,) + (1,) * (b.ndim - 1))
            b[:ka] = np.where(sel, c, b[:ka])
        self._value_baselines[w] = vb
        if self.incremental_state:
            self._incr_vb_dirty.add(w)
        return out

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2))
    def _purge_cells_step(self, leaves, counts, key_mask, pane_slots):
        """Reset (key, pane) cells for fired count-trigger windows."""
        new_leaves = []
        for l, init in zip(leaves, self.spec.leaf_inits):
            sel = jnp.take(l, pane_slots, axis=1)
            fill = jnp.broadcast_to(jnp.asarray(init, l.dtype), sel.shape)
            m = key_mask.reshape((-1, 1) + (1,) * (l.ndim - 2))
            new_leaves.append(l.at[:, pane_slots].set(jnp.where(m, fill, sel)))
        csel = jnp.take(counts, pane_slots, axis=1)
        new_counts = counts.at[:, pane_slots].set(
            jnp.where(key_mask[:, None], 0, csel))
        return tuple(new_leaves), new_counts

    def _emit(self, mask, result, window) -> List[StreamElement]:
        mask_np = np.asarray(mask[: self.key_index.num_keys]) if self.key_index else np.asarray(mask)
        idx = np.nonzero(mask_np)[0]
        if idx.size == 0:
            return []
        res_np = jax.tree_util.tree_map(lambda a: np.asarray(a)[idx], result)
        self.phase_bytes["d2h"] = self.phase_bytes.get("d2h", 0) + \
            mask_np.nbytes + sum(a.nbytes for a in
                                 jax.tree_util.tree_leaves(result))
        return self._rows_for(idx, res_np, window)

    # ------------------------------------------------------------- paging
    def _live_panes(self) -> np.ndarray:
        return np.arange(self.pane_base, self.max_pane + 1, dtype=np.int64)

    def _page_slots(self, gids: np.ndarray) -> np.ndarray:
        """Map global key ids to resident HBM rows, evicting cold keys and
        promoting/initializing missing ones.  Batched: at most one page-out
        gather and one page-in scatter per micro-batch."""
        pager = self._pager
        pager.ensure_gids(self.key_index.num_keys)
        uniq = np.unique(gids)
        rows_u = pager.rows(uniq)
        missing = uniq[rows_u < 0]
        if missing.size:
            live = self._live_panes()
            n_evict = int(missing.size) - pager.free_count()
            if n_evict > 0:
                victims = pager.pick_victims(n_evict, rows_u[rows_u >= 0])
                counts, leaves = self._gather_rows(victims, live)
                bits = self._mirror_bits_rows(victims, live)
                pager.spill_rows(victims, live, counts, leaves, bits)
                self._clear_mirror_rows(victims)
            rows_new, recycled = pager.assign_rows(missing)
            if pager.any_spilled(missing, live):
                counts_cols, leaf_cols, bits, _found = pager.load_entries(
                    missing, live, delete=True)
                self._page_in(rows_new, live, counts_cols, leaf_cols)
                for j, p in enumerate(live.tolist()):
                    hit = bits[:, j]
                    if hit.any():
                        self._mirror_mark(int(p), rows_new[hit])
            elif recycled:
                # recycled rows carry the previous tenant's stale cells:
                # reset them even when nothing was promoted from spill
                self._reset_rows(rows_new)
        rows = pager.rows(gids)
        active = pager.rows(uniq)
        pager.touch(active)
        # rows referenced by the in-flight dispatch: protected from the
        # OOM forced page-out (their flat scatter ids are already built)
        self._active_rows = active
        return rows

    @partial(jax.jit, static_argnums=(0,))
    def _gather_rows_step(self, leaves, counts, rows, pane_slots):
        return gather_row_pane_columns(leaves, counts, rows, pane_slots)

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2))
    def _page_in_step(self, leaves, counts, rows, pane_slots,
                      counts_cols, leaf_cols):
        return set_row_pane_columns(leaves, counts, rows, pane_slots,
                                    leaf_cols, counts_cols,
                                    self.spec.leaf_inits)

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2))
    def _reset_rows_step(self, leaves, counts, rows):
        return reset_rows(leaves, counts, rows, self.spec.leaf_inits)

    def _gather_rows(self, rows: np.ndarray, panes: np.ndarray):
        """Download the ``rows x panes`` cell grid (page-out / snapshot):
        (counts [V, m] np, leaves [V, m, *leaf] np)."""
        V, m = int(rows.size), int(panes.size)
        Vp = _next_pow2(V, 64)
        mp = _next_pow2(m, 1)
        rows_p = np.zeros(Vp, np.int32)
        rows_p[:V] = rows
        slots_p = np.zeros(mp, np.int32)
        slots_p[:m] = panes % self._P
        c, ls = self._gather_rows_step(self._leaves, self._counts,
                                       jnp.asarray(rows_p),
                                       jnp.asarray(slots_p))
        counts = np.asarray(c)[:V, :m]
        leaves = [np.asarray(l)[:V, :m] for l in ls]
        self.phase_bytes["d2h_page_out"] = \
            self.phase_bytes.get("d2h_page_out", 0) + counts.nbytes + \
            sum(l.nbytes for l in leaves)
        return counts, leaves

    def _page_in(self, rows: np.ndarray, panes: np.ndarray,
                 counts_cols: np.ndarray, leaf_cols) -> None:
        """Upload promoted cells into freshly assigned rows (whole rows
        reset first — recycled rows carry the previous tenant's cells)."""
        R, m = int(rows.size), int(panes.size)
        Rp = _next_pow2(R, 64)
        mp = _next_pow2(m, 1)
        rows_p = np.full(Rp, self._K, np.int32)       # pads dropped
        rows_p[:R] = rows
        slots_p = np.full(mp, self._P, np.int32)      # pads dropped
        slots_p[:m] = panes % self._P
        cc = np.zeros((Rp, mp), np.int32)
        cc[:R, :m] = counts_cols
        lc = []
        for col, arr in zip(leaf_cols, identity_grid(self.spec, Rp, mp)):
            arr[:R, :m] = col
            lc.append(jnp.asarray(arr))
        self._leaves, self._counts = self._page_in_step(
            self._leaves, self._counts, jnp.asarray(rows_p),
            jnp.asarray(slots_p), jnp.asarray(cc), tuple(lc))
        self.phase_bytes["h2d_page_in"] = \
            self.phase_bytes.get("h2d_page_in", 0) + cc.nbytes + \
            sum(int(l.nbytes) for l in lc)

    def _reset_rows(self, rows: np.ndarray) -> None:
        Rp = _next_pow2(int(rows.size), 64)
        rows_p = np.full(Rp, self._K, np.int32)
        rows_p[: rows.size] = rows
        self._leaves, self._counts = self._reset_rows_step(
            self._leaves, self._counts, jnp.asarray(rows_p))

    def _mirror_bits_rows(self, rows: np.ndarray,
                          panes: np.ndarray) -> np.ndarray:
        """Emit-mirror bits of the ``rows x panes`` grid (spilled alongside
        counts so promotion restores the exact emit set)."""
        out = np.zeros((rows.size, panes.size), bool)
        for j, p in enumerate(panes.tolist()):
            arr = self._mirror.get(int(p))
            if arr is not None:
                out[:, j] = arr[rows]
        return out

    def _clear_mirror_rows(self, rows: np.ndarray) -> None:
        for arr in self._mirror.values():
            arr[rows] = False

    @partial(jax.jit, static_argnums=(0,))
    def _spill_fire_step(self, counts_cols, leaf_cols):
        """Window fire over UPLOADED spilled cells: the same pane combine +
        get_result the resident gather fire runs (same dtypes, same tree
        order over the same unpadded pane axis), so a key's emitted value
        is independent of which tier held it."""
        total = counts_cols.sum(axis=1)
        combined = combine_along_axis(leaf_cols, self.agg.combine_leaves,
                                      axis=1)
        result = self.agg.get_result(self.spec.unflatten(combined))
        return total > 0, result

    def _fire_window_spilled(self, window_id: int,
                             panes: np.ndarray) -> List[StreamElement]:
        """Fire contribution of COLD keys: load their spilled cells for the
        window's panes, upload as dense columns, combine on device,
        download only the emitted results.  Chunked so memory stays bounded
        at any spilled cardinality."""
        pager = self._pager
        gids = pager.spilled_gids(panes)
        if gids.size == 0:
            return []
        out: List[StreamElement] = []
        window = self.assigner.window_bounds(window_id)
        reverse = np.asarray(self.key_index.reverse_keys())
        CH = 1 << 14
        for lo in range(0, int(gids.size), CH):
            g = gids[lo: lo + CH]
            counts, leaves, _bits, _found = pager.load_entries(
                g, panes, delete=False)
            R, m = int(g.size), int(panes.size)
            Rp = _quantize_cap(R)
            cc = np.zeros((Rp, m), np.int32)
            cc[:R] = counts
            lc = []
            for col, arr in zip(leaves, identity_grid(self.spec, Rp, m)):
                arr[:R] = col
                lc.append(jnp.asarray(arr))
            mask, result = self._spill_fire_step(jnp.asarray(cc), tuple(lc))
            mask_np = np.asarray(mask)[:R]
            idx = np.flatnonzero(mask_np)
            if idx.size == 0:
                continue
            res_np = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[:R][idx], result)
            self.phase_bytes["d2h"] = self.phase_bytes.get("d2h", 0) + \
                mask_np.nbytes + sum(a.nbytes for a in
                                     jax.tree_util.tree_leaves(res_np))
            out.extend(self._rows_for_keys(reverse[g[idx]], res_np, window))
        return out

    def paging_stats(self) -> Optional[Dict[str, int]]:
        """Occupancy + eviction/promotion counters, or None when paging is
        off (job-scope ``paging.*`` metrics and bench details read this).

        Monitoring-grade: deliberately NO pipeline barrier — metrics/REST
        pollers call this from foreign threads and must neither block on
        in-flight hot stages nor receive the task's parked stage error.
        Under pipelining the counters may lag by the (bounded) in-flight
        stages; every correctness path (fires, snapshots) barriers."""
        if self._pager is None:
            return None
        n = self.key_index.num_keys if self.key_index is not None else 0
        return self._pager.stats(n)

    # ------------------------------------ incremental (delta) checkpoints
    def _incr_clear(self) -> None:
        """Reset ALL delta tracking: the next cut must be a full re-base
        (restore, reset — any point where the confirmed-base linkage to
        the storage-side increment chain is severed)."""
        self._incr_keychunks: List = []       # live (raw keys, panes) pairs
        self._incr_gid_cells: Dict[int, List[np.ndarray]] = {}  # pane->gids
        self._incr_cb_dirty: set = set()
        self._incr_vb_dirty: set = set()
        self._incr_cb_drops: set = set()
        self._incr_vb_drops: set = set()
        #: cuts taken but not yet confirmed: [(cid, cells, cbd, vbd,
        #: cb_drops, vb_drops, num_keys_at_cut)] — every later cut ships
        #: the UNION of these with the live dirt, so a crash between cut
        #: and confirmation can never lose a mutation
        self._incr_unconfirmed: List = []
        self._incr_last_confirmed: Optional[int] = None
        self._incr_confirmed_n = 0

    def _incr_mark_batch(self, keys: np.ndarray, panes: np.ndarray) -> None:
        self._incr_keychunks.append((np.array(keys, copy=True),
                                     np.array(panes, copy=True)))
        if len(self._incr_keychunks) > 512:
            # bound live memory between cuts: coalesce into the gid map
            self._incr_coalesce_live()

    def _incr_mark_gids(self, gids: np.ndarray, panes) -> None:
        """Product mark: every (gid, pane) cell in gids x panes is dirty."""
        if not self.incremental_state or len(gids) == 0:
            return
        g = np.asarray(gids, np.int64).copy()
        for p in np.asarray(panes).tolist():
            self._incr_gid_cells.setdefault(int(p), []).append(g)

    def _incr_coalesce_live(self) -> None:
        """Resolve live raw-key chunks to gids and fold into the cell map."""
        chunks, self._incr_keychunks = self._incr_keychunks, []
        if self.key_index is None:
            return
        for keys, panes in chunks:
            gids = np.asarray(self.key_index.lookup(keys), np.int64)
            ok = gids >= 0
            if not ok.all():
                gids, panes = gids[ok], panes[ok]
            for p in np.unique(panes).tolist():
                self._incr_gid_cells.setdefault(int(p), []).append(
                    gids[panes == p])

    def _incr_freeze(self, cid: int) -> None:
        """Move the live dirt into the unconfirmed ledger under ``cid``."""
        self._incr_coalesce_live()
        cells = {p: np.unique(lst[0] if len(lst) == 1
                              else np.concatenate(lst))
                 for p, lst in self._incr_gid_cells.items()}
        n = self.key_index.num_keys if self.key_index is not None else 0
        self._incr_unconfirmed.append(
            (cid, cells, self._incr_cb_dirty, self._incr_vb_dirty,
             self._incr_cb_drops, self._incr_vb_drops, n))
        self._incr_gid_cells = {}
        self._incr_cb_dirty, self._incr_vb_dirty = set(), set()
        self._incr_cb_drops, self._incr_vb_drops = set(), set()

    def _incremental_snapshot(self, cid: int):
        """A ``window_delta`` increment covering every mutation since the
        last CONFIRMED checkpoint, or None when this cut must be a full
        re-base (no confirmed base yet, or the grid is too dirty for a
        delta to pay off).  Either way the live dirt is frozen under
        ``cid`` so the NEXT cut keeps covering it until confirmation."""
        self._incr_freeze(cid)
        base_n = self._incr_confirmed_n
        if self._incr_last_confirmed is None or self.key_index is None:
            return None
        n = self.key_index.num_keys
        # union of all unconfirmed dirt (absolute values: last-writer-wins
        # replay makes shipping a superset harmless)
        union: Dict[int, List[np.ndarray]] = {}
        cbd: set = set()
        vbd: set = set()
        cb_drops: set = set()
        vb_drops: set = set()
        for (_c, ecells, ecbd, evbd, ecbdrop, evbdrop, _n) \
                in self._incr_unconfirmed:
            for p, g in ecells.items():
                union.setdefault(int(p), []).append(g)
            cbd |= ecbd
            vbd |= evbd
            cb_drops |= ecbdrop
            vb_drops |= evbdrop
        cells_map: Dict[int, np.ndarray] = {}
        for p, lst in union.items():
            if self.pane_base is not None and \
                    not (self.pane_base <= p <= self.max_pane):
                continue            # pane expired since it was marked
            g = lst[0] if len(lst) == 1 else np.unique(np.concatenate(lst))
            g = np.asarray(g, np.int64)
            g = g[g < n]
            if g.size:
                cells_map[int(p)] = g
        has_grid = (self._leaves is not None or self._degraded) \
            and self.pane_base is not None
        if has_grid:
            m = int(self.max_pane - self.pane_base + 1)
            dirty_cells = sum(int(g.size) for g in cells_map.values())
            if n and m and dirty_cells > self.incr_rebase_ratio * n * m:
                return None          # too dirty: re-base with a full cut
        inc: Dict[str, Any] = {
            "__increment__": 1, "kind": "window_delta",
            "checkpoint_id": cid, "n": n, "base_n": base_n,
            "has_grid": has_grid,
            "meta": {"pane_base": self.pane_base, "max_pane": self.max_pane,
                     "last_fired_window": self.last_fired_window,
                     "watermark": self.watermark,
                     "late_dropped": self.late_dropped, "P": self._P},
            "key_index_kind": type(self.key_index).__name__,
            "key_tail": np.asarray(
                self.key_index.reverse_keys()[base_n:n]).copy(),
        }
        cell_list: List[Dict[str, Any]] = []
        if has_grid and cells_map:
            dirty_panes = sorted(cells_map)
            panes_arr = np.asarray(dirty_panes, np.int64)
            if self.snapshot_source == "mirror" or self._degraded:
                with self._phase("snapshot"):
                    counts, leaves = self._mirror_columns(dirty_panes, n)
                for j, p in enumerate(dirty_panes):
                    g = cells_map[p]
                    cell_list.append(
                        {"pane": p, "gids": g,
                         "counts": counts[g, j].copy(),
                         "leaves": [l[g, j].copy() for l in leaves]})
            elif self._pager is not None:
                with self._phase("snapshot"):
                    counts, leaves = self._paged_snapshot_rows(n, panes_arr)
                for j, p in enumerate(dirty_panes):
                    g = cells_map[p]
                    cell_list.append(
                        {"pane": p, "gids": g,
                         "counts": counts[g, j].copy(),
                         "leaves": [l[g, j].copy() for l in leaves]})
            else:
                # device tier: ONE gather of the dirty-rows x dirty-panes
                # grid — d2h bytes scale with the dirt, not the state
                rows = np.unique(np.concatenate(
                    [cells_map[p] for p in dirty_panes]))
                with self._phase("snapshot"):
                    counts, leaves = self._gather_rows(rows, panes_arr)
                for j, p in enumerate(dirty_panes):
                    g = cells_map[p]
                    idx = np.searchsorted(rows, g)
                    cell_list.append(
                        {"pane": p, "gids": g,
                         "counts": counts[idx, j].copy(),
                         "leaves": [l[idx, j].copy() for l in leaves]})
        inc["cells"] = cell_list
        if has_grid:
            from flink_tpu.state.evolution import acc_leaf_schema
            inc["leaf_meta"] = [
                (np.asarray(init, np.dtype(d)), str(np.dtype(d)),
                 tuple(shape))
                for init, shape, d in zip(self.spec.leaf_inits,
                                          self.spec.leaf_shapes,
                                          self.spec.leaf_dtypes)]
            inc["leaf_schema"] = acc_leaf_schema(self.spec)
        else:
            inc["leaf_meta"] = []
        if self._pager is not None:
            inc["paging_stats"] = self._pager.stats(n)
        cb_vals: Dict[int, np.ndarray] = {}
        for w in cbd:
            b = self._count_baselines.get(w)
            if b is None:
                cb_drops.add(w)
            else:
                cb_vals[w] = np.asarray(b, np.int64).copy()
        vb_vals: Dict[int, List[np.ndarray]] = {}
        for w in vbd:
            ls = self._value_baselines.get(w)
            if ls is None:
                vb_drops.add(w)
            else:
                vb_vals[w] = [np.asarray(l).copy() for l in ls]
        inc["count_baselines"] = cb_vals
        inc["value_baselines"] = vb_vals
        inc["cb_drops"] = sorted(cb_drops)
        inc["vb_drops"] = sorted(vb_drops)
        return inc

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        """Track the last completed checkpoint so queryable live views tag
        the consistency point they reflect (base hook is a no-op)."""
        if self._last_completed_checkpoint is None \
                or checkpoint_id > self._last_completed_checkpoint:
            self._last_completed_checkpoint = checkpoint_id
        # delta tracking: dirt up to a CONFIRMED cut may be forgotten —
        # only for cuts we actually froze (savepoints/finals are not part
        # of the storage-side increment chain and must not advance it)
        match = next((e for e in self._incr_unconfirmed
                      if e[0] == checkpoint_id), None)
        if match is not None:
            self._incr_unconfirmed = [e for e in self._incr_unconfirmed
                                      if e[0] > checkpoint_id]
            self._incr_last_confirmed = checkpoint_id
            self._incr_confirmed_n = match[6]
        super().notify_checkpoint_complete(checkpoint_id)

    def queryable_view(self):
        """The live-read view (``queryable/view.WindowReadView``) when this
        operator was constructed with ``queryable=<name>``, else None.
        Monitoring-grade: reading it takes no barrier."""
        return self._qview

    def close(self) -> None:
        try:
            self.flush_pipeline()
        finally:
            if self._pipe is not None:
                self._pipe.close()
                self._pipe = None
            if self._pager is not None:
                self._pager.close()

    def _paged_snapshot_rows(self, n: int, panes: np.ndarray):
        """Dense gid-indexed snapshot arrays merging both tiers:
        counts int32 [n, m] + one [n, m, *leaf] per ACC leaf."""
        m = int(panes.size)
        counts = np.zeros((n, m), np.int32)
        leaves = identity_grid(self.spec, n, m)
        rows, gids = self._pager.resident_pairs()
        if rows.size:
            res_counts, res_leaves = self._gather_rows(rows, panes)
            counts[gids] = res_counts
            for dst, src in zip(leaves, res_leaves):
                dst[gids] = src
        self._pager.fill_snapshot(counts, leaves, panes)
        return counts, leaves

    def _paged_restore_rows(self, n: int, panes: np.ndarray,
                            counts_np: np.ndarray, leaves_np) -> None:
        """Restore a dense snapshot at THIS operator's K_cap: the first
        ``min(n, K_cap)`` keys become resident (one upload), the overflow
        pages straight into the spill tier — a savepoint written at any
        capacity (paged or fully resident) restores at any other."""
        pager = self._pager
        pager.reset()
        pager.ensure_gids(max(n, 1))
        self._ensure_alloc()
        R = min(n, self._K)
        self._mirror = {}
        if R:
            # fresh rows are 0..R-1 in gid order: upload via a plain
            # slice-set, so resident row i == global id i after restore
            pager.assign_rows(np.arange(R, dtype=np.int64))
            slots = jnp.asarray(panes % self._P, jnp.int32)
            self._leaves = tuple(
                l.at[:R, slots].set(jnp.asarray(s[:R]))
                for l, s in zip(self._leaves, leaves_np))
            self._counts = self._counts.at[:R, slots].set(
                jnp.asarray(counts_np[:R]))
            for j, p in enumerate(panes.tolist()):
                nz = np.flatnonzero(counts_np[:R, j] > 0)
                if nz.size:
                    self._mirror_mark(int(p), nz)
        if n > R:
            pager.import_rows(np.arange(R, n, dtype=np.int64), panes,
                              counts_np, [np.asarray(l) for l in leaves_np])

    # ------------------------------------------------------------- snapshots
    def prepare_snapshot_pre_barrier(self) -> List[StreamElement]:
        """Drain pending async fire downloads so their emissions travel
        downstream BEFORE the barrier — the reference drains its external
        Python runtime the same way
        (``AbstractPythonFunctionOperator.prepareSnapshotPreBarrier:173``).
        After this, ``snapshot_state`` is always legal, async_fire included.

        Also the checkpoint-aligned SAFE POINT for device-lane healing:
        a degraded operator whose monitor probed healthy re-promotes its
        state to the device tier here, so the snapshot that follows is
        already device-sourced and the tier switch is barrier-aligned —
        a watermark or barrier can never observe half-migrated state."""
        self.flush_pipeline()
        self._maybe_repromote()
        if self.async_fire:
            return self.drain_pending_fires(force=True)
        return []

    def snapshot_state(self) -> Dict[str, Any]:
        self.flush_pipeline()  # the snapshot must contain in-flight stages
        if self._pending_fires:
            # the runtime must call prepare_snapshot_pre_barrier first (all
            # in-repo runtimes do); a snapshot with un-drained async fires
            # could neither replay nor contain those emissions — refuse
            raise ValueError(
                "snapshot with in-flight async fires: the runtime must call "
                "prepare_snapshot_pre_barrier() (and forward its elements) "
                "before snapshot_state()")
        cid = current_checkpoint_id()
        if self.incremental_state and cid is not None \
                and snapshot_is_incremental():
            inc = self._incremental_snapshot(cid)
            if inc is not None:
                return inc
            # fall through: full re-base cut (the dirt was still frozen
            # under cid, so confirmation advances the delta base to it)
        snap: Dict[str, Any] = {
            "pane_base": self.pane_base,
            "max_pane": self.max_pane,
            "last_fired_window": self.last_fired_window,
            "watermark": self.watermark,
            "late_dropped": self.late_dropped,
            "P": self._P,
        }
        if self.key_index is not None:
            snap["key_index"] = self.key_index.snapshot()
            snap["key_index_kind"] = type(self.key_index).__name__
        if (self._leaves is not None or self._degraded) \
                and self.pane_base is not None and self.key_index is not None:
            n = self.key_index.num_keys
            panes = np.arange(self.pane_base, self.max_pane + 1, dtype=np.int64)
            snap["panes"] = panes
            if self.snapshot_source == "mirror" or self._degraded:
                # degraded: the host value mirror IS the state — the dense
                # gid-indexed format is identical, so a checkpoint taken
                # DURING quarantine restores on either tier
                # serialize the host mirror (continuously equal to device
                # state, in higher precision) — zero device->host transfer;
                # cast down to the device leaf dtypes so the snapshot format
                # is identical either way
                with self._phase("snapshot"):
                    counts, leaves = self._mirror_columns(panes.tolist(), n)
                    snap["leaves"] = leaves
                    snap["counts"] = counts
            elif self._pager is not None:
                # paged: resident rows download in one gather, spilled rows
                # fill in from the store — the snapshot is the SAME dense
                # gid-indexed format either way, so redistribute/rescale
                # and restore into a non-paged operator work unchanged
                with self._phase("snapshot"):
                    snap["counts"], snap["leaves"] = \
                        self._paged_snapshot_rows(n, panes)
            else:
                # snapshot only live keys × live panes (device→host transfer)
                with self._phase("snapshot"):
                    slots = jnp.asarray(panes % self._P, jnp.int32)
                    snap["leaves"] = [
                        np.asarray(jnp.take(l, slots, axis=1))[:n]
                        for l in self._leaves]
                    snap["counts"] = np.asarray(
                        jnp.take(self._counts, slots, axis=1))[:n]
                self.phase_bytes["d2h"] = self.phase_bytes.get("d2h", 0) + \
                    snap["counts"].nbytes + \
                    sum(l.nbytes for l in snap["leaves"])
            from flink_tpu.state.evolution import acc_leaf_schema
            snap["leaf_schema"] = acc_leaf_schema(self.spec)
        if self._pager is not None:
            snap["paging_stats"] = self._pager.stats(
                self.key_index.num_keys if self.key_index else 0)
        if self._count_baselines:
            n = self.key_index.num_keys if self.key_index else 0
            packed = {}
            for w, b in self._count_baselines.items():
                arr = np.zeros(n, np.int64)  # pad: slot-aligned with leaves
                arr[:min(len(b), n)] = np.asarray(b)[:n]
                packed[w] = arr
            snap["count_baselines"] = packed
        if self._value_baselines:
            snap["value_baselines"] = {
                w: [np.asarray(l).copy() for l in leaves]
                for w, leaves in self._value_baselines.items()}
        return snap

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.flush_pipeline()
        # mesh snapshots arrive as per-shard slices with key-group-range
        # manifests (state/shard_layout): merge to the dense gid-indexed
        # layout first — restore at ANY mesh size (1 included) re-slices
        # by the CURRENT operator's layout, not the writer's
        from flink_tpu.state.shard_layout import densify_keyed_snapshot
        snap = densify_keyed_snapshot(snap)
        # restores land on the device tier; if the process-wide monitor is
        # still quarantined, the first dispatch re-quarantines and the
        # operator migrates again (the snapshot format is tier-agnostic)
        self._degraded = False
        with self._tier_lock:
            self._tier_epoch += 1   # fence any in-flight promotion
        self._active_rows = None
        self.pane_base = snap["pane_base"]
        self.max_pane = snap["max_pane"]
        self.last_fired_window = snap["last_fired_window"]
        self.watermark = snap["watermark"]
        self.late_dropped = snap.get("late_dropped", 0)
        self._P = snap["P"]
        self._nm = None          # rebinds to the restored key index below
        self._nm_tried = False
        self._dki = None         # probe table rebuilds from the key index
        self._drop_delta()
        self._incr_clear()       # restored state: first cut is a full base
        self._devprobe_resolved = None
        if "key_index" in snap:
            if snap["key_index_kind"] == "ObjectKeyIndex":
                self.key_index = ObjectKeyIndex.restore(snap["key_index"])
            else:
                self.key_index = KeyIndex.restore(snap["key_index"])
            self._K = self._round_key_capacity(max(self.key_index.num_keys, 1))
            self._try_native_mirror()
        self._leaves = None
        self._counts = None
        self._mirror = {}
        if self._pager is not None:
            self._pager.reset()
        if "leaves" in snap:
            from flink_tpu.state.evolution import migrate_acc_leaves
            n = snap["counts"].shape[0]
            panes = np.asarray(snap["panes"], np.int64)

            def fill(j, _n=n, _np=len(panes)):
                # ADDED accumulator field: identity rows in [n, panes] shape
                init = np.asarray(self.spec.leaf_inits[j],
                                  self.spec.leaf_dtypes[j])
                return np.broadcast_to(
                    init, (_n, _np) + tuple(self.spec.leaf_shapes[j])).copy()

            leaves = migrate_acc_leaves(snap["leaves"],
                                        snap.get("leaf_schema"),
                                        self.spec, fill)
            if self._pager is not None:
                # paged restore: resident prefix uploads, overflow spills —
                # works at ANY K_cap relative to the snapshot's key count
                self._paged_restore_rows(n, panes, np.asarray(snap["counts"]),
                                         leaves)
                self._vmirror = {}
                self._count_baselines = {}
                self._value_baselines = {}
                return
            # resolve the cadence NOW (a process-wide calibration verdict may
            # already exist): a deferred restore skips the dispatched device
            # import — the costliest possible upload on exactly the links
            # deferred mode exists for ("calibrating" restores like scatter)
            if self._resolve_device_sync() == "deferred":
                # the mirror (rebuilt below) is the authority; the device
                # replica catches up at the next device_refresh.  Alloc so
                # time/fire guards see live state (content = identity).
                self._ensure_alloc()
                self._device_stale = True
            else:
                self._ensure_alloc()
                slots = jnp.asarray(panes % self._P, jnp.int32)
                self._leaves = tuple(
                    l.at[:n, slots].set(jnp.asarray(s))
                    for l, s in zip(self._leaves, leaves))
                self._counts = self._counts.at[:n, slots].set(
                    jnp.asarray(snap["counts"]))
            # rebuild the host emit mirror from the snapshot's counts
            self._mirror = {}
            counts_np = np.asarray(snap["counts"])
            for j, p in enumerate(panes.tolist()):
                nz = np.flatnonzero(counts_np[:, j] > 0)
                if nz.size:
                    self._mirror_mark(int(p), nz)
            # host tier: re-seed the value mirror from the snapshot (device
            # precision — the f64 surplus re-accumulates from here on)
            self._vmirror = {}
            if self.emit_tier == "host":
                restored = [np.asarray(l) for l in leaves]
                for j, p in enumerate(panes.tolist()):
                    if not counts_np[:, j].any():
                        continue
                    if self._nm is not None:
                        self._nm.import_pane(
                            int(p), counts_np[:, j],
                            [src[:, j] for src in restored])
                        continue
                    entry = self._vmirror_pane(int(p))
                    entry[0][:n] = counts_np[:, j]
                    for k, src in enumerate(restored):
                        entry[k + 1][:n] = src[:, j].astype(
                            self._mirror_dtypes[k])
        self._count_baselines = {w: np.asarray(b, np.int64).copy()
                                 for w, b in
                                 snap.get("count_baselines", {}).items()}
        self._value_baselines = {w: [np.asarray(l).copy() for l in leaves]
                                 for w, leaves in
                                 snap.get("value_baselines", {}).items()}


def _pad_rows(a: np.ndarray, n: int) -> np.ndarray:
    if a.shape[0] == n:
        return a
    pad = [(0, n - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, pad, mode="edge")
