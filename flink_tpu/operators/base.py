"""Stream operator contract (batched).

Analog of ``StreamOperator.java:47`` / ``AbstractStreamOperator.java:88``:
lifecycle (open/snapshot/close), element processing, watermark/time hooks.
Re-designed batched: an operator consumes a ``RecordBatch`` (not one record)
and returns the list of elements it emits; the executor (mailbox analog,
``MailboxProcessor.java:66``) owns ordering, watermark forwarding and barrier
alignment so each operator stays single-writer — the same structural
race-avoidance the reference gets from the mailbox model (SURVEY §5.2).
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Dict, List, Optional

from flink_tpu.core.batch import RecordBatch, StreamElement, Watermark
from flink_tpu.core.functions import RuntimeContext

#: checkpoint id of the snapshot currently being taken, visible to any
#: operator/sink inside the snapshot call tree (chains included).  The
#: runtimes set it around ``snapshot_state()``; 2PC sinks record it with
#: their staged transactions so ``notify_checkpoint_complete(id)`` commits
#: exactly the txns with ``staged_id <= id`` (the TwoPhaseCommitSinkFunction
#: contract).  ContextVar: per-thread defaults keep concurrent subtask
#: threads isolated.
_CURRENT_CHECKPOINT_ID: contextvars.ContextVar[Optional[int]] = \
    contextvars.ContextVar("flink_tpu_current_checkpoint_id", default=None)


def current_checkpoint_id() -> Optional[int]:
    """Checkpoint id of the in-progress snapshot, or None outside one."""
    return _CURRENT_CHECKPOINT_ID.get()


#: True while the snapshot being taken may ship delta INCREMENTS instead of
#: full state (incremental checkpointing enabled AND this cut is neither a
#: savepoint nor a final FLIP-147 snapshot).  Operators with delta tracking
#: (WindowAggOperator, changelog-backed KeyedProcessOperator) read it inside
#: snapshot_state(); everyone else ignores it.  ContextVar like the id:
#: concurrent subtask threads stay isolated, chained operators inherit it.
_SNAPSHOT_INCREMENTAL: contextvars.ContextVar[bool] = \
    contextvars.ContextVar("flink_tpu_snapshot_incremental", default=False)


def snapshot_is_incremental() -> bool:
    """May the in-progress snapshot ship a delta increment?  False outside
    a snapshot, for savepoints and for final (FLIP-147) snapshots."""
    return _SNAPSHOT_INCREMENTAL.get()


@contextlib.contextmanager
def snapshot_scope(checkpoint_id: Optional[int], incremental: bool = False):
    """Runtimes wrap operator ``snapshot_state()`` calls in this scope so
    sinks can associate staged 2PC transactions with the checkpoint id and
    delta-tracking operators know whether increments are allowed."""
    tok = _CURRENT_CHECKPOINT_ID.set(checkpoint_id)
    tok2 = _SNAPSHOT_INCREMENTAL.set(incremental)
    try:
        yield
    finally:
        _SNAPSHOT_INCREMENTAL.reset(tok2)
        _CURRENT_CHECKPOINT_ID.reset(tok)


class StreamOperator:
    """Base operator. Subclasses override what they need.

    Emission contract: every ``process_*`` returns the elements to forward
    downstream (RecordBatches and, rarely, control elements).  The executor
    forwards watermarks/barriers itself *after* delivering them to the
    operator, so fires triggered by a watermark reach downstream before the
    watermark does — same ordering as the reference's in-band control flow.
    """

    name: str = "operator"
    #: operators that only transform rows (no state/time) are chainable into
    #: the surrounding jitted step (``OperatorChain.java:88`` analog)
    is_stateless: bool = False
    #: False for operators that OWN event time (TimestampsAndWatermarks): the
    #: executor/chain must not forward upstream watermarks past them
    forwards_watermarks: bool = True
    #: two-input operators (``TwoInputStreamOperator`` analog) receive
    #: batches via process_batch2(batch, input_index) instead
    is_two_input: bool = False

    def open(self, ctx: RuntimeContext) -> None:
        self.ctx = ctx

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        raise NotImplementedError

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        """Two-input path (``processElement1/2`` analog); only called when
        ``is_two_input`` is True."""
        raise NotImplementedError

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        """Called on watermark advance; return fired elements (watermark itself
        is forwarded by the executor afterwards)."""
        return []

    def on_processing_time(self, timestamp_ms: int) -> List[StreamElement]:
        """Processing-time timer callback (``onProcessingTime`` analog)."""
        return []

    def end_input(self) -> List[StreamElement]:
        """Bounded-input flush (``BoundedOneInput.endInput`` analog)."""
        return []

    def flush_pipeline(self) -> List[StreamElement]:
        """Pipeline barrier hook: operators that pipeline their hot path
        (``WindowAggOperator`` with ``pipeline_depth > 0``) complete every
        in-flight stage here.  Task drivers call it at idle points — input
        momentarily empty, source exhausted — so pipelined work never waits
        on the NEXT batch's arrival.  Default: no-op."""
        return []

    # -- checkpointing -------------------------------------------------------
    def prepare_snapshot_pre_barrier(self) -> List[StreamElement]:
        """Called BEFORE the barrier is forwarded / the snapshot is taken:
        drain any asynchronously-pending emissions so they reach downstream
        ahead of the barrier (the reference drains its external Python
        runtime the same way —
        ``AbstractPythonFunctionOperator.prepareSnapshotPreBarrier:173``).
        Returned elements are forwarded downstream pre-barrier."""
        return []

    def snapshot_state(self) -> Dict[str, Any]:
        """Synchronous snapshot part: return a host-side state dict (numpy
        trees); called at barrier alignment points."""
        return {}

    def restore_state(self, snapshot: Dict[str, Any]) -> None:
        pass

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        """``CheckpointListener`` analog: the checkpoint is durably stored —
        two-phase-commit side effects may publish now."""

    def close(self) -> None:
        pass

    # -- metrics -------------------------------------------------------------
    def metric_group(self):
        m = getattr(self.ctx, "metrics", None) if hasattr(self, "ctx") else None
        return m
