"""Keyed stream joins: interval join, window join, window co-group.

Analogs of ``IntervalJoinOperator`` (``flink-streaming-java/.../co/
IntervalJoinOperator.java``: per-key time-bucketed buffers, join on
|t_l - t_r| in [lower, upper], cleanup by watermark) and
``WindowedStream``-based joins (``JoinedStreams``/``CoGroupedStreams``:
both sides buffered per (key, window), joined at window fire).

Batched columnar design: each side's rows accumulate in per-side host
buffers (columns + timestamps + keys); on watermark advance the *completed*
time range is joined VECTORIZED — sort both sides by key, intersect key
spans, emit the per-key cross products filtered by the time predicate — one
numpy pass instead of per-record state lookups.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import (LONG_MIN, RecordBatch, StreamElement,
                                  Watermark)
from flink_tpu.operators.base import StreamOperator
from flink_tpu.windowing.assigners import WindowAssigner


class _SideBuffer:
    """Columnar row buffer for one join side."""

    def __init__(self):
        self.batches: List[RecordBatch] = []

    def add(self, batch: RecordBatch) -> None:
        if len(batch):
            self.batches.append(batch)

    def materialize(self) -> Optional[RecordBatch]:
        if not self.batches:
            return None
        out = RecordBatch.concat(self.batches)
        self.batches = [out]
        return out

    def retain_after(self, min_ts: int) -> None:
        """Drop rows with ts < min_ts (watermark cleanup)."""
        m = self.materialize()
        if m is None or m.timestamps is None:
            return
        keep = np.asarray(m.timestamps) >= min_ts
        self.batches = [m.select(keep)] if keep.any() else []

    def snapshot(self):
        m = self.materialize()
        return None if m is None else {
            "columns": {k: np.asarray(v) for k, v in m.columns.items()},
            "timestamps": None if m.timestamps is None else np.asarray(m.timestamps),
        }

    def restore(self, snap) -> None:
        self.batches = []
        if snap is not None:
            self.batches = [RecordBatch(snap["columns"],
                                        timestamps=snap["timestamps"])]


def _join_pairs(lk: np.ndarray, rk: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized equi-join index pairs: returns (left_idx, right_idx) of
    every cross pair with equal keys (sort + span intersection).

    Dispatches to the device sorted-merge kernel
    (``ops/join_kernels.device_join_pairs``) when ``FLINK_TPU_DEVICE_JOIN=1``
    — the right choice for device-resident pipelines; host-numpy span
    intersection otherwise (transfer-bound transports, see the kernel
    module's docstring)."""
    import os

    if os.environ.get("FLINK_TPU_DEVICE_JOIN") == "1":
        from flink_tpu.ops.join_kernels import device_join_pairs
        return device_join_pairs(lk, rk)
    lo = np.argsort(lk, kind="stable")
    ro = np.argsort(rk, kind="stable")
    lks, rks = lk[lo], rk[ro]
    # unique keys + spans on both sides
    lu, lstart, lcount = np.unique(lks, return_index=True, return_counts=True)
    ru, rstart, rcount = np.unique(rks, return_index=True, return_counts=True)
    common, li, ri = np.intersect1d(lu, ru, return_indices=True)
    if common.size == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    ls, lc = lstart[li], lcount[li]
    rs, rc = rstart[ri], rcount[ri]
    n_pairs = int((lc * rc).sum())
    left_out = np.empty(n_pairs, np.int64)
    right_out = np.empty(n_pairs, np.int64)
    pos = 0
    for s_l, c_l, s_r, c_r in zip(ls.tolist(), lc.tolist(),
                                  rs.tolist(), rc.tolist()):
        block = c_l * c_r
        left_out[pos:pos + block] = np.repeat(lo[s_l:s_l + c_l], c_r)
        right_out[pos:pos + block] = np.tile(ro[s_r:s_r + c_r], c_l)
        pos += block
    return left_out, right_out


def _merge_columns(left: RecordBatch, right: RecordBatch,
                   li: np.ndarray, ri: np.ndarray,
                   left_prefix: str = "", right_prefix: str = "r_") -> Dict[str, np.ndarray]:
    cols: Dict[str, np.ndarray] = {}
    for k, v in left.columns.items():
        cols[left_prefix + k] = np.asarray(v)[li]
    for k, v in right.columns.items():
        name = right_prefix + k if (left_prefix + k) in cols or k in cols else k
        cols[name] = np.asarray(v)[ri]
    return cols


class IntervalJoinOperator(StreamOperator):
    """``a.interval_join(b).between(lower, upper)``: emit (l, r) where
    ``l.key == r.key`` and ``l.ts + lower <= r.ts <= l.ts + upper``."""

    is_two_input = True

    def __init__(self, key_column: str, other_key_column: str,
                 lower_ms: int, upper_ms: int,
                 output_fn: Optional[Callable[[Dict[str, Any]], Dict[str, Any]]] = None,
                 name: str = "interval-join"):
        self.key_column = key_column
        self.other_key_column = other_key_column
        self.lower_ms = lower_ms
        self.upper_ms = upper_ms
        self.output_fn = output_fn
        self.name = name
        self.left = _SideBuffer()
        self.right = _SideBuffer()
        self._emitted_wm = LONG_MIN

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        if batch.timestamps is None:
            raise ValueError("interval join needs event-time timestamps")
        (self.left if input_index == 0 else self.right).add(batch)
        return []

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        return self._fire(watermark.timestamp)

    def end_input(self) -> List[StreamElement]:
        return self._fire(2 ** 62)

    def _fire(self, wm: int) -> List[StreamElement]:
        """Join all left rows whose FULL right-window [l+lower, l+upper] is
        covered by the watermark (they can never match again afterwards)."""
        l = self.left.materialize()
        r = self.right.materialize()
        out: List[StreamElement] = []
        if l is not None and r is not None and len(l) and len(r):
            lts = np.asarray(l.timestamps)
            complete = lts + self.upper_ms <= wm
            prev_done = lts + self.upper_ms <= self._emitted_wm
            ready = complete & ~prev_done
            if ready.any():
                lsel = l.select(ready)
                lk = np.asarray(lsel.column(self.key_column))
                rk = np.asarray(r.column(self.other_key_column))
                li, ri = _join_pairs(lk, rk)
                if li.size:
                    lt = np.asarray(lsel.timestamps)[li]
                    rt = np.asarray(r.timestamps)[ri]
                    ok = (rt >= lt + self.lower_ms) & (rt <= lt + self.upper_ms)
                    li, ri = li[ok], ri[ok]
                if li.size:
                    cols = _merge_columns(lsel, r, li, ri)
                    ts = np.maximum(np.asarray(lsel.timestamps)[li],
                                    np.asarray(r.timestamps)[ri])
                    if self.output_fn is not None:
                        cols = self.output_fn(cols)
                    out.append(RecordBatch(cols, timestamps=ts))
        self._emitted_wm = max(self._emitted_wm, wm)
        # cleanup: a LEFT row is dead once joined (ts+upper <= wm). A RIGHT
        # row may still match any UNFIRED left row; the oldest unfired left
        # row has ts > wm - upper, so right rows with ts >= wm - upper + lower
        # must be kept.
        self.left.retain_after(wm - self.upper_ms if wm < 2 ** 61 else 2 ** 62)
        self.right.retain_after(wm - self.upper_ms + self.lower_ms
                                if wm < 2 ** 61 else 2 ** 62)
        return out

    def snapshot_state(self) -> Dict[str, Any]:
        return {"left": self.left.snapshot(), "right": self.right.snapshot(),
                "emitted_wm": self._emitted_wm}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.left.restore(snap.get("left"))
        self.right.restore(snap.get("right"))
        self._emitted_wm = snap.get("emitted_wm", LONG_MIN)


class WindowJoinOperator(StreamOperator):
    """``a.join(b).where(k).equal_to(k).window(w).apply(...)``: inner join of
    the two sides per (key, window), emitted at window fire.  ``cogroup=True``
    emits grouped rows to ``apply_fn(key, window, left_rows, right_rows)``
    instead (CoGroup semantics: fires even when one side is empty)."""

    is_two_input = True

    def __init__(self, assigner: WindowAssigner, key_column: str,
                 other_key_column: str,
                 apply_fn: Optional[Callable] = None,
                 cogroup: bool = False, name: str = "window-join"):
        if getattr(assigner, "panes_per_window", 1) != 1:
            raise ValueError("window join supports tumbling windows "
                             "(one pane per window)")
        self.assigner = assigner
        self.key_column = key_column
        self.other_key_column = other_key_column
        self.apply_fn = apply_fn
        self.cogroup = cogroup
        self.name = name
        self.left = _SideBuffer()
        self.right = _SideBuffer()
        self._fired_upto = LONG_MIN

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        if batch.timestamps is None:
            raise ValueError("window join needs event-time timestamps")
        (self.left if input_index == 0 else self.right).add(batch)
        return []

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        return self._fire(watermark.timestamp)

    def end_input(self) -> List[StreamElement]:
        return self._fire(2 ** 62)

    def _window_ids(self, ts: np.ndarray) -> np.ndarray:
        return self.assigner.pane_of(ts)

    def _fire(self, wm: int) -> List[StreamElement]:
        l = self.left.materialize()
        r = self.right.materialize()
        out: List[StreamElement] = []
        lw = self._window_ids(np.asarray(l.timestamps)) if l is not None and len(l) else np.zeros(0, np.int64)
        rw = self._window_ids(np.asarray(r.timestamps)) if r is not None and len(r) else np.zeros(0, np.int64)
        all_windows = np.union1d(np.unique(lw), np.unique(rw))
        for w in all_windows.tolist():
            bounds = self.assigner.window_bounds(int(w))
            if bounds.max_timestamp > wm or bounds.max_timestamp <= self._fired_upto:
                continue
            lsel = l.select(lw == w) if l is not None and len(l) else None
            rsel = r.select(rw == w) if r is not None and len(r) else None
            if self.cogroup:
                out.extend(self._emit_cogroup(int(w), bounds, lsel, rsel))
            else:
                if lsel is None or rsel is None or not len(lsel) or not len(rsel):
                    continue
                li, ri = _join_pairs(
                    np.asarray(lsel.column(self.key_column)),
                    np.asarray(rsel.column(self.other_key_column)))
                if not li.size:
                    continue
                cols = _merge_columns(lsel, rsel, li, ri)
                cols["window_start"] = np.full(li.size, bounds.start, np.int64)
                cols["window_end"] = np.full(li.size, bounds.end, np.int64)
                if self.apply_fn is not None:
                    cols = self.apply_fn(cols)
                out.append(RecordBatch(
                    cols, timestamps=np.full(li.size, bounds.max_timestamp,
                                             np.int64)))
        self._fired_upto = max(self._fired_upto, wm)

        # drop rows of fully-fired windows (window end computed once per
        # UNIQUE window id, mapped back vectorized)
        def _ends(wids: np.ndarray) -> np.ndarray:
            uw, inv = np.unique(wids, return_inverse=True)
            uend = np.asarray([self.assigner.window_bounds(int(w)).max_timestamp
                               for w in uw.tolist()], np.int64)
            return uend[inv]

        if l is not None and len(l):
            ends = _ends(lw)
            self.left.batches = [l.select(ends > wm)] if (ends > wm).any() else []
        if r is not None and len(r):
            ends = _ends(rw)
            self.right.batches = [r.select(ends > wm)] if (ends > wm).any() else []
        return out

    def _emit_cogroup(self, w: int, bounds, lsel, rsel) -> List[StreamElement]:
        lkeys = (np.asarray(lsel.column(self.key_column))
                 if lsel is not None and len(lsel) else np.zeros(0, np.int64))
        rkeys = (np.asarray(rsel.column(self.other_key_column))
                 if rsel is not None and len(rsel) else np.zeros(0, np.int64))
        rows = []
        for key in np.union1d(np.unique(lkeys), np.unique(rkeys)).tolist():
            lrows = lsel.select(lkeys == key).to_rows() if lkeys.size else []
            rrows = rsel.select(rkeys == key).to_rows() if rkeys.size else []
            res = self.apply_fn(key, bounds, lrows, rrows)
            if res is not None:
                rows.append((res, bounds.max_timestamp))
        if not rows:
            return []
        cols = {k: np.asarray([r[0][k] for r in rows]) for k in rows[0][0]}
        return [RecordBatch(cols, timestamps=np.asarray([r[1] for r in rows],
                                                        np.int64))]

    def snapshot_state(self) -> Dict[str, Any]:
        return {"left": self.left.snapshot(), "right": self.right.snapshot(),
                "fired_upto": self._fired_upto}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.left.restore(snap.get("left"))
        self.right.restore(snap.get("right"))
        self._fired_upto = snap.get("fired_upto", LONG_MIN)
