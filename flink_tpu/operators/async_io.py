"""Async I/O operator: concurrent external lookups with ordered/unordered
result emission.

Analog of ``AsyncWaitOperator.java:78`` (``AsyncDataStream.orderedWait`` /
``unorderedWait``): user async function runs on a thread pool, a bounded
in-flight queue applies backpressure, a timeout fails or drops slow calls.
Batched: the unit of async work is a whole RecordBatch (one pool task per
batch), keeping the boundary-crossing cost amortized.  Ordered mode emits
results in submission order; unordered emits as they complete but never
across a watermark (the reference's watermark fencing).
"""

from __future__ import annotations

import concurrent.futures as cf
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.core.batch import RecordBatch, StreamElement, Watermark
from flink_tpu.core.functions import RuntimeContext
from flink_tpu.operators.base import StreamOperator


class AsyncFunction:
    """User async function: ``invoke(cols) -> cols`` runs on a worker
    thread (``AsyncFunction.asyncInvoke`` analog)."""

    def open(self, ctx: RuntimeContext) -> None:
        pass

    def invoke(self, cols: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def timeout(self, cols: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Called when a batch times out; return replacement output or None
        to drop (default: raise, failing the job like the reference)."""
        raise TimeoutError("async I/O batch timed out")


class _Entry:
    __slots__ = ("future", "batch", "is_watermark", "watermark", "deadline")

    def __init__(self, future=None, batch=None, watermark=None,
                 deadline: float = 0.0):
        self.future = future
        self.batch = batch
        self.is_watermark = watermark is not None
        self.watermark = watermark
        #: absolute monotonic deadline; the timeout clock starts at
        #: SUBMISSION (reference registers the timer on asyncInvoke), not at
        #: drain time
        self.deadline = deadline


class AsyncWaitOperator(StreamOperator):
    #: the operator owns watermark ordering: queued watermarks re-emit from
    #: _drain AFTER the results submitted before them — the runtime must not
    #: forward them early
    forwards_watermarks = False

    def __init__(self, fn: AsyncFunction | Callable, capacity: int = 16,
                 timeout_ms: int = 60_000, ordered: bool = True,
                 name: str = "async-wait"):
        if not isinstance(fn, AsyncFunction):
            f = fn

            class _Wrap(AsyncFunction):
                def invoke(self, cols):
                    return f(cols)

            fn = _Wrap()
        self.fn = fn
        self.capacity = capacity
        self.timeout_ms = timeout_ms
        self.ordered = ordered
        self.name = name
        self._queue: List[_Entry] = []
        self._pool: Optional[cf.ThreadPoolExecutor] = None

    def open(self, ctx: RuntimeContext) -> None:
        super().open(ctx)
        self.fn.open(ctx)
        self._pool = cf.ThreadPoolExecutor(
            max_workers=min(self.capacity, 8),
            thread_name_prefix=f"async-{self.name}")

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        out = []
        # capacity backpressure: block for completions when the queue is full
        while len([e for e in self._queue if not e.is_watermark]) >= self.capacity:
            out.extend(self._drain(wait_one=True))
        self._queue.append(_Entry(
            future=self._pool.submit(self.fn.invoke, dict(batch.columns)),
            batch=batch,
            deadline=time.monotonic() + self.timeout_ms / 1000.0))
        out.extend(self._drain())
        return out

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        # watermark fences: everything submitted before it must emit first
        self._queue.append(_Entry(watermark=watermark))
        return self._drain()

    def end_input(self) -> List[StreamElement]:
        out = []
        while self._queue:
            out.extend(self._drain(wait_one=True))
        return out

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    # -- emission ------------------------------------------------------------
    def _result(self, entry: _Entry) -> Optional[RecordBatch]:
        remaining = max(0.0, entry.deadline - time.monotonic())
        try:
            cols = entry.future.result(timeout=remaining)
        except cf.TimeoutError:
            entry.future.cancel()
            cols = self.fn.timeout(dict(entry.batch.columns))
            if cols is None:
                return None
        return RecordBatch({k: np.asarray(v) for k, v in cols.items()},
                           entry.batch.timestamps)

    def _drain(self, wait_one: bool = False) -> List[StreamElement]:
        out: List[StreamElement] = []
        while self._queue:
            head = self._queue[0]
            if head.is_watermark:
                self._queue.pop(0)
                out.append(head.watermark)
                continue
            if self.ordered:
                expired = time.monotonic() >= head.deadline
                if not head.future.done() and not wait_one and not expired:
                    break
                self._queue.pop(0)
                res = self._result(head)
                if res is not None:
                    out.append(res)
                wait_one = False
            else:
                # unordered: emit ANY completed entry up to the next fence
                fence = next((i for i, e in enumerate(self._queue)
                              if e.is_watermark), len(self._queue))
                now = time.monotonic()
                done = [i for i in range(fence)
                        if self._queue[i].future.done()
                        or now >= self._queue[i].deadline]
                if not done and wait_one and fence > 0:
                    # waits up to the timeout and applies the fn.timeout
                    # replacement hook — same semantics as ordered mode
                    e = self._queue.pop(0)
                    res = self._result(e)
                    if res is not None:
                        out.append(res)
                    wait_one = False
                    continue
                if not done:
                    if fence == 0:
                        continue  # head is a fence: loop handles it
                    break
                for i in reversed(done):
                    e = self._queue.pop(i)
                    res = self._result(e)
                    if res is not None:
                        out.append(res)
                wait_one = False
        return out

    #: note on checkpoints: the WHOLE queue is part of the snapshot — batches
    #: re-submit on restore, and fenced watermarks must survive too (this
    #: operator is their only forwarder: forwards_watermarks is False)
    def snapshot_state(self) -> Dict[str, Any]:
        entries = []
        for e in self._queue:
            if e.is_watermark:
                entries.append({"watermark": e.watermark.timestamp})
            else:
                entries.append({"columns": {k: np.asarray(v)
                                            for k, v in e.batch.columns.items()},
                                "timestamps": None if e.batch.timestamps is None
                                else np.asarray(e.batch.timestamps)})
        return {"queue": entries}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        for e in snap.get("queue", snap.get("pending", [])):
            if "watermark" in e:
                self._queue.append(_Entry(watermark=Watermark(e["watermark"])))
            else:
                self._queue.append(_Entry(
                    future=self._pool.submit(self.fn.invoke, dict(e["columns"])),
                    batch=RecordBatch(e["columns"], timestamps=e["timestamps"]),
                    deadline=time.monotonic() + self.timeout_ms / 1000.0))
