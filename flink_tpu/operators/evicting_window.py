"""Evicting window operator: raw-element window buffers + evictor + apply.

Analog of ``EvictingWindowOperator.java``: unlike the incremental
``WindowAggOperator`` (constant-size ACC per key x pane), evicting windows
must buffer the raw rows (reference: ``ListStateDescriptor`` in
``WindowOperatorBuilder:271``) because the evictor inspects individual
elements at fire time.

TPU-first layout (VERDICT r2 #2 "raw-element ListState rows sharded like
pane state"):

- **Columnar pane buffers**: rows are appended as columnar chunks per PANE
  (the gcd-span shared by all covering windows) — sliding windows share
  pane buffers exactly like ``WindowAggOperator``'s pane ring shares ACC
  cells, so each row is stored once however many windows cover it.
- **Vectorized bookkeeping, per-key UDF boundary**: batching, the lateness
  gate (watermark formula, identical to ``WindowAggOperator``), pane
  retention and window-due computation are all array ops; only the
  evictor + ``apply_fn`` run per (key, window) — they are row-level user
  functions by contract (the reference's evictor inspects individual
  elements too, ``EvictingWindowOperator.java``), which is also why this
  state stays host-side: the fire-time compute IS the user's Python.
- **Key-group rescale**: snapshots are columnar with raw keys;
  ``split_snapshot``/``merge_snapshots`` route rows by key group
  (``StateAssignmentOperation.reDistributeKeyedStates`` analog) and
  parallel restores filter to the subtask's range — same story as
  sessions.  Under a mesh/multi-process deployment the keyed exchange
  partitions rows to subtasks; each subtask holds only its key range.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.core.batch import (LONG_MIN, RecordBatch, StreamElement,
                                  Watermark)
from flink_tpu.operators.base import StreamOperator
from flink_tpu.windowing.assigners import WindowAssigner
from flink_tpu.windowing.evictors import Evictor


class EvictingWindowOperator(StreamOperator):
    """``window(...).evictor(...).apply(fn)``: fn(key, window, rows) -> row."""

    def __init__(self, assigner: WindowAssigner, evictor: Optional[Evictor],
                 key_column: str,
                 apply_fn: Callable[[Any, Any, List[dict]], Optional[dict]],
                 name: str = "evicting-window",
                 allowed_lateness_ms: int = 0):
        if not hasattr(assigner, "pane_of") or \
                not hasattr(assigner, "window_panes"):
            raise ValueError("evicting windows require a pane-based "
                             "assigner (tumbling/sliding)")
        self.assigner = assigner
        self.evictor = evictor
        self.key_column = key_column
        self.apply_fn = apply_fn
        self.name = name
        self.lateness = int(allowed_lateness_ms)
        #: pane id -> list of columnar chunks (seq[B], ts[B], cols dict)
        self._panes: Dict[int, List[tuple]] = {}
        self._seq = 0
        self.watermark: int = LONG_MIN
        self.last_fired_window: Optional[int] = None
        self.late_dropped = 0

    # ------------------------------------------------------------- ingest
    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if batch.timestamps is None:
            raise ValueError("evicting windows need event-time timestamps")
        if len(batch) == 0:
            return []
        ts = np.asarray(batch.timestamps, np.int64)
        panes = self.assigner.pane_of(ts)

        # ---- beyond-lateness drop: cleanup time (last covering window end
        # - 1 + lateness) passed by the WATERMARK (never arrival order)
        if self.watermark != LONG_MIN:
            uniq_p = np.unique(panes)
            is_late = np.asarray(
                [self.assigner.last_window_end_of_pane(int(p)) - 1
                 + self.lateness <= self.watermark
                 for p in uniq_p.tolist()])
            if is_late.any():
                live = ~np.isin(panes, uniq_p[is_late])
                self.late_dropped += int(np.count_nonzero(~live))
                if not live.any():
                    return []
                batch = batch.select(live)
                ts = ts[live]
                panes = panes[live]

        cols = {c: np.asarray(v) for c, v in batch.columns.items()}
        refire: set = set()
        for p in np.unique(panes).tolist():
            m = panes == p
            nsel = int(np.count_nonzero(m))
            chunk = (np.arange(self._seq, self._seq + nsel, dtype=np.int64),
                     ts[m], {c: v[m] for c, v in cols.items()})
            self._seq += nsel
            self._panes.setdefault(int(p), []).append(chunk)
            # late-but-within-lateness rows re-fire already-fired windows —
            # but ONLY windows whose OWN cleanup horizon (maxTimestamp +
            # lateness) is still open: a sliding pane can outlive an early
            # covering window whose state the reference would have purged
            if self.last_fired_window is not None:
                w0, w1 = self.assigner.windows_of_pane(int(p))
                for w in range(w0, w1 + 1):
                    max_ts = self.assigner.window_bounds(w).max_timestamp
                    if (w <= self.last_fired_window
                            and max_ts <= self.watermark
                            and max_ts + self.lateness > self.watermark):
                        refire.add(w)
        if refire:
            return self._fire_windows(sorted(refire))
        return []

    # ------------------------------------------------------------- firing
    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        self.watermark = max(self.watermark, watermark.timestamp)
        return self._advance(self.watermark)

    def end_input(self) -> List[StreamElement]:
        return self._advance(2 ** 62)

    def _largest_fired_window(self, now: int) -> Optional[int]:
        """Largest window id whose maxTimestamp <= now (the EventTimeTrigger
        fire horizon)."""
        a = self.assigner
        denom = a.pane_stride * a.pane_ms
        w = (now + 1 - a._offset - a.panes_per_window * a.pane_ms) // denom
        while a.window_bounds(w + 1).max_timestamp <= now:
            w += 1
        while a.window_bounds(w).max_timestamp > now:
            w -= 1
        return int(w)

    def _advance(self, now: int) -> List[StreamElement]:
        if not self._panes:
            return []
        a = self.assigner
        live = sorted(self._panes)
        lo_w = a.windows_of_pane(live[0])[0]
        hi_w = a.windows_of_pane(live[-1])[1]
        due = [w for w in range(
            max(lo_w, (self.last_fired_window + 1)
                if self.last_fired_window is not None else lo_w),
            hi_w + 1)
            if a.window_bounds(w).max_timestamp <= now]
        out = self._fire_windows(due)
        if due and (self.last_fired_window is None
                    or due[-1] > self.last_fired_window):
            self.last_fired_window = due[-1]
        # retention: drop panes past their cleanup horizon
        for p in live:
            if a.last_window_end_of_pane(p) - 1 + self.lateness <= now:
                del self._panes[p]
        return out

    def _fire_windows(self, windows) -> List[StreamElement]:
        out_rows, out_ts = [], []
        for w in windows:
            first, last = self.assigner.window_panes(w)
            chunks = [c for p in range(first, last + 1)
                      for c in self._panes.get(p, [])]
            if not chunks:
                continue
            bounds = self.assigner.window_bounds(w)
            seq = np.concatenate([c[0] for c in chunks])
            ts = np.concatenate([c[1] for c in chunks])
            cols = {name: np.concatenate([c[2][name] for c in chunks])
                    for name in chunks[0][2]}
            keys = cols[self.key_column]
            uniq, inv = np.unique(keys, return_inverse=True)
            order = np.lexsort((seq, inv))       # per-key, arrival order
            inv_s = inv[order]
            starts = np.flatnonzero(np.r_[True, inv_s[1:] != inv_s[:-1]])
            ends = np.r_[starts[1:], inv_s.size]
            for s, e in zip(starts, ends):
                sel = order[s:e]
                k = uniq[inv_s[s]]
                k = k.item() if isinstance(k, np.generic) else k
                rows = RecordBatch({c: v[sel] for c, v in
                                    cols.items()}).to_rows()
                if self.evictor is not None:
                    keep = self.evictor.keep_mask(ts[sel],
                                                  bounds.max_timestamp,
                                                  rows=rows)
                    rows = [r for r, m in zip(rows, keep) if m]
                if not rows:
                    continue
                res = self.apply_fn(k, bounds, rows)
                if res is not None:
                    out_rows.append(res)
                    out_ts.append(bounds.max_timestamp)
        if not out_rows:
            return []
        ocols = {c: np.asarray([r[c] for r in out_rows]) for c in out_rows[0]}
        return [RecordBatch(ocols, timestamps=np.asarray(out_ts, np.int64))]

    # ------------------------------------------------------- checkpointing
    def snapshot_state(self) -> Dict[str, Any]:
        packed = {}
        for p, chunks in self._panes.items():
            packed[p] = {
                "seq": np.concatenate([c[0] for c in chunks]),
                "ts": np.concatenate([c[1] for c in chunks]),
                "cols": {name: np.concatenate([c[2][name] for c in chunks])
                         for name in chunks[0][2]},
            }
        return {"panes": packed, "seq": self._seq,
                "watermark": self.watermark,
                "last_fired_window": self.last_fired_window,
                "late_dropped": self.late_dropped,
                "__key_column__": self.key_column}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        if "buffers" in snap:
            self._restore_legacy(snap)
            return
        self._seq = int(snap.get("seq", 0))
        self.watermark = int(snap.get("watermark", LONG_MIN))
        self.last_fired_window = snap.get("last_fired_window")
        self.late_dropped = int(snap.get("late_dropped", 0))
        self._panes = {}
        ctx = getattr(self, "ctx", None)
        for p, packed in snap.get("panes", {}).items():
            seq = np.asarray(packed["seq"])
            ts = np.asarray(packed["ts"])
            cols = {c: np.asarray(v) for c, v in packed["cols"].items()}
            if ctx is not None and ctx.parallelism > 1:
                from flink_tpu.core import keygroups
                kg = keygroups.assign_to_key_group(
                    keygroups.hash_keys(cols[self.key_column]),
                    ctx.max_parallelism)
                rng = keygroups.compute_key_group_range(
                    ctx.max_parallelism, ctx.parallelism, ctx.subtask_index)
                keep = (kg >= rng.start) & (kg <= rng.end)
                seq, ts = seq[keep], ts[keep]
                cols = {c: v[keep] for c, v in cols.items()}
            if seq.size:
                self._panes[int(p)] = [(seq, ts, cols)]

    def _restore_legacy(self, snap: Dict[str, Any]) -> None:
        """Pre-r3 per-row dict snapshots ((key, window) -> [(seq, ts, row)]);
        tumbling assigners only (pane id == window id there)."""
        self._seq = int(snap["seq"])
        self.watermark = int(snap.get("fired_upto", LONG_MIN))
        # the old gate was fired_upto: every window whose maxTimestamp it
        # passed HAS fired — recover that horizon, or retained-for-lateness
        # windows would spuriously re-fire at the next watermark
        self.last_fired_window = (
            self._largest_fired_window(self.watermark)
            if self.watermark != LONG_MIN else None)
        self._panes = {}
        for (k, w), entries in snap.get("buffers", {}).items():
            for seq, ts, row in entries:
                chunk = (np.asarray([seq], np.int64),
                         np.asarray([ts], np.int64),
                         {c: np.asarray([v]) for c, v in row.items()})
                self._panes.setdefault(int(w), []).append(chunk)

    @staticmethod
    def split_snapshot(snap: Dict[str, Any], max_parallelism: int,
                       new_parallelism: int, key_column: str = None,
                       ) -> List[Dict[str, Any]]:
        """Rescale: route buffered rows by key group.  The key column name
        rides inside the snapshot's pane columns; the first column set's
        keys are found via ``__key_column__`` when present, else the caller
        passes it."""
        from flink_tpu.core import keygroups
        kc = key_column or snap.get("__key_column__")
        out = []
        for i, rng in enumerate(
                keygroups.key_group_ranges(max_parallelism, new_parallelism)):
            part = dict(snap)
            part_panes = {}
            for p, packed in snap.get("panes", {}).items():
                keys = np.asarray(packed["cols"][kc])
                kg = keygroups.assign_to_key_group(
                    keygroups.hash_keys(keys), max_parallelism)
                keep = (kg >= rng.start) & (kg <= rng.end)
                if keep.any():
                    part_panes[p] = {
                        "seq": np.asarray(packed["seq"])[keep],
                        "ts": np.asarray(packed["ts"])[keep],
                        "cols": {c: np.asarray(v)[keep]
                                 for c, v in packed["cols"].items()},
                    }
            part["panes"] = part_panes
            if i > 0:
                part["late_dropped"] = 0
            out.append(part)
        return out

    @staticmethod
    def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Scale-down: per-pane columnar concat."""
        merged = dict(snaps[0])
        panes: Dict[int, Dict[str, Any]] = {}
        for s in snaps:
            for p, packed in s.get("panes", {}).items():
                cur = panes.get(p)
                if cur is None:
                    panes[p] = {k: (dict(v) if isinstance(v, dict) else
                                    np.asarray(v))
                                for k, v in packed.items()}
                else:
                    cur["seq"] = np.concatenate([cur["seq"], packed["seq"]])
                    cur["ts"] = np.concatenate([cur["ts"], packed["ts"]])
                    cur["cols"] = {c: np.concatenate([cur["cols"][c],
                                                      packed["cols"][c]])
                                   for c in cur["cols"]}
        merged["panes"] = panes
        merged["seq"] = max(int(s.get("seq", 0)) for s in snaps)
        merged["watermark"] = max(int(s.get("watermark", LONG_MIN))
                                  for s in snaps)
        merged["late_dropped"] = sum(int(s.get("late_dropped", 0))
                                     for s in snaps)
        lf = [s.get("last_fired_window") for s in snaps
              if s.get("last_fired_window") is not None]
        merged["last_fired_window"] = max(lf) if lf else None
        return merged
