"""Evicting window operator: raw-element window buffers + evictor + apply.

Analog of ``EvictingWindowOperator.java``: unlike the incremental
``WindowAggOperator`` (constant-size ACC per key x pane), evicting windows
must buffer the raw rows (reference: ``ListStateDescriptor`` in
``WindowOperatorBuilder:271``) because the evictor inspects individual
elements at fire time.  Buffered columnar per (key, window); at watermark
fire the evictor computes a keep-mask (arrival order), then the window
function folds the surviving rows.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.core.batch import (LONG_MIN, RecordBatch, StreamElement,
                                  Watermark)
from flink_tpu.operators.base import StreamOperator
from flink_tpu.windowing.assigners import WindowAssigner
from flink_tpu.windowing.evictors import Evictor


class EvictingWindowOperator(StreamOperator):
    """``window(...).evictor(...).apply(fn)``: fn(key, window, rows) -> row."""

    def __init__(self, assigner: WindowAssigner, evictor: Optional[Evictor],
                 key_column: str,
                 apply_fn: Callable[[Any, Any, List[dict]], Optional[dict]],
                 name: str = "evicting-window",
                 allowed_lateness_ms: int = 0):
        if getattr(assigner, "panes_per_window", 1) != 1:
            raise ValueError("evicting windows support tumbling assigners")
        self.assigner = assigner
        self.evictor = evictor
        self.key_column = key_column
        self.apply_fn = apply_fn
        self.name = name
        self.allowed_lateness_ms = allowed_lateness_ms
        #: (key, window_id) -> list of (arrival_seq, ts, row)
        self._buffers: Dict[Any, list] = {}
        self._seq = 0
        self._fired_upto = LONG_MIN

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if batch.timestamps is None:
            raise ValueError("evicting windows need event-time timestamps")
        keys = np.asarray(batch.column(self.key_column))
        ts = np.asarray(batch.timestamps, np.int64)
        wins = self.assigner.pane_of(ts)
        rows = batch.to_rows()
        late_refire = set()
        for i in range(len(batch)):
            w = int(wins[i])
            max_ts = self.assigner.window_bounds(w).max_timestamp
            if max_ts <= self._fired_upto:
                # window already fired: within allowed lateness the element
                # joins the retained buffer and the window RE-fires
                # (WindowOperator late-firing semantics); beyond it: dropped
                if max_ts + self.allowed_lateness_ms <= self._fired_upto:
                    continue
                late_refire.add((self._key_of(keys, i), w))
            k = self._key_of(keys, i)
            self._buffers.setdefault((k, w), []).append(
                (self._seq, int(ts[i]), rows[i]))
            self._seq += 1
        if late_refire:
            return self._fire_windows(late_refire, cleanup=False)
        return []

    @staticmethod
    def _key_of(keys: np.ndarray, i: int):
        return keys[i].item() if isinstance(keys[i], np.generic) else keys[i]

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        return self._fire(watermark.timestamp)

    def end_input(self) -> List[StreamElement]:
        return self._fire(2 ** 62)

    def _fire(self, wm: int) -> List[StreamElement]:
        to_fire = set()
        cleanup = []
        for (k, w) in self._buffers:
            max_ts = self.assigner.window_bounds(w).max_timestamp
            if max_ts + self.allowed_lateness_ms <= wm:
                cleanup.append((k, w))
            if self._fired_upto < max_ts <= wm:
                to_fire.add((k, w))
        out = self._fire_windows(to_fire, cleanup=False)
        for kw in cleanup:
            self._buffers.pop(kw, None)
        self._fired_upto = max(self._fired_upto, wm)
        return out

    def _fire_windows(self, window_keys, cleanup: bool) -> List[StreamElement]:
        out_rows = []
        out_ts = []
        for (k, w) in sorted(window_keys, key=lambda kw: kw[1]):
            entries = self._buffers.get((k, w))
            if not entries:
                continue
            bounds = self.assigner.window_bounds(w)
            entries.sort(key=lambda e: e[0])         # arrival order
            ts = np.asarray([e[1] for e in entries], np.int64)
            if self.evictor is None:
                rows = [e[2] for e in entries]
            else:
                all_rows = [e[2] for e in entries]
                keep = self.evictor.keep_mask(ts, bounds.max_timestamp,
                                              rows=all_rows)
                rows = [r for r, m in zip(all_rows, keep) if m]
            res = self.apply_fn(k, bounds, rows)
            if res is not None:
                out_rows.append(res)
                out_ts.append(bounds.max_timestamp)
            if cleanup:
                del self._buffers[(k, w)]
        if not out_rows:
            return []
        cols = {c: np.asarray([r[c] for r in out_rows]) for c in out_rows[0]}
        return [RecordBatch(cols, timestamps=np.asarray(out_ts, np.int64))]

    def snapshot_state(self) -> Dict[str, Any]:
        return {"buffers": {k: list(v) for k, v in self._buffers.items()},
                "seq": self._seq, "fired_upto": self._fired_upto}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._buffers = {k: list(v) for k, v in snap["buffers"].items()}
        self._seq = snap["seq"]
        self._fired_upto = snap["fired_upto"]
