"""Streaming iterations: feedback edges via in-memory queues.

Analog of ``StreamIterationHead``/``StreamIterationTail``
(``runtime/tasks/StreamIterationHead.java``): ``iterate()`` unions the
original stream with a feedback source backed by a shared queue;
``close_with(stream)`` attaches a feedback sink writing that stream's
batches back into the queue.  Like the reference, termination is
timeout-based: the feedback source ends after ``max_wait_ms`` with no
feedback data once its upstream finished feeding it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Iterator, List, Optional

from flink_tpu.core.batch import RecordBatch, StreamElement
from flink_tpu.connectors.sources import Source, SourceSplit
from flink_tpu.operators.base import StreamOperator


class FeedbackQueue:
    """Shared buffer between iteration tail and head."""

    def __init__(self):
        self._q: deque = deque()
        self._lock = threading.Lock()
        self.closed = False

    def put(self, batch: RecordBatch) -> None:
        with self._lock:
            self._q.append(batch)

    def poll(self) -> Optional[RecordBatch]:
        with self._lock:
            return self._q.popleft() if self._q else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)


class FeedbackSource(Source):
    """Iteration head: replays fed-back batches; ends after ``max_wait_ms``
    of quiet (the reference's iteration timeout)."""

    bounded = True  # terminates via timeout

    def __init__(self, queue: FeedbackQueue, max_wait_ms: int = 200):
        self.queue = queue
        self.max_wait_ms = max_wait_ms

    def create_splits(self, parallelism: int) -> List[SourceSplit]:
        return [SourceSplit(self, 0, 1)]

    def read_split(self, index: int, of: int) -> Iterator[StreamElement]:
        last_data = time.monotonic()
        while True:
            b = self.queue.poll()
            if b is not None:
                last_data = time.monotonic()
                yield b
                continue
            if (time.monotonic() - last_data) * 1000 > self.max_wait_ms:
                return
            time.sleep(0.001)
            yield RecordBatch({})  # keep the round-robin loop moving


class FeedbackSinkOperator(StreamOperator):
    """Iteration tail: pushes batches back to the head's queue."""

    def __init__(self, queue: FeedbackQueue, name: str = "iteration-tail"):
        self.queue = queue
        self.name = name

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch):
            self.queue.put(batch)
        return []

    def process_watermark(self, watermark) -> List[StreamElement]:
        return []
