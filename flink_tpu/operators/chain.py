"""Operator chain: fused execution of consecutive same-parallelism operators.

Analog of ``OperatorChain.java:88`` — chained outputs are direct calls, no
re-batching or serialization between chain members.  Control elements
(watermarks, processing time, end-of-input) are threaded through every member
in order, with each member's emissions delivered to the next *before* the
control element itself — the same ordering the reference's
``ChainingOutput`` + in-band control flow guarantees.
"""

from __future__ import annotations

from typing import Any, Dict, List

from flink_tpu.core.batch import RecordBatch, StreamElement, Watermark
from flink_tpu.core.functions import RuntimeContext
from flink_tpu.operators.base import StreamOperator


class ChainedOperator(StreamOperator):
    def __init__(self, operators: List[StreamOperator], name: str = "chain"):
        self.operators = operators
        self.name = name
        self.is_stateless = all(op.is_stateless for op in operators)
        self.forwards_watermarks = all(op.forwards_watermarks for op in operators)

    def open(self, ctx: RuntimeContext) -> None:
        super().open(ctx)
        for op in self.operators:
            op.open(ctx)

    def _feed(self, start: int, elements: List[StreamElement]) -> List[StreamElement]:
        """Push elements through chain members [start:]; returns chain output."""
        for op in self.operators[start:]:
            nxt: List[StreamElement] = []
            for el in elements:
                if isinstance(el, RecordBatch):
                    nxt.extend(op.process_batch(el))
                elif isinstance(el, Watermark):
                    nxt.extend(op.process_watermark(el))
                    if op.forwards_watermarks:
                        nxt.append(el)
                else:
                    nxt.append(el)
            elements = nxt
        return elements

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self._feed(0, [batch])

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        # Deliver to member i, push its fires through members i+1.., then move
        # the watermark itself to member i+1 (unless member i owns event time
        # and blocks it).  The executor appends the watermark downstream after
        # this returns, gated on self.forwards_watermarks.
        out: List[StreamElement] = []
        for i, op in enumerate(self.operators):
            out.extend(self._feed(i + 1, op.process_watermark(watermark)))
            if not op.forwards_watermarks:
                break
        return out

    def on_processing_time(self, timestamp_ms: int) -> List[StreamElement]:
        out: List[StreamElement] = []
        for i, op in enumerate(self.operators):
            out.extend(self._feed(i + 1, op.on_processing_time(timestamp_ms)))
        return out

    def end_input(self) -> List[StreamElement]:
        out: List[StreamElement] = []
        for i, op in enumerate(self.operators):
            out.extend(self._feed(i + 1, op.end_input()))
        return out

    def flush_pipeline(self) -> List[StreamElement]:
        """Driver idle hook: barrier every chained operator's pipeline."""
        out: List[StreamElement] = []
        for i, op in enumerate(self.operators):
            out.extend(self._feed(i + 1, op.flush_pipeline()))
        return out

    def on_latency_marker(self, marker):
        """Markers flow around user functions; a recording member (sink)
        consumes them, otherwise the marker continues downstream."""
        handled = False
        for op in self.operators:
            hook = getattr(op, "on_latency_marker", None)
            if hook is not None:
                hook(marker)
                handled = True
        return [] if handled else [marker]

    def prepare_snapshot_pre_barrier(self) -> List[StreamElement]:
        # getattr: operators are duck-typed to the StreamOperator protocol;
        # this hook is newer than some user/test operators, so absence
        # means "nothing to drain" (same guard as the task runtimes)
        out: List[StreamElement] = []
        for i, op in enumerate(self.operators):
            prep = getattr(op, "prepare_snapshot_pre_barrier", None)
            if prep is not None:
                out.extend(self._feed(i + 1, prep()))
        return out

    def snapshot_state(self) -> Dict[str, Any]:
        return {f"op{i}": op.snapshot_state() for i, op in enumerate(self.operators)}

    def restore_state(self, snapshot: Dict[str, Any]) -> None:
        if not snapshot:
            return
        if not any(f"op{i}" in snapshot for i in range(len(self.operators))):
            # flat KEYED snapshot (e.g. a bootstrapped savepoint from the
            # state processor API): hand it to the chain's single
            # keyed-stateful member (the one owning a keyed backend/index)
            keyed = [op for op in self.operators
                     if hasattr(op, "backend") or hasattr(op, "key_index")]
            if len(keyed) == 1:
                keyed[0].restore_state(snapshot)
                return
            if snapshot:
                raise ValueError(
                    f"chain {self.name!r}: flat snapshot cannot be attributed "
                    f"({len(keyed)} keyed-stateful members); write the "
                    f"savepoint with per-member op0/op1/... structure")
        for i, op in enumerate(self.operators):
            if f"op{i}" in snapshot:
                op.restore_state(snapshot[f"op{i}"])

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        # the reference's OperatorChain.notifyCheckpointComplete notifies
        # EVERY member: 2PC sinks commit, queryable views tag the
        # consistency point — a chained member must not miss it
        for op in self.operators:
            hook = getattr(op, "notify_checkpoint_complete", None)
            if hook is not None:
                hook(checkpoint_id)

    def close(self) -> None:
        for op in self.operators:
            op.close()
