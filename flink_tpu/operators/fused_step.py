"""One-dispatch fused megastep: scan over staged super-batches.

ROADMAP item 6 (the roofline push): the hot path still paid per-stage host
orchestration on every micro-batch — route, probe, window fold, and fire
detection as separate jitted dispatches (or separate native passes)
stitched together with Python glue.  Flink wins the same battle by
codegenning operator chains into one fused driver loop (PAPER.md L3 table
planner); our equivalent is XLA fusion plus a device-side ``lax.scan``:

- **Staging**: ``WindowAggOperator(superbatch=N)`` parks up to N
  micro-batches host-side instead of folding each one eagerly.  Watermarks
  that pass no window end leave the stage untouched (the same pure-assigner
  fire-boundary math the pipelined fast path uses decides the scan
  boundary), so steady-state traffic accumulates whole super-batches
  between fires.
- **Scan lane** (device-resident key probe active): the staged batches pad
  into one ``[N, B]`` block — sticky pow2 high-water on BOTH axes, the same
  compile-once discipline as the PR-6 exchange and the PR-7 probe table —
  and ONE jitted dispatch advances all N steps with ``lax.scan`` over
  donated state buffers.  Only the per-super-batch miss list (and the
  scalar miss total, the dispatch's sync point) returns to the host:
  steady-state warm-key super-batches cost exactly one dispatch.
- **Fused host pass** (CPU fallback tier / probe off): the staged batches
  concatenate into one contiguous block and the fused C probe+mirror fold
  (``wm_probe_update2``) runs ONCE over all of them — sharded across the
  native worker pool at a super-batch-calibrated shard count, bit-identical
  to the per-batch passes by the same ownership argument as PR-3's sharded
  probe.  Under scatter sync the device replica then catches up with ONE
  dispatch for the whole super-batch.

Bit-identity contract: with the mirror tier's f64/i64 precision, f32/int
contributions accumulate EXACTLY (a 24-bit mantissa summed in 53 bits),
so regrouping records across the warm/miss split or across batch
boundaries cannot change a digest — fire digests, snapshot bytes, and job
counters are identical fused-on vs fused-off (tests/test_fused_step.py).
Per-batch probe hit/miss telemetry MAY differ: a key first seen mid-super-
batch misses for the whole scan (the device table is immutable during it)
where the per-batch path would hit from the second batch on.

This module holds the host-side stager and the measured auto-calibration;
the jitted scan steps live on the operator (their jit caches key on the
instance) and the fused Pallas probe+fold kernel next to its probe twin in
``state/device_keyindex.py``.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Tuple

import numpy as np

#: staged super-batch row bound: staging is a latency/memory trade, and the
#: padded [N, B] scan block must stay far from HBM pressure — past this the
#: stage flushes regardless of depth
MAX_STAGED_ROWS = 1 << 21

#: auto-calibration's candidate depth (the measured A/B compares this
#: against the per-batch path; FLINK_TPU_SUPERBATCH overrides)
AUTO_DEPTH = 8

#: env override: "<N>" pins the depth (1 = off), "auto"/"" measures
_ENV = "FLINK_TPU_SUPERBATCH"

_calibrated_depth: Optional[int] = None
_calibrated_shards: Optional[int] = None
_calib_lock = threading.Lock()


class SuperBatchStage:
    """Host-side stage of pending micro-batches (keys, panes, values, B).

    Single-threaded by construction: batches are staged from wherever the
    hot stage runs (the pipeline worker, or the task thread inline) and
    flushed either there (depth reached) or on the task thread after a
    pipeline barrier — the two never overlap because the task thread only
    touches the stage after ``_HotPipeline.flush()`` returned."""

    __slots__ = ("batches", "rows")

    def __init__(self):
        self.batches: List[tuple] = []
        self.rows = 0

    def push(self, keys, panes, values, b: int) -> None:
        self.batches.append((keys, panes, values, b))
        self.rows += int(b)

    def take(self) -> List[tuple]:
        st, self.batches, self.rows = self.batches, [], 0
        return st

    def __bool__(self) -> bool:
        return bool(self.batches)

    def __len__(self) -> int:
        return len(self.batches)


def concat_staged(staged: List[tuple]) -> Tuple[np.ndarray, np.ndarray,
                                                object, int]:
    """Concatenate staged micro-batches into one contiguous super-batch
    (record order preserved — the bit-identity mechanism: key inserts and
    per-cell folds happen in exactly the order the per-batch path used)."""
    import jax

    if len(staged) == 1:
        keys, panes, values, b = staged[0]
        return keys, panes, values, int(b)
    keys = np.concatenate([s[0] for s in staged])
    panes = np.concatenate([s[1] for s in staged])
    treedef = jax.tree_util.tree_structure(staged[0][2])
    per = [jax.tree_util.tree_leaves(s[2]) for s in staged]
    cat = [np.concatenate([np.asarray(p[j]) for p in per])
           for j in range(len(per[0]))]
    values = jax.tree_util.tree_unflatten(treedef, cat)
    return keys, panes, values, int(sum(s[3] for s in staged))


# ---------------------------------------------------------------------------
# measured auto-calibration (the --superbatch 0 verdict)
# ---------------------------------------------------------------------------

def _super_shards_locked() -> int:
    """Body of :func:`calibrated_super_shards`; caller holds _calib_lock
    (or is the measurement path that already does)."""
    global _calibrated_shards
    if _calibrated_shards is not None:
        return _calibrated_shards
    from flink_tpu.native import get_lib
    from flink_tpu.state.native_mirror import (auto_shards,
                                               measure_fused_probe)
    auto = auto_shards()
    lib = get_lib()
    if auto <= 1 or lib is None or not hasattr(lib, "wm_create"):
        _calibrated_shards = 1
        return 1
    n_keys = 1 << 19
    B = AUTO_DEPTH << 17               # one super-batch worth of rows
    rng = np.random.default_rng(29)
    keys = np.ascontiguousarray(
        rng.integers(0, n_keys, 3 * B).astype(np.int64))
    vals = np.ascontiguousarray(rng.random(3 * B).astype(np.float32))
    timings = {s: measure_fused_probe(lib, s, n_keys, B, keys, vals)
               for s in (1, auto)}
    _calibrated_shards = min(timings, key=timings.get)
    return _calibrated_shards


def calibrated_super_shards() -> int:
    """Shard count for the SUPER-batch fused C pass, measured at super-batch
    size and cached process-wide.  ``calibrated_shards`` (PR-3) measures at
    one micro-batch, where thread-pool wake latency can eat the win on a
    small box; a super-batch amortizes that wake over N× the rows, so the
    verdict is re-measured at the size this lane actually dispatches."""
    if _calibrated_shards is not None:
        return _calibrated_shards
    with _calib_lock:
        return _super_shards_locked()


def calibrated_superbatch() -> int:
    """MEASURED super-batch depth, cached process-wide: does ONE fused C
    probe+fold over ``AUTO_DEPTH`` concatenated micro-batches (at the
    super-calibrated shard count) beat ``AUTO_DEPTH`` per-batch passes at
    the per-batch calibration?  The same measure-don't-assume pattern as
    ``calibrated_device_probe`` and the device-sync transport calibration.
    Returns the depth to stage (1 = staging off).  ``FLINK_TPU_SUPERBATCH``
    pins the verdict without measuring."""
    global _calibrated_depth
    if _calibrated_depth is not None:
        return _calibrated_depth
    with _calib_lock:
        if _calibrated_depth is not None:
            return _calibrated_depth
        env = os.environ.get(_ENV, "").strip().lower()
        if env and env != "auto":
            try:
                _calibrated_depth = max(1, int(env))
                return _calibrated_depth
            except ValueError:
                pass
        _calibrated_depth = _measure_superbatch()
        return _calibrated_depth


def _measure_superbatch() -> int:
    import time

    from flink_tpu.native import get_lib
    from flink_tpu.state.native_mirror import (calibrated_shards,
                                               measure_fused_probe)
    lib = get_lib()
    if lib is None or not hasattr(lib, "wm_create"):
        # numpy-mirror fallback: staging amortizes one bincount sweep per
        # pane over N batches — structurally a win, nothing to measure
        return AUTO_DEPTH
    # HEADLINE-realistic sizes: a toy super-batch fits the LLC and hides
    # the staging copies' real memory traffic (measured: a 5MB concat
    # reads "free", the bench's 42MB concat does not) — the verdict must
    # reflect the batch geometry the lane actually stages
    n_keys = 1 << 19
    B = 1 << 17
    N = AUTO_DEPTH
    rng = np.random.default_rng(31)
    keys = np.ascontiguousarray(
        rng.integers(0, n_keys, 3 * N * B).astype(np.int64))
    vals = np.ascontiguousarray(rng.random(3 * N * B).astype(np.float32))
    per_shards = calibrated_shards()
    # per-batch side: one B-row pass at the per-batch calibration, scaled
    # (the measurement harness keys the table warm either way)
    t_per = measure_fused_probe(lib, per_shards, n_keys, B,
                                keys[:3 * B], vals[:3 * B]) * N
    # super side END-TO-END: the staging CONCAT is part of the lane's real
    # cost (N-1 extra copies of every staged column) and on memory-bound
    # single-stream boxes it can eat the whole super-pass win — measure
    # it, don't assume it away.  NOTE: caller already holds _calib_lock —
    # the locked helper, not the public wrapper (Lock is not reentrant).
    t0 = time.perf_counter()
    seg_k = [keys[i * B:(i + 1) * B] for i in range(N)]
    seg_p = [np.zeros(B, np.int64) for _ in range(N)]
    seg_v = [vals[i * B:(i + 1) * B] for i in range(N)]
    np.concatenate(seg_k)
    np.concatenate(seg_p)
    np.concatenate(seg_v)
    t_concat = time.perf_counter() - t0
    t_super = measure_fused_probe(lib, _super_shards_locked(), n_keys,
                                  N * B, keys, vals) + t_concat
    # <=: a tie goes to staging — the C pass + concat is the measurable
    # part, and the per-batch Python glue it amortizes is upside on top
    return N if t_super <= t_per else 1


def _reset_calibration_for_tests() -> None:
    """Test seam: drop the process-wide verdicts (mirrors the pattern of
    transport/calibration resets in the existing suites)."""
    global _calibrated_depth, _calibrated_shards
    with _calib_lock:
        _calibrated_depth = None
        _calibrated_shards = None
