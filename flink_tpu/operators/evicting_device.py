"""Device fast lane for evicting windows: columnar raw elements on device.

``EvictingWindowOperator`` (the general lane) buffers rows host-side
because the evictor + apply function are arbitrary per-row Python.  But
the COMMON evictor cases need no row-level Python at all (VERDICT r3 next
#10): CountEvictor keeps the last n per (key, window) and TimeEvictor
keeps a trailing time span — both are vectorizable masks — and the
built-in aggregates (sum/min/max/count/avg) are segment combines.  This
operator keeps the raw elements as COLUMNAR DEVICE BUFFERS, evicts by
mask inside one jitted fire step, combines on device, and downloads only
the fired per-key results — the batched analog of
``EvictingWindowOperator.java:1`` with ``CountEvictor``/``TimeEvictor``.

Layout: ONE append-only element buffer (values [C], key slots [C], pane
ids [C], timestamps [C], write cursor) — append is a single
``dynamic_update_slice`` of the pow2-padded batch, so XLA compiles O(log)
shapes; arrival order IS buffer order (what CountEvictor ranks by).
Expired panes are dropped by an on-device stable compaction when the
buffer passes 3/4 occupancy.  Fires slice the window's panes by mask:
per-key reverse arrival ranks (count eviction) or per-key max-timestamp
spans (time eviction), then a masked segment combine.

Scope (falls back to the host lane otherwise): pane-based assigners,
event time, Count/Time evictors, aggregates with declared scatter kinds.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.core.batch import (LONG_MIN, RecordBatch, StreamElement,
                                  Watermark)
from flink_tpu.core.functions import AggregateFunction, RuntimeContext
from flink_tpu.operators.base import StreamOperator
from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex, make_key_index
from flink_tpu.windowing.assigners import WindowAssigner
from flink_tpu.windowing.evictors import CountEvictor, Evictor, TimeEvictor

from flink_tpu.ops.shapes import next_pow2 as _next_pow2

_SEG = {"add": jax.ops.segment_sum, "min": jax.ops.segment_min,
        "max": jax.ops.segment_max}


def device_evictor_supported(evictor: Optional[Evictor],
                             agg: AggregateFunction) -> bool:
    """True when the (evictor, aggregate) pair runs on the device lane."""
    return (isinstance(evictor, (CountEvictor, TimeEvictor))
            and agg.scatter_kind_leaves() is not None)


class DeviceEvictingWindowOperator(StreamOperator):
    """``window(...).evictor(Count/Time).aggregate(built-in)``, on device."""

    def __init__(self, assigner: WindowAssigner, evictor: Evictor,
                 agg: AggregateFunction, key_column: str,
                 value_column: str, output_column: str = "result",
                 allowed_lateness_ms: int = 0,
                 emit_window_bounds: bool = True,
                 initial_capacity: int = 1 << 12,
                 initial_key_capacity: int = 1 << 10,
                 name: str = "evicting-window-device"):
        if not hasattr(assigner, "pane_of"):
            raise ValueError("device evictor lane requires a pane-based "
                             "assigner (tumbling/sliding)")
        if not isinstance(evictor, (CountEvictor, TimeEvictor)):
            raise ValueError("device evictor lane supports CountEvictor and "
                             "TimeEvictor")
        kinds = agg.scatter_kind_leaves()
        if kinds is None:
            raise ValueError("device evictor lane requires an aggregate "
                             "with declared scatter kinds (built-ins)")
        self.assigner = assigner
        self.evictor = evictor
        self.agg = agg
        self.kinds = kinds
        self.spec = agg.acc_spec()
        self.key_column = key_column
        self.value_column = value_column
        self.output_column = output_column
        self.emit_window_bounds = emit_window_bounds
        self.lateness = int(allowed_lateness_ms)
        self.name = name
        self._C = _next_pow2(initial_capacity)
        self._K = _next_pow2(initial_key_capacity)
        self.key_index: Optional[KeyIndex | ObjectKeyIndex] = None
        self._vals = None          # f32 [C]
        self._keys = None          # i32 [C]  (K = invalid row)
        self._panes = None         # i32 [C], RELATIVE to _pane_epoch
        self._ts = None            # i32 [C], RELATIVE to _ts_epoch (ms)
        self._count = 0            # host write cursor (rows appended)
        # device columns are int32 (x64 off): absolute pane ids and
        # epoch-ms timestamps rebase against per-operator epochs fixed at
        # the first batch; snapshots store absolute values
        self._pane_epoch: Optional[int] = None
        self._ts_epoch: Optional[int] = None
        self.pane_base: Optional[int] = None
        self.max_pane: Optional[int] = None
        self.last_fired_window: Optional[int] = None
        self.watermark: int = LONG_MIN
        self.late_dropped = 0

    INVALID_PANE = -(1 << 31)     # int32 min: invalid row

    def open(self, ctx: RuntimeContext) -> None:
        pass

    # -------------------------------------------------------------- buffers
    def _alloc(self, C: int):
        return (jnp.zeros(C, jnp.float32),
                jnp.full(C, self._K, jnp.int32),
                jnp.full(C, self.INVALID_PANE, jnp.int32),
                jnp.zeros(C, jnp.int32))

    def _ensure(self, extra: int):
        if self._vals is None:
            while self._C < extra:
                self._C <<= 1
            self._vals, self._keys, self._panes, self._ts = \
                self._alloc(self._C)
            return
        if self._count + extra <= self._C:
            return
        # try on-device compaction of expired panes first
        if self.pane_base is not None:
            self._compact()
        while self._count + extra > self._C:
            self._C <<= 1
            nv, nk, npn, nts = self._alloc(self._C)
            half = self._C >> 1
            self._vals = nv.at[:half].set(self._vals)
            self._keys = nk.at[:half].set(self._keys)
            self._panes = npn.at[:half].set(self._panes)
            self._ts = nts.at[:half].set(self._ts)

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2, 3, 4))
    def _compact_step(self, vals, keys, panes, ts, lo):
        """Stable-partition live rows (pane >= lo) to the front, reset the
        rest to invalid — one device op, no download but the live count."""
        live = panes >= lo
        order = jnp.argsort(~live, stable=True)
        n_live = live.sum()
        idx = jnp.arange(vals.shape[0])
        keep = idx < n_live
        vals2 = jnp.where(keep, vals[order], 0.0)
        keys2 = jnp.where(keep, keys[order], self._K)
        panes2 = jnp.where(keep, panes[order], self.INVALID_PANE)
        ts2 = jnp.where(keep, ts[order], 0)
        return vals2, keys2, panes2, ts2, n_live

    def _compact(self):
        lo = self.pane_base - (self._pane_epoch or 0)
        self._vals, self._keys, self._panes, self._ts, n_live = \
            self._compact_step(self._vals, self._keys, self._panes,
                               self._ts, jnp.int32(lo))
        self._count = int(n_live)  # one scalar download

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2, 3, 4))
    def _append_step(self, vals, keys, panes, ts, new_v, new_k, new_p,
                     new_t, at):
        return (jax.lax.dynamic_update_slice(vals, new_v, (at,)),
                jax.lax.dynamic_update_slice(keys, new_k, (at,)),
                jax.lax.dynamic_update_slice(panes, new_p, (at,)),
                jax.lax.dynamic_update_slice(ts, new_t, (at,)))

    # ------------------------------------------------------------ batching
    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        keys = np.asarray(batch.column(self.key_column))
        if self.key_index is None:
            self.key_index = make_key_index(keys[0] if keys.ndim else keys,
                                            capacity_hint=self._K)
        if batch.timestamps is None:
            raise ValueError("evicting windows require timestamps")
        ts = np.asarray(batch.timestamps, np.int64)
        panes = self.assigner.pane_of(ts)
        # lateness gate (same formula as WindowAggOperator)
        if self.watermark != LONG_MIN:
            p0, p1 = int(panes.min()), int(panes.max())
            cand = (np.arange(p0, p1 + 1, dtype=np.int64)
                    if p1 - p0 < 64 else np.unique(panes))
            is_late = np.asarray(
                [self.assigner.last_window_end_of_pane(int(p)) - 1
                 + self.lateness <= self.watermark for p in cand.tolist()])
            if is_late.any():
                live = ~np.isin(panes, cand[is_late])
                self.late_dropped += int(np.count_nonzero(~live))
                if not live.any():
                    return []
                batch = batch.select(live)
                keys = np.asarray(batch.column(self.key_column))
                ts = ts[live]
                panes = panes[live]
        slots = self.key_index.lookup_or_insert(keys)
        if self.key_index.num_keys > self._K:
            self._grow_keys()
        pmin, pmax = int(panes.min()), int(panes.max())
        self.pane_base = pmin if self.pane_base is None \
            else min(self.pane_base, pmin)
        self.max_pane = pmax if self.max_pane is None \
            else max(self.max_pane, pmax)
        B = len(batch)
        Bp = _next_pow2(B, 64)
        self._ensure(Bp)
        if self._pane_epoch is None:
            self._pane_epoch = pmin
            self._ts_epoch = int(ts.min())
        vals = np.zeros(Bp, np.float32)
        vals[:B] = np.asarray(batch.column(self.value_column), np.float32)
        kp = np.full(Bp, self._K, np.int32)
        kp[:B] = slots
        pp = np.full(Bp, self.INVALID_PANE, np.int32)
        pp[:B] = panes - self._pane_epoch
        tp = np.zeros(Bp, np.int32)
        tp[:B] = ts - self._ts_epoch
        # guarded: the evicting lane's hot dispatch runs under the same
        # device-health watchdog as the window hot path (a wedge here
        # quarantines the tier and FAILS this operator — raw-element
        # device buffers have no host twin tier to degrade onto, so the
        # restart strategy recovers from the last checkpoint instead)
        from flink_tpu.runtime import device_health
        geom = (int(self._vals.shape[0]), Bp)
        fresh_geom = geom != getattr(self, "_last_dispatch_geom", None)
        self._last_dispatch_geom = geom
        self._vals, self._keys, self._panes, self._ts = \
            device_health.guarded_dispatch(
                lambda: self._append_step(
                    self._vals, self._keys, self._panes, self._ts,
                    jnp.asarray(vals), jnp.asarray(kp), jnp.asarray(pp),
                    jnp.asarray(tp), jnp.int32(self._count)),
                mb=(vals.nbytes + kp.nbytes + pp.nbytes + tp.nbytes) / 1e6,
                label=f"{getattr(self, 'name', 'evicting-window')}"
                      ".append_step",
                compile_grace=fresh_geom)
        self._count += Bp
        return []

    def _grow_keys(self):
        # key ids only live in the buffer's key column; capacity is virtual
        while self._K < self.key_index.num_keys:
            self._K <<= 1

    # ---------------------------------------------------------------- time
    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        self.watermark = max(self.watermark, watermark.timestamp)
        return self._advance(self.watermark)

    def end_input(self) -> List[StreamElement]:
        return self._advance(2 ** 62)

    def _advance(self, now: int) -> List[StreamElement]:
        if self._vals is None or self.pane_base is None:
            return []
        a = self.assigner
        lo_w = a.windows_of_pane(self.pane_base)[0]
        hi_w = a.windows_of_pane(self.max_pane)[1]
        start = (self.last_fired_window + 1
                 if self.last_fired_window is not None else lo_w)
        out: List[StreamElement] = []
        fired_any = None
        for w in range(max(start, lo_w), hi_w + 1):
            if a.window_bounds(w).max_timestamp > now:
                break
            out.extend(self._fire_window(w))
            fired_any = w
        if fired_any is not None and (self.last_fired_window is None
                                      or fired_any > self.last_fired_window):
            self.last_fired_window = fired_any
        # retention: panes behind every un-expired window drop at compaction
        p = self.pane_base
        while (p <= self.max_pane
               and a.last_window_end_of_pane(p) - 1 + self.lateness <= now):
            p += 1
        self.pane_base = p
        return out

    # --------------------------------------------------------------- fires
    @partial(jax.jit, static_argnums=(0, 5, 6))
    def _fire_step(self, vals, keys, panes, ts, k_active: int, n_rows: int,
                   lo, hi):
        """Evict + combine for one window, entirely on device.  Static:
        key capacity bound and the buffer slice bound (pow2-quantized);
        the window's pane range rides as TRACED scalars (one compile
        serves every window)."""
        vals = jax.lax.slice_in_dim(vals, 0, n_rows)
        keys = jax.lax.slice_in_dim(keys, 0, n_rows)
        panes = jax.lax.slice_in_dim(panes, 0, n_rows)
        ts = jax.lax.slice_in_dim(ts, 0, n_rows)
        return self._fire_core(vals, keys, panes, ts, k_active, lo, hi)

    def _fire_core(self, vals, keys, panes, ts, k_active: int, lo, hi):
        K = k_active
        in_win = (panes >= lo) & (panes <= hi) & (keys < K)
        kmask = jnp.where(in_win, keys, K)
        # group by key, arrival order preserved within groups
        order = jnp.argsort(kmask, stable=True)
        sk = kmask[order]
        sv = vals[order]
        st = ts[order]
        idx = jnp.arange(sk.shape[0])
        is_start = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
        group_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(is_start, idx, 0))
        pos = idx - group_start
        counts = jax.ops.segment_sum(in_win.astype(jnp.int32), kmask, K + 1)
        gsize = counts[jnp.clip(sk, 0, K)]
        valid = sk < K
        if isinstance(self.evictor, CountEvictor):
            keep = valid & ((gsize - pos) <= self.evictor.n)
        else:  # TimeEvictor: trailing span from each key's newest element
            tmax = jax.ops.segment_max(
                jnp.where(in_win, ts, jnp.int32(-(1 << 31) + 1)), kmask,
                K + 1)
            keep = valid & (st >= tmax[jnp.clip(sk, 0, K)]
                            - jnp.int32(self.evictor.window_ms))
        lifted = jax.tree_util.tree_leaves(self.agg.lift(sv))
        seg_ids = jnp.where(keep, sk, K)
        acc = []
        for leaf, kind in zip(lifted, self.kinds):
            acc.append(_SEG[kind](
                jnp.where(self._lift_mask(keep, leaf), leaf,
                          self._identity_like(leaf, kind)),
                seg_ids, K + 1)[:K])
        kept_counts = jax.ops.segment_sum(keep.astype(jnp.int32), seg_ids,
                                          K + 1)[:K]
        result = self.agg.get_result(self.spec.unflatten(acc))
        return kept_counts > 0, result

    @staticmethod
    def _lift_mask(keep, leaf):
        return keep.reshape(keep.shape + (1,) * (leaf.ndim - 1))

    @staticmethod
    def _identity_like(leaf, kind):
        if kind == "add":
            return jnp.zeros((), leaf.dtype)
        if jnp.issubdtype(leaf.dtype, jnp.integer):
            info = jnp.iinfo(leaf.dtype)
            return jnp.asarray(info.max if kind == "min" else info.min,
                               leaf.dtype)
        return jnp.asarray(jnp.inf if kind == "min" else -jnp.inf,
                           leaf.dtype)

    def _fire_window(self, w: int) -> List[StreamElement]:
        if self.key_index is None or self._vals is None:
            return []
        first, last = self.assigner.window_panes(w)
        if last < self.pane_base or first > self.max_pane:
            return []
        ka = _next_pow2(max(self.key_index.num_keys, 1), 64)
        nrows = _next_pow2(max(self._count, 1), 64)
        ep = self._pane_epoch or 0
        mask, result = self._fire_step(self._vals, self._keys, self._panes,
                                       self._ts, ka, min(nrows, self._C),
                                       jnp.int32(first - ep),
                                       jnp.int32(last - ep))
        mask_np = np.asarray(mask)[: self.key_index.num_keys]
        idx = np.flatnonzero(mask_np)
        if idx.size == 0:
            return []
        res_np = jax.tree_util.tree_map(
            lambda a: np.asarray(a)[idx], result)
        win = self.assigner.window_bounds(w)
        keys = np.asarray(self.key_index.reverse_keys())[idx]
        cols: Dict[str, Any] = {self.key_column: keys}
        if isinstance(res_np, dict):
            cols.update(res_np)
        else:
            cols[self.output_column] = res_np
        if self.emit_window_bounds:
            cols["window_start"] = np.broadcast_to(np.int64(win.start),
                                                   (idx.size,))
            cols["window_end"] = np.broadcast_to(np.int64(win.end),
                                                 (idx.size,))
        ts = np.broadcast_to(np.int64(win.max_timestamp), (idx.size,))
        return [RecordBatch(cols, timestamps=ts)]

    # ----------------------------------------------------------- snapshots
    def snapshot_state(self) -> Dict[str, Any]:
        snap: Dict[str, Any] = {
            "pane_base": self.pane_base, "max_pane": self.max_pane,
            "last_fired_window": self.last_fired_window,
            "watermark": self.watermark, "late_dropped": self.late_dropped,
        }
        if self.key_index is not None:
            snap["key_index"] = self.key_index.snapshot()
            snap["key_index_kind"] = type(self.key_index).__name__
        if self._vals is not None and self._count:
            n = self._count
            ep = self._pane_epoch or 0
            te = self._ts_epoch or 0
            panes = np.asarray(self._panes[:n]).astype(np.int64) + ep
            lo = (self.pane_base if self.pane_base is not None
                  else self.INVALID_PANE + 1 + ep)
            live = (np.asarray(self._panes[:n]) != self.INVALID_PANE) \
                & (panes >= lo)
            snap["vals"] = np.asarray(self._vals[:n])[live]
            snap["keys"] = np.asarray(self._keys[:n])[live]
            snap["panes"] = panes[live]
            snap["ts"] = (np.asarray(self._ts[:n]).astype(np.int64)
                          + te)[live]
        return snap

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.pane_base = snap["pane_base"]
        self.max_pane = snap["max_pane"]
        self.last_fired_window = snap["last_fired_window"]
        self.watermark = snap["watermark"]
        self.late_dropped = snap.get("late_dropped", 0)
        self._vals = None
        self._count = 0
        if "key_index" in snap:
            if snap["key_index_kind"] == "ObjectKeyIndex":
                self.key_index = ObjectKeyIndex.restore(snap["key_index"])
            else:
                self.key_index = KeyIndex.restore(snap["key_index"])
            self._grow_keys()
        self._pane_epoch = None
        self._ts_epoch = None
        if "vals" in snap and len(snap["vals"]):
            n = len(snap["vals"])
            self._pane_epoch = int(np.min(snap["panes"]))
            self._ts_epoch = int(np.min(snap["ts"]))
            Bp = _next_pow2(n, 64)
            self._ensure(Bp)
            vals = np.zeros(Bp, np.float32)
            vals[:n] = snap["vals"]
            kp = np.full(Bp, self._K, np.int32)
            kp[:n] = snap["keys"]
            pp = np.full(Bp, self.INVALID_PANE, np.int32)
            pp[:n] = np.asarray(snap["panes"]) - self._pane_epoch
            tp = np.zeros(Bp, np.int32)
            tp[:n] = np.asarray(snap["ts"]) - self._ts_epoch
            self._vals, self._keys, self._panes, self._ts = \
                self._append_step(self._vals, self._keys, self._panes,
                                  self._ts, jnp.asarray(vals),
                                  jnp.asarray(kp), jnp.asarray(pp),
                                  jnp.asarray(tp), jnp.int32(0))
            self._count = Bp
