"""KeyedProcessFunction operator: user logic + keyed state + timers.

Analog of ``KeyedProcessOperator`` running a ``KeyedProcessFunction``
(``flink-streaming-java/.../api/operators/KeyedProcessOperator.java``),
batched: the user function receives a whole ``RecordBatch`` plus a context
exposing vectorized keyed state (``flink_tpu/state/heap.py``) and batched
timer registration (``flink_tpu/runtime/timers.py``); ``on_timer_batch``
receives ALL timers firing at one watermark advance as arrays.

Timer snapshots store raw keys (not backend-local slot ids) so they survive
key-group redistribution on rescale — the same property the reference gets
from key-grouped timer queues (``InternalTimerServiceImpl.java:50``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from flink_tpu.core.batch import LONG_MIN, RecordBatch, StreamElement, Watermark
from flink_tpu.core.functions import RichFunction, RuntimeContext
from flink_tpu.operators.base import (StreamOperator, current_checkpoint_id,
                                      snapshot_is_incremental)
from flink_tpu.runtime.timers import InternalTimerService
from flink_tpu.state.heap import HeapKeyedStateBackend


class KeyedProcessFunction(RichFunction):
    """Batched ``KeyedProcessFunction`` contract.

    process_batch(ctx, batch)        -> elements to emit (list or None)
    on_timer_batch(ctx, slots, ts)   -> elements to emit for fired timers
    """

    def process_batch(self, ctx: "Context", batch: RecordBatch):
        raise NotImplementedError

    def on_timer_batch(self, ctx: "OnTimerContext", slots: np.ndarray,
                       timestamps: np.ndarray):
        return None


class TimerServiceView:
    """User-facing timer registration surface (``TimerService`` analog)."""

    def __init__(self, timers: InternalTimerService):
        self._timers = timers

    def current_watermark(self) -> int:
        return self._timers.current_watermark

    def register_event_time_timers(self, slots, timestamps) -> None:
        self._timers.register_event_time(slots, timestamps)

    def register_processing_time_timers(self, slots, timestamps) -> None:
        self._timers.register_processing_time(slots, timestamps)

    def delete_event_time_timers(self, slots, timestamps) -> None:
        self._timers.delete_event_time(slots, timestamps)

    def delete_processing_time_timers(self, slots, timestamps) -> None:
        self._timers.delete_processing_time(slots, timestamps)


class Context:
    """Per-batch context: state access + timers + key metadata."""

    def __init__(self, op: "KeyedProcessOperator", slots: Optional[np.ndarray]):
        self._op = op
        self.slots = slots  # dense slot per row of the current batch
        self.timer_service = TimerServiceView(op.timers)
        self._side: list = []

    def side_output(self, tag, columns, timestamps=None) -> None:
        """Emit a batch to the named side output (``Context.output`` analog).
        ``tag`` is an OutputTag or its name string."""
        from flink_tpu.core.batch import OutputTag, TaggedBatch

        name = tag.name if isinstance(tag, OutputTag) else str(tag)
        self._side.append(TaggedBatch(
            name, RecordBatch({k: np.asarray(v) for k, v in columns.items()},
                              timestamps=timestamps)))

    def state(self, descriptor):
        return self._op.backend.get_state(descriptor)

    def keys_of(self, slots: np.ndarray) -> np.ndarray:
        return self._op.backend.slot_keys(slots)

    @property
    def current_watermark(self) -> int:
        return self._op.timers.current_watermark


class OnTimerContext(Context):
    pass


class KeyedProcessOperator(StreamOperator):
    def __init__(self, fn: KeyedProcessFunction, key_column: str,
                 name: str = "keyed-process", backend=None):
        self.fn = fn
        self.key_column = key_column
        self.name = name
        #: configurable keyed backend (state.backend): heap / native spill /
        #: changelog wrapper — same vectorized State API either way
        self.backend = backend if backend is not None \
            else HeapKeyedStateBackend()
        self.timers = InternalTimerService()
        #: incremental checkpoints: ship changelog-suffix increments when
        #: the backend supports them (runtime enables this per job)
        self.incremental_state = False

    def open(self, ctx: RuntimeContext) -> None:
        super().open(ctx)
        self.backend.max_parallelism = ctx.max_parallelism
        # budgeted backends claim their share of the slot's managed memory
        mm = getattr(ctx, "memory_manager", None)
        if mm is not None and hasattr(self.backend, "reserve_managed"):
            self.backend.reserve_managed(
                mm, owner=f"{ctx.task_name}[{ctx.subtask_index}]")
        self.fn.open(ctx)

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        slots = self.backend.key_slots(np.asarray(batch.column(self.key_column)))
        batch = batch.with_keys(slots, batch.key_groups)
        ctx = Context(self, slots)
        out = self.fn.process_batch(ctx, batch)
        return _normalize(out) + ctx._side

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        slots, _ns, ts = self.timers.advance_watermark(watermark.timestamp)
        if slots.size == 0:
            return []
        ctx = OnTimerContext(self, None)
        out = self.fn.on_timer_batch(ctx, slots, ts)
        return _normalize(out) + ctx._side

    def on_processing_time(self, timestamp_ms: int) -> List[StreamElement]:
        slots, _ns, ts = self.timers.advance_processing_time(timestamp_ms)
        if slots.size == 0:
            return []
        ctx = OnTimerContext(self, None)
        out = self.fn.on_timer_batch(ctx, slots, ts)
        return _normalize(out) + ctx._side

    # -- checkpointing -------------------------------------------------------
    def _timer_snapshot(self) -> Dict[str, Any]:
        tsnap = self.timers.snapshot()
        # slot ids -> raw keys for rescale-safety
        for part in ("event", "proc"):
            slots = tsnap[part]["slots"]
            tsnap[part] = dict(tsnap[part])
            tsnap[part]["keys"] = (self.backend.slot_keys(slots)
                                   if slots.size else np.zeros(0, np.int64))
            del tsnap[part]["slots"]
        return tsnap

    def snapshot_state(self) -> Dict[str, Any]:
        cid = current_checkpoint_id()
        if self.incremental_state and cid is not None \
                and snapshot_is_incremental() \
                and hasattr(self.backend, "snapshot_increment"):
            inc = self.backend.snapshot_increment(cid)
            if inc is not None:
                # timers ride in extras (small, shipped whole every cut:
                # the applier overwrites them onto the resolved base)
                inc["extras"] = {"timers": self._timer_snapshot()}
                return inc
            # fall through: full cut (the backend froze the position, so
            # confirmation still advances the suffix base to this cut)
        snap = self.backend.snapshot()
        snap["timers"] = self._timer_snapshot()
        return snap

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        if hasattr(self.backend, "notify_checkpoint_complete"):
            self.backend.notify_checkpoint_complete(checkpoint_id)
        super().notify_checkpoint_complete(checkpoint_id)

    def restore_state(self, snap: Dict[str, Any]) -> None:
        tsnap = snap.get("timers")
        self.backend.restore({k: v for k, v in snap.items() if k != "timers"})
        if tsnap is not None:
            from flink_tpu.core import keygroups

            ctx = getattr(self, "ctx", None)
            my_range = (keygroups.compute_key_group_range(
                ctx.max_parallelism, ctx.parallelism, ctx.subtask_index)
                if ctx is not None else None)
            restored = {"watermark": tsnap.get("watermark", LONG_MIN)}
            for part in ("event", "proc"):
                p = dict(tsnap[part])
                keys = np.asarray(p.pop("keys"))
                if keys.size and my_range is not None and ctx.parallelism > 1:
                    # rescale: a split snapshot carries every subtask's timers;
                    # keep only keys in this subtask's key-group range
                    kg = keygroups.assign_to_key_group(
                        keygroups.hash_keys(keys), ctx.max_parallelism)
                    mine = (kg >= my_range.start) & (kg <= my_range.end)
                    keys = keys[mine]
                    p["ns"] = np.asarray(p["ns"])[mine]
                    p["ts"] = np.asarray(p["ts"])[mine]
                p["slots"] = (self.backend.key_slots(keys).astype(np.int64)
                              if keys.size else np.zeros(0, np.int64))
                restored[part] = p
            self.timers.restore(restored)

    def close(self) -> None:
        self.fn.close()
        # releases the backend's managed-memory claim + spill resources
        if hasattr(self.backend, "close"):
            self.backend.close()

    # -- rescale hooks (StateAssignmentOperation analog) ---------------------
    @staticmethod
    def split_snapshot(snap: Dict[str, Any], max_parallelism: int,
                       new_parallelism: int) -> List[Dict[str, Any]]:
        """Each part carries the full timer set; ``restore_state`` filters by
        the restoring subtask's key-group range."""
        from flink_tpu.state.redistribute import split_keyed_snapshot
        return split_keyed_snapshot(snap, HeapKeyedStateBackend.row_fields(snap),
                                    max_parallelism, new_parallelism)

    @staticmethod
    def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Scale-down merge: keyed rows via the shared redistribution path,
        timers unioned across every part (they are not per-slot row fields)."""
        from flink_tpu.state.redistribute import merge_keyed_snapshots
        fields = HeapKeyedStateBackend.row_fields(snaps[0]) if snaps else []
        merged = merge_keyed_snapshots(snaps, fields)
        timer_parts = [s["timers"] for s in snaps if "timers" in s]
        if timer_parts:
            union: Dict[str, Any] = {
                "watermark": max(t.get("watermark", LONG_MIN)
                                 for t in timer_parts)}
            for part in ("event", "proc"):
                union[part] = {
                    f: np.concatenate([np.asarray(t[part][f])
                                       for t in timer_parts])
                    for f in ("keys", "ns", "ts")}
            merged["timers"] = union
        return merged


def _normalize(out) -> List[StreamElement]:
    if out is None:
        return []
    if isinstance(out, RecordBatch):
        return [out]
    return [o for o in out if o is not None and (not o.is_batch() or len(o))]
