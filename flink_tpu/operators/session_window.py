"""SessionWindowOperator — gap-based merging windows on keyed streams.

Analog of the reference's merging-window path
(``WindowOperator.java:311-411`` + ``MergingWindowSet.java``): session
windows merge whenever their extended intervals overlap, and merging windows
must merge their accumulators (the reason the reference requires
``AggregateFunction.merge`` — ``AggregateFunction.java:114``).

TPU-first split of the work (SURVEY §7.3 "Sessions"):

- **Batch-local sessionization is vectorized**: sort rows by (key slot, ts),
  detect gap boundaries with one array comparison, fold each batch-local
  session's values with ufunc scatters (fast path) or per-segment combines —
  per-record Python never runs.
- **Merge decisions stay on host**: each *batch-local session* (not each
  record — orders of magnitude fewer) is merged into the per-key interval
  set, combining accumulator rows on overlap.  This is exactly the
  reference's host-side ``MergingWindowSet`` bookkeeping with
  ``mergeNamespaces`` replaced by a row-level monoid combine.
- Accumulators live in dense ``[cap, *leaf]`` row tables with a free list —
  promotable to device arrays; fire-time ``get_result`` is vectorized over
  all sessions firing at one watermark advance.

Allowed lateness follows the reference's semantics: a fired session is
retained until ``end + lateness`` passes the watermark; a late record inside
that horizon merges in and re-fires the (possibly larger) session; records
beyond it are dropped and counted.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from flink_tpu.core.batch import (LONG_MIN, RecordBatch, StreamElement,
                                  TaggedBatch, Watermark)
from flink_tpu.core.functions import AggregateFunction, RuntimeContext
from flink_tpu.operators.base import StreamOperator
from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex, make_key_index
from flink_tpu.windowing.assigners import SessionGap


class _SessionStore:
    """Dense session-row tables + per-key interval sets.

    Rows: key_slot/start/end/active/fired arrays + acc leaf tables.  The
    per-key dict maps key slot -> list of active row ids (usually length 1).
    """

    def __init__(self, spec):
        self.spec = spec
        self.key_slot = np.zeros(0, np.int64)
        self.start = np.zeros(0, np.int64)
        self.end = np.zeros(0, np.int64)      # exclusive: last_ts + gap
        self.active = np.zeros(0, bool)
        self.fired = np.zeros(0, bool)        # fired but retained (lateness)
        self.leaves = [np.zeros((0,) + s, d)
                       for s, d in zip(spec.leaf_shapes, spec.leaf_dtypes)]
        #: row -> distinct-value set (DISTINCT aggregates only; the
        #: reference's distinct-state MapView per window namespace)
        self.sets: List[Optional[set]] = []
        self.by_key: Dict[int, List[int]] = {}
        self._free: List[int] = []

    def _grow(self, extra: int) -> None:
        old = self.key_slot.size
        cap = max(old + extra, max(64, old * 2))
        def gr(a, fill=0):
            n = np.full((cap,) + a.shape[1:], fill, a.dtype)
            n[:old] = a
            return n
        self.key_slot, self.start, self.end = (gr(self.key_slot), gr(self.start),
                                               gr(self.end))
        self.active, self.fired = gr(self.active, False), gr(self.fired, False)
        self.leaves = [gr(l) for l in self.leaves]
        for i, init in enumerate(self.spec.leaf_inits):
            self.leaves[i][old:] = init
        self.sets.extend([None] * (cap - old))
        self._free.extend(range(cap - 1, old - 1, -1))

    def alloc(self) -> int:
        if not self._free:
            self._grow(1)
        return self._free.pop()

    def release(self, row: int) -> None:
        self.active[row] = False
        self.fired[row] = False
        for leaf, init in zip(self.leaves, self.spec.leaf_inits):
            leaf[row] = init
        self.sets[row] = None
        self._free.append(row)

    def acc_of(self, row: int) -> Tuple[np.ndarray, ...]:
        return tuple(leaf[row] for leaf in self.leaves)

    def set_acc(self, row: int, acc) -> None:
        for leaf, a in zip(self.leaves, acc):
            leaf[row] = a


class SessionWindowOperator(StreamOperator):
    """``key_by(k).window(EventTimeSessionWindows(gap)).aggregate(agg)``."""

    def __init__(self, session: SessionGap, agg: AggregateFunction,
                 key_column: str,
                 value_selector: Optional[Callable] = None,
                 value_column: Optional[str] = None,
                 allowed_lateness_ms: int = 0,
                 output_column: str = "result",
                 emit_window_bounds: bool = True,
                 name: str = "session-window-agg",
                 late_output_tag: Optional[str] = None,
                 distinct_specs: Optional[Dict[str, str]] = None,
                 distinct_column: Optional[str] = None):
        #: sideOutputLateData: beyond-lateness records ship as TaggedBatch
        #: instead of dropping (the drop counter stays untouched for them)
        self.gap = int(session.gap_ms)
        self.is_event_time = session.is_event_time
        self.agg = agg
        self.key_column = key_column
        if value_selector is not None:
            self._select = value_selector
        elif value_column is not None:
            self._select = lambda cols: cols[value_column]
        else:
            self._select = lambda cols: cols
        self.lateness = int(allowed_lateness_ms)
        self.output_column = output_column
        self.emit_window_bounds = emit_window_bounds
        self.name = name
        self.spec = agg.acc_spec()
        self.kinds = agg.scatter_kind_leaves()
        self.key_index: Optional[KeyIndex | ObjectKeyIndex] = None
        self.store = _SessionStore(self.spec)
        self.late_output_tag = late_output_tag
        #: DISTINCT aggregates over merging windows (the PARITY r2 SESSION
        #: DISTINCT gap): per-session value SETS ride the interval merge —
        #: out_name -> func (COUNT/SUM/AVG/MIN/MAX) over ``distinct_column``
        self.distinct_specs = distinct_specs or {}
        self.distinct_column = distinct_column
        if self.distinct_specs and distinct_column is None:
            raise ValueError("distinct_specs needs distinct_column")
        self.watermark: int = LONG_MIN
        self._proc_time: int = LONG_MIN
        self.late_dropped: int = 0

    def open(self, ctx: RuntimeContext) -> None:
        super().open(ctx)

    # ------------------------------------------------------------ ingest
    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        late_out: List[StreamElement] = []
        keys = np.asarray(batch.column(self.key_column))
        if self.is_event_time:
            if batch.timestamps is None:
                raise ValueError(
                    "session windows need event timestamps "
                    "(assign_timestamps_and_watermarks upstream)")
            ts = np.asarray(batch.timestamps, np.int64)
        else:
            # processing time: stamp arrival time (the reference's
            # ProcessingTimeSessionWindows assigns currentProcessingTime)
            from flink_tpu.utils import clock
            now = clock.now_ms() if self._proc_time == LONG_MIN \
                else self._proc_time
            ts = np.full(len(batch), now, np.int64)
        if self.key_index is None:
            self.key_index = make_key_index(keys[0])
        slots = self.key_index.lookup_or_insert(keys).astype(np.int64)
        values = self._select(batch.columns)

        # ---- beyond-lateness drop, evaluated on the POST-MERGE window like
        # the reference (isWindowLate after mergeWindows): a candidate-late
        # record survives if it overlaps a still-retained session, because the
        # merged window then inherits that session's (unexpired) cleanup time.
        if self.is_event_time and self.watermark != LONG_MIN:
            late = (ts + self.gap + self.lateness) <= self.watermark
            if late.any():
                for i in np.nonzero(late)[0]:
                    t0, t1 = int(ts[i]), int(ts[i]) + self.gap
                    for r in self.store.by_key.get(int(slots[i]), ()):
                        if self.store.start[r] < t1 and t0 < self.store.end[r]:
                            late[i] = False
                            break
                if late.any() and self.late_output_tag is not None:
                    late_out.append(TaggedBatch(self.late_output_tag,
                                                batch.select(late)))
                elif late.any():
                    self.late_dropped += int(late.sum())
                keep = ~late
                slots, ts = slots[keep], ts[keep]
                values = jax.tree_util.tree_map(
                    lambda c: np.asarray(c)[keep], values)
                if not slots.size:
                    return late_out

        # ---- vectorized batch-local sessionization + fold (the mesh
        # subclass reroutes the FOLD through the device exchange)
        bounds = (self._session_bounds(slots, ts)
                  if self.distinct_specs else None)
        b_key, b_start, b_end, accs = self._sessionize(slots, ts, values,
                                                       bounds)
        n_sess = b_key.size
        bsets = (self._batch_distinct_sets(values, bounds)
                 if self.distinct_specs else None)

        # ---- host merge of batch sessions into the per-key interval sets
        st = self.store
        refire: set = set()  # rows needing an immediate late re-fire
        for i in range(n_sess):
            k = int(b_key[i])
            start, end = int(b_start[i]), int(b_end[i])
            acc = tuple(a[i] for a in accs)
            dset = set(bsets[i]) if bsets is not None else None
            rows = st.by_key.get(k)
            if rows is None:
                rows = []
                st.by_key[k] = rows
            absorbed_fired = False
            survivors = []
            for r in rows:
                # overlap of [start,end) with stored [st.start[r], st.end[r])
                if st.start[r] < end and start < st.end[r]:
                    acc = tuple(np.asarray(x) for x in self.agg.combine_leaves(
                        st.acc_of(r), acc))
                    if dset is not None and st.sets[r]:
                        dset |= st.sets[r]
                    start = min(start, int(st.start[r]))
                    end = max(end, int(st.end[r]))
                    # merging a fired (or refire-pending) session → re-fire
                    absorbed_fired |= bool(st.fired[r]) or (r in refire)
                    refire.discard(r)
                    st.release(r)
                else:
                    survivors.append(r)
            row = st.alloc()
            st.key_slot[row], st.start[row], st.end[row] = k, start, end
            st.active[row] = True
            st.fired[row] = False
            st.set_acc(row, acc)
            st.sets[row] = dset
            survivors.append(row)
            st.by_key[k] = survivors
            if absorbed_fired and self.is_event_time \
                    and end <= self.watermark:
                refire.add(row)

        out: List[StreamElement] = list(late_out)
        if refire:
            rows = np.asarray(sorted(refire), np.int64)
            out.extend(self._emit_rows(rows))
            st.fired[rows] = True  # re-fired: don't emit again at next advance
        return out

    # ------------------------------------------------- batch sessionization
    def _session_bounds(self, slots: np.ndarray, ts: np.ndarray):
        """Sort by (key slot, ts) and find batch-local session boundaries:
        a new session starts on key change or when the next record's window
        [t, t+gap) does NOT overlap the previous one's — records exactly
        ``gap`` apart stay separate, same boundary as the interval-overlap
        merge and the reference's ``TimeWindow.intersects`` (maxTimestamp =
        end - 1).  Returns (order, s_slots, s_ts, sess_id, firsts, lasts)
        with the sorted arrays included (callers need them too)."""
        order = np.lexsort((ts, slots))
        s_slots, s_ts = slots[order], ts[order]
        new_key = np.concatenate([[True], s_slots[1:] != s_slots[:-1]])
        gap_break = np.concatenate([[True],
                                    (s_ts[1:] - s_ts[:-1]) >= self.gap])
        sess_first = new_key | gap_break
        sess_id = np.cumsum(sess_first) - 1          # batch-local session id
        firsts = np.nonzero(sess_first)[0]
        lasts = np.concatenate([firsts[1:] - 1, [len(s_ts) - 1]])
        return order, s_slots, s_ts, sess_id, firsts, lasts

    def _sessionize(self, slots: np.ndarray, ts: np.ndarray, values,
                    bounds=None):
        """(b_key, b_start, b_end, acc leaf list) for this batch's local
        sessions — host fold (``ufunc.reduceat`` over the sorted runs for
        declared kinds, per-segment combine otherwise).  ``bounds``: the
        precomputed ``_session_bounds`` result (avoids a second sort when
        the caller needed it too)."""
        order, s_slots, s_ts, sess_id, firsts, lasts = \
            bounds if bounds is not None else self._session_bounds(slots, ts)
        lifted = jax.tree_util.tree_leaves(self.agg.lift(values))
        lifted = [np.asarray(l)[order] for l in lifted]
        n_sess = int(firsts.size)
        b_key = s_slots[firsts]
        b_start = s_ts[firsts]
        b_end = s_ts[lasts] + self.gap               # exclusive end

        accs = [np.empty((n_sess,) + sh, dt) for sh, dt in
                zip(self.spec.leaf_shapes, self.spec.leaf_dtypes)]
        for a, init in zip(accs, self.spec.leaf_inits):
            a[:] = init
        if self.kinds is not None:
            from flink_tpu.core.functions import SCATTER_UFUNCS
            # rows are session-contiguous after the sort: one reduceat per
            # leaf folds every session (ufunc.at is ~50x slower)
            for a, l, kind in zip(accs, lifted, self.kinds):
                a[:] = SCATTER_UFUNCS[kind].reduceat(
                    l.astype(a.dtype, copy=False), firsts, axis=0)
        else:
            for i, b in enumerate(firsts):
                e = int(lasts[i]) + 1
                acc = tuple(a[i] for a in accs)
                for j in range(b, e):
                    acc = tuple(np.asarray(x) for x in self.agg.combine_leaves(
                        acc, tuple(l[j] for l in lifted)))
                for a, v in zip(accs, acc):
                    a[i] = v
        return b_key, b_start, b_end, accs

    def _batch_distinct_sets(self, values, bounds) -> List[set]:
        """Per batch-local session: the SET of distinct-column values
        (``bounds`` = the shared ``_session_bounds`` result)."""
        order, _ss, _st, _sid, firsts, lasts = bounds
        dv = np.asarray(values[self.distinct_column])[order]
        return [set(dv[f:l + 1].tolist()) for f, l in zip(firsts, lasts)]

    # ------------------------------------------------------------- firing
    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        self.watermark = watermark.timestamp
        if not self.is_event_time:
            return []
        return self._fire_due(self.watermark)

    def on_processing_time(self, timestamp_ms: int) -> List[StreamElement]:
        # monotone clamp: a backward-stepped clock (chaos ClockSkew) must
        # neither rewind session gap progress nor close sessions early
        self._proc_time = max(self._proc_time, timestamp_ms)
        if self.is_event_time:
            return []
        return self._fire_due(self._proc_time)

    def end_input(self) -> List[StreamElement]:
        if self.is_event_time:
            return []  # MAX_WATERMARK already fired everything
        from flink_tpu.core.batch import LONG_MAX
        return self._fire_due(LONG_MAX)

    def _fire_due(self, t: int) -> List[StreamElement]:
        st = self.store
        due = st.active & ~st.fired & (st.end <= t)
        out = (self._emit_rows(np.nonzero(due)[0]) if due.any() else [])
        st.fired[due] = True
        # cleanup past the lateness horizon (clearAllState analog)
        dead = st.active & st.fired & (st.end + self.lateness <= t)
        for r in np.nonzero(dead)[0]:
            k = int(st.key_slot[r])
            rows = st.by_key.get(k)
            if rows is not None:
                rows = [x for x in rows if x != r]
                if rows:
                    st.by_key[k] = rows
                else:
                    del st.by_key[k]
            st.release(int(r))
        return out

    def _emit_rows(self, rows: np.ndarray) -> List[StreamElement]:
        if rows.size == 0:
            return []
        st = self.store
        order = np.argsort(st.end[rows], kind="stable")
        rows = rows[order]
        acc = self.spec.unflatten([leaf[rows] for leaf in st.leaves])
        result = self.agg.get_result(acc)
        raw_keys = np.asarray(self.key_index.reverse_keys())[st.key_slot[rows]]
        cols: Dict[str, Any] = {self.key_column: raw_keys}
        if isinstance(result, dict):
            cols.update({k: np.asarray(v) for k, v in result.items()})
        else:
            cols[self.output_column] = np.asarray(result)
        for out, func in self.distinct_specs.items():
            vals = []
            for r in rows.tolist():
                s = st.sets[r] or ()
                if func == "COUNT":
                    vals.append(len(s))
                elif func == "SUM":
                    vals.append(float(sum(s)))
                elif func == "AVG":
                    vals.append(float(sum(s)) / len(s) if s else 0.0)
                elif func == "MIN":
                    vals.append(min(s) if s else np.nan)
                else:
                    vals.append(max(s) if s else np.nan)
            cols[out] = np.asarray(vals)
        if self.emit_window_bounds:
            cols["window_start"] = st.start[rows].copy()
            cols["window_end"] = st.end[rows].copy()
        # emission timestamp = window end - 1 (reference: window.maxTimestamp)
        return [RecordBatch(cols, timestamps=st.end[rows] - 1)]

    # -------------------------------------------------------- checkpointing
    def snapshot_state(self) -> Dict[str, Any]:
        st = self.store
        live = np.nonzero(st.active)[0]
        raw = (np.asarray(self.key_index.reverse_keys())[st.key_slot[live]]
               if self.key_index is not None else np.zeros(0, np.int64))
        snap = {
            "session_keys": raw,                  # raw keys → rescale-safe
            "start": st.start[live].copy(),
            "end": st.end[live].copy(),
            "fired": st.fired[live].copy(),
            "acc": tuple(leaf[live].copy() for leaf in st.leaves),
            "watermark": self.watermark,
            "late_dropped": self.late_dropped,
        }
        if self.distinct_specs:
            snap["sets"] = [sorted(st.sets[r]) if st.sets[r] else []
                            for r in live.tolist()]
        return snap

    def restore_state(self, snap: Dict[str, Any]) -> None:
        keys = np.asarray(snap["session_keys"])
        self.watermark = int(snap.get("watermark", LONG_MIN))
        self.late_dropped = int(snap.get("late_dropped", 0))
        self.key_index = None
        self.store = _SessionStore(self.spec)
        if keys.size == 0:
            return
        ctx = getattr(self, "ctx", None)
        keep = np.ones(keys.size, bool)
        if ctx is not None and ctx.parallelism > 1:
            from flink_tpu.core import keygroups
            kg = keygroups.assign_to_key_group(keygroups.hash_keys(keys),
                                               ctx.max_parallelism)
            rng = keygroups.compute_key_group_range(
                ctx.max_parallelism, ctx.parallelism, ctx.subtask_index)
            keep = (kg >= rng.start) & (kg <= rng.end)
        sel = np.nonzero(keep)[0]
        keys = keys[sel]
        if keys.size == 0:
            return
        starts = np.asarray(snap["start"])[sel]
        ends = np.asarray(snap["end"])[sel]
        fireds = np.asarray(snap["fired"])[sel]
        accs = tuple(np.asarray(a)[sel] for a in snap["acc"])
        sets = ([snap["sets"][i] for i in sel.tolist()]
                if "sets" in snap else None)
        self.key_index = make_key_index(keys[0])
        slots = self.key_index.lookup_or_insert(keys).astype(np.int64)
        st = self.store
        for i in range(keys.size):
            row = st.alloc()
            st.key_slot[row] = slots[i]
            st.start[row], st.end[row] = starts[i], ends[i]
            st.fired[row] = fireds[i]
            st.active[row] = True
            st.set_acc(row, tuple(a[i] for a in accs))
            if sets is not None:
                st.sets[row] = set(sets[i]) if sets[i] else None
            st.by_key.setdefault(int(slots[i]), []).append(row)

    @staticmethod
    def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Scale-down: sessions are plain per-row records — concatenate."""
        live = [s for s in snaps if "session_keys" in s
                and len(np.asarray(s["session_keys"]))]
        if not live:
            return dict(snaps[0]) if snaps else {}
        merged = dict(live[0])
        merged["session_keys"] = np.concatenate(
            [np.asarray(s["session_keys"]) for s in live])
        for f in ("start", "end", "fired"):
            merged[f] = np.concatenate([np.asarray(s[f]) for s in live])
        merged["acc"] = tuple(
            np.concatenate([np.asarray(s["acc"][i]) for s in live])
            for i in range(len(live[0]["acc"])))
        if any("sets" in s for s in live):
            merged["sets"] = [x for s in live
                              for x in s.get(
                                  "sets",
                                  [[]] * len(np.asarray(s["session_keys"])))]
        # MIN, not max: under an unaligned rescale cut the parts sit at
        # different watermarks, and the behind part's persisted in-flight
        # elements replay with their own watermark progression (PR-5
        # ordering) — a max here would mark them late on arrival, records
        # an unfaulted run accepts.  The ahead part's already-fired
        # sessions keep their fired flags, so the lower restart point
        # cannot double-fire them.
        merged["watermark"] = min(int(s.get("watermark", LONG_MIN))
                                  for s in live)
        merged["late_dropped"] = sum(int(s.get("late_dropped", 0))
                                     for s in live)
        return merged

    @staticmethod
    def split_snapshot(snap: Dict[str, Any], max_parallelism: int,
                       new_parallelism: int) -> List[Dict[str, Any]]:
        """Rescale: route session rows by their key's key group."""
        from flink_tpu.core import keygroups
        keys = np.asarray(snap["session_keys"])
        kg = (keygroups.assign_to_key_group(keygroups.hash_keys(keys),
                                            max_parallelism)
              if keys.size else np.zeros(0, np.int64))
        out = []
        for i, rng in enumerate(
                keygroups.key_group_ranges(max_parallelism, new_parallelism)):
            sel = (kg >= rng.start) & (kg <= rng.end)
            sub = dict(snap)
            sub["session_keys"] = keys[sel]
            for f in ("start", "end", "fired"):
                sub[f] = np.asarray(snap[f])[sel]
            sub["acc"] = tuple(np.asarray(a)[sel] for a in snap["acc"])
            if "sets" in snap:
                sub["sets"] = [snap["sets"][j]
                               for j in np.nonzero(sel)[0].tolist()]
            if i > 0:
                # job-level counter: carried by part 0 only, or a later
                # merge_snapshots would sum it new_parallelism times
                sub["late_dropped"] = 0
            out.append(sub)
        return out
