"""SQL runtime operators: joins, changelog aggregation, Top-N, dedup,
mini-batch bundling.

Analogs of the blink table runtime (``flink-table-runtime-blink``):
``StreamingJoinOperator`` (regular equi-join), ``GroupAggFunction`` with
retraction (``+I/-U/+U/-D`` changelog rows), ``AppendOnlyTopNFunction`` /
``RankOperator``, ``DeduplicateKeepFirstRow/KeepLastRow`` functions, and the
``bundle/`` mini-batch operators.  Batched columnar: each structure keys on
vectorized column ops, not per-record state probes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import (LONG_MIN, RecordBatch, StreamElement,
                                  Watermark)
from flink_tpu.operators.base import StreamOperator
from flink_tpu.operators.joins import _join_pairs, _merge_columns


class SqlJoinOperator(StreamOperator):
    """Bounded-table equi-join (``StreamExecJoin`` over bounded inputs):
    both sides buffer; the join emits once at end-of-input — batch SQL
    semantics.  ``how``: inner / left / right / full."""

    is_two_input = True

    def __init__(self, left_key: str, right_key: str, how: str = "inner",
                 right_rename: Optional[Dict[str, str]] = None,
                 left_columns: Optional[List[str]] = None,
                 right_columns: Optional[List[str]] = None,
                 name: str = "sql-join"):
        self.left_key = left_key
        self.right_key = right_key
        self.how = how
        self.right_rename = right_rename or {}
        #: declared schemas: outer joins must emit null-filled columns for an
        #: EMPTY side, which cannot be inferred from received batches
        self.left_columns = left_columns
        self.right_columns = right_columns
        self.name = name
        self._left: List[RecordBatch] = []
        self._right: List[RecordBatch] = []
        self._ended = 0

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        if len(batch):
            (self._left if input_index == 0 else self._right).append(batch)
        return []

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)

    def end_input(self) -> List[StreamElement]:
        # called once per vertex after ALL inputs ended
        l = RecordBatch.concat(self._left) if self._left else None
        r = RecordBatch.concat(self._right) if self._right else None
        self._left, self._right = [], []
        return self._join(l, r)

    def _rename_right(self, cols: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return {self.right_rename.get(k, k): v for k, v in cols.items()}

    def _join(self, l: Optional[RecordBatch],
              r: Optional[RecordBatch]) -> List[StreamElement]:
        nl = len(l) if l is not None else 0
        nr = len(r) if r is not None else 0
        parts: List[Dict[str, np.ndarray]] = []
        li = ri = np.zeros(0, np.int64)
        if nl and nr:
            li, ri = _join_pairs(np.asarray(l.column(self.left_key)),
                                 np.asarray(r.column(self.right_key)))
        lcols = (self.left_columns if self.left_columns is not None
                 else (list(l.columns) if l is not None else []))
        rcols = (self.right_columns if self.right_columns is not None
                 else (list(r.columns) if r is not None else []))
        if li.size:
            cols = {k: np.asarray(v)[li] for k, v in l.columns.items()}
            cols.update(self._rename_right(
                {k: np.asarray(v)[ri] for k, v in r.columns.items()}))
            parts.append(cols)
        if self.how in ("left", "full") and nl:
            unmatched = np.setdiff1d(np.arange(nl), li)
            if unmatched.size:
                cols = {k: np.asarray(v)[unmatched]
                        for k, v in l.columns.items()}
                cols.update(self._rename_right(
                    {k: np.full(unmatched.size, None, object) for k in rcols}))
                parts.append(cols)
        if self.how in ("right", "full") and nr:
            unmatched = np.setdiff1d(np.arange(nr), ri)
            if unmatched.size:
                cols = {k: np.full(unmatched.size, None, object)
                        for k in lcols}
                cols.update(self._rename_right(
                    {k: np.asarray(v)[unmatched]
                     for k, v in r.columns.items()}))
                parts.append(cols)
        if not parts:
            return []
        batches = [RecordBatch(c) for c in parts]
        return [RecordBatch.concat(batches) if len(batches) > 1 else batches[0]]

    def snapshot_state(self) -> Dict[str, Any]:
        def pack(bs):
            if not bs:
                return None
            b = RecordBatch.concat(bs)
            return {k: np.asarray(v) for k, v in b.columns.items()}
        return {"left": pack(self._left), "right": pack(self._right)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._left = ([RecordBatch(snap["left"])] if snap.get("left") else [])
        self._right = ([RecordBatch(snap["right"])] if snap.get("right") else [])


class ChangelogGroupAggOperator(StreamOperator):
    """Non-windowed group aggregate emitting a CHANGELOG (retraction) stream
    (``GroupAggFunction`` analog): every batch updates the affected groups
    and emits ``+I`` for new groups, ``-U`` (old value) + ``+U`` (new value)
    for changed ones.  The ``op`` column carries the change kind."""

    def __init__(self, key_column: str, agg_columns: Dict[str, Tuple[str, str]],
                 name: str = "changelog-group-agg"):
        """agg_columns: out_name -> (input column, how in sum/count/min/max)."""
        self.key_column = key_column
        self.agg_columns = agg_columns
        self.name = name
        #: key -> {out_name: value}
        self._groups: Dict[Any, Dict[str, float]] = {}

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        keys = np.asarray(batch.column(self.key_column))
        uniq, inv = np.unique(keys, return_inverse=True)
        # per-batch partial per group
        partials: Dict[str, np.ndarray] = {}
        for out, (col, how) in self.agg_columns.items():
            vals = (np.ones(len(batch)) if col is None
                    else np.asarray(batch.column(col), np.float64))
            if how in ("sum", "count"):
                partials[out] = np.bincount(inv, weights=vals,
                                            minlength=len(uniq))
            elif how == "min":
                agg = np.full(len(uniq), np.inf)
                np.minimum.at(agg, inv, vals)
                partials[out] = agg
            elif how == "max":
                agg = np.full(len(uniq), -np.inf)
                np.maximum.at(agg, inv, vals)
                partials[out] = agg
            else:
                raise ValueError(f"unsupported changelog aggregate {how!r}")
        out_rows: List[Dict[str, Any]] = []
        for gi, key in enumerate(uniq.tolist()):
            old = self._groups.get(key)
            if old is None:
                new = {out: float(partials[out][gi])
                       for out in self.agg_columns}
                self._groups[key] = new
                out_rows.append({"op": "+I", self.key_column: key, **new})
            else:
                new = {}
                for out, (col, how) in self.agg_columns.items():
                    p = float(partials[out][gi])
                    new[out] = (old[out] + p if how in ("sum", "count")
                                else (min(old[out], p) if how == "min"
                                      else max(old[out], p)))
                if new != old:
                    out_rows.append({"op": "-U", self.key_column: key, **old})
                    out_rows.append({"op": "+U", self.key_column: key, **new})
                    self._groups[key] = new
        if not out_rows:
            return []
        cols = {c: np.asarray([r[c] for r in out_rows]) for c in out_rows[0]}
        return [RecordBatch(cols)]

    def snapshot_state(self) -> Dict[str, Any]:
        return {"groups": dict(self._groups)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._groups = dict(snap.get("groups", {}))


class TopNOperator(StreamOperator):
    """Streaming Top-N per partition (``AppendOnlyTopNFunction`` /
    ``StreamExecRank`` analog): keeps the best ``n`` rows per partition key,
    emits changelog rows (``+I`` entering, ``-D`` leaving) as ranks change;
    ``end_input`` emits the final ranked table (rank column included)."""

    def __init__(self, n: int, partition_column: Optional[str],
                 order_column: str, ascending: bool = False,
                 emit_changelog: bool = True, name: str = "top-n"):
        self.n = n
        self.partition_column = partition_column
        self.order_column = order_column
        self.ascending = ascending
        self.emit_changelog = emit_changelog
        self.name = name
        #: partition -> list of (sort_value, seq, row) kept sorted best-first
        self._tops: Dict[Any, List[Tuple[Any, int, dict]]] = {}
        self._seq = 0

    def _better(self, a, b) -> bool:
        return a < b if self.ascending else a > b

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        rows = batch.to_rows()
        out_rows: List[Dict[str, Any]] = []
        for row in rows:
            part = (row[self.partition_column]
                    if self.partition_column else None)
            top = self._tops.setdefault(part, [])
            val = row[self.order_column]
            self._seq += 1
            if len(top) < self.n or self._better(val, top[-1][0]):
                top.append((val, self._seq, row))
                top.sort(key=lambda e: (e[0], e[1]),
                         reverse=not self.ascending)
                if self.emit_changelog:
                    out_rows.append({"op": "+I", **row})
                if len(top) > self.n:
                    _, _, evicted = top.pop()
                    if self.emit_changelog:
                        out_rows.append({"op": "-D", **evicted})
        if not out_rows or not self.emit_changelog:
            return []
        cols = {c: np.asarray([r.get(c) for r in out_rows])
                for c in out_rows[0]}
        return [RecordBatch(cols)]

    def end_input(self) -> List[StreamElement]:
        out_rows = []
        for part in sorted(self._tops, key=lambda p: (p is None, p)):
            for rank, (_v, _s, row) in enumerate(self._tops[part], start=1):
                out_rows.append({**row, "rank": rank, "op": "final"})
        if not out_rows:
            return []
        cols = {c: np.asarray([r.get(c) for r in out_rows])
                for c in out_rows[0]}
        return [RecordBatch(cols)]

    def snapshot_state(self) -> Dict[str, Any]:
        return {"tops": {k: list(v) for k, v in self._tops.items()},
                "seq": self._seq}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._tops = {k: list(v) for k, v in snap.get("tops", {}).items()}
        self._seq = snap.get("seq", 0)


class DeduplicateOperator(StreamOperator):
    """Deduplication per key (``DeduplicateKeepFirstRow/KeepLastRow``):
    ``keep='first'`` emits a key's first row immediately and drops the rest;
    ``keep='last'`` retains the latest row per key and emits the final table
    at end-of-input (streaming updates would be a changelog; bounded gives
    batch semantics)."""

    def __init__(self, key_column: str, keep: str = "first",
                 order_column: Optional[str] = None, name: str = "deduplicate"):
        if keep not in ("first", "last"):
            raise ValueError("keep must be 'first' or 'last'")
        self.key_column = key_column
        self.keep = keep
        self.order_column = order_column
        self.name = name
        self._seen: Dict[Any, dict] = {}
        self._order: Dict[Any, Any] = {}

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        keys = np.asarray(batch.column(self.key_column))
        if self.keep == "first":
            # vectorized: first occurrence in-batch AND not seen before
            _, first_idx = np.unique(keys, return_index=True)
            mask = np.zeros(len(batch), bool)
            mask[first_idx] = True
            unseen = np.asarray([k not in self._seen for k in keys.tolist()])
            mask &= unseen
            for k in keys[mask].tolist():
                self._seen[k] = {}
            return [batch.select(mask)] if mask.any() else []
        # keep == 'last': retain latest (by order column or arrival)
        rows = batch.to_rows()
        for i, row in enumerate(rows):
            k = keys[i].item() if isinstance(keys[i], np.generic) else keys[i]
            if self.order_column is not None:
                o = row[self.order_column]
                if k in self._order and not o >= self._order[k]:
                    continue
                self._order[k] = o
            self._seen[k] = row
        return []

    def end_input(self) -> List[StreamElement]:
        if self.keep == "first" or not self._seen:
            return []
        rows = list(self._seen.values())
        cols = {c: np.asarray([r.get(c) for r in rows]) for c in rows[0]}
        return [RecordBatch(cols)]

    def snapshot_state(self) -> Dict[str, Any]:
        return {"seen": dict(self._seen), "order": dict(self._order)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._seen = dict(snap.get("seen", {}))
        self._order = dict(snap.get("order", {}))


class SortLimitOperator(StreamOperator):
    """Bounded ORDER BY / LIMIT inside a query pipeline (subquery result
    semantics): buffer, sort at end of input, truncate."""

    def __init__(self, order_by: List[Tuple[str, bool]],
                 limit: Optional[int], name: str = "sort-limit"):
        self.order_by = list(order_by)
        self.limit = limit
        self.name = name
        self._buf: List[RecordBatch] = []

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch):
            self._buf.append(batch)
        return []

    def end_input(self) -> List[StreamElement]:
        if not self._buf:
            return []
        b = RecordBatch.concat(self._buf)
        self._buf = []
        order = np.arange(len(b))
        for name, asc in reversed(self.order_by):
            col = np.asarray(b.column(name))[order]
            o = np.argsort(col, kind="stable")
            if not asc:
                o = o[::-1]
            order = order[o]
        if self.limit is not None:
            order = order[: self.limit]
        return [b.take(order)]

    def snapshot_state(self) -> Dict[str, Any]:
        if not self._buf:
            return {}
        b = RecordBatch.concat(self._buf)
        return {"cols": {k: np.asarray(v) for k, v in b.columns.items()},
                "ts": None if b.timestamps is None else np.asarray(b.timestamps)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        if snap.get("cols"):
            self._buf = [RecordBatch(snap["cols"], timestamps=snap.get("ts"))]


class MiniBatchOperator(StreamOperator):
    """Bundle small batches into bigger ones before an expensive stateful
    operator (``MiniBatch`` bundle operators, ``operators/bundle/``):
    flushes at ``max_rows`` OR on any watermark/barrier boundary — control
    elements must never overtake their data."""

    is_stateless = True

    def __init__(self, max_rows: int = 16_384, name: str = "mini-batch"):
        self.max_rows = max_rows
        self.name = name
        self._buf: List[RecordBatch] = []
        self._rows = 0

    def _flush(self) -> List[StreamElement]:
        if not self._buf:
            return []
        out = [RecordBatch.concat(self._buf)] if len(self._buf) > 1 \
            else [self._buf[0]]
        self._buf = []
        self._rows = 0
        return out

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        self._buf.append(batch)
        self._rows += len(batch)
        if self._rows >= self.max_rows:
            return self._flush()
        return []

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        return self._flush()

    def end_input(self) -> List[StreamElement]:
        return self._flush()

    def snapshot_state(self) -> Dict[str, Any]:
        # barrier boundary: flush downstream is not possible from snapshot;
        # persist the bundle instead (reference finishes bundles pre-barrier)
        if not self._buf:
            return {}
        b = RecordBatch.concat(self._buf)
        return {"bundle": {k: np.asarray(v) for k, v in b.columns.items()},
                "ts": None if b.timestamps is None else np.asarray(b.timestamps)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        if snap.get("bundle"):
            self._buf = [RecordBatch(snap["bundle"], timestamps=snap.get("ts"))]
            self._rows = sum(len(b) for b in self._buf)


class OverAggSpec:
    """One aggregate column of an OVER window (``StreamExecOverAggregate``).

    ``func``: SUM/COUNT/AVG/MIN/MAX/ROW_NUMBER; ``in_col``: pre-projected
    numeric input column (None for COUNT(*)/ROW_NUMBER).  Frame: both bounds
    None = unbounded preceding; ``rows`` = ROWS n PRECEDING AND CURRENT ROW;
    ``range_ms`` = RANGE INTERVAL n PRECEDING AND CURRENT ROW.  ``is_rows``
    picks per-row vs peer-inclusive semantics for unbounded frames
    (``RowTimeRowsUnboundedPrecedingFunction`` vs ``RowTimeRange...``)."""

    __slots__ = ("out_name", "func", "in_col", "rows", "range_ms", "is_rows")

    def __init__(self, out_name: str, func: str, in_col: Optional[str],
                 rows: Optional[int] = None, range_ms: Optional[int] = None,
                 is_rows: bool = False):
        self.out_name = out_name
        self.func = func
        self.in_col = in_col
        self.rows = rows
        self.range_ms = range_ms
        self.is_rows = is_rows


def _sliding_window(padded: np.ndarray, width: int) -> np.ndarray:
    from numpy.lib.stride_tricks import sliding_window_view
    return sliding_window_view(padded, width)


class OverAggregateOperator(StreamOperator):
    """Per-partition running aggregates over time-ordered rows — the
    ``StreamExecOverAggregate`` analog (reference:
    ``flink-table-planner-blink/.../stream/StreamExecOverAggregate.java``,
    runtime ``RowTime{Range,Rows}{Unbounded,Bounded}PrecedingFunction``).

    Event-time mode buffers rows per partition and, on each watermark,
    emits every buffered row with ``ts <= watermark`` in timestamp order,
    each extended with its frame aggregates (vectorized: cumulative sums /
    sliding-window reductions over the sorted flush, not a per-row state
    probe).  Late rows (ts at or below the last watermark) are dropped, as
    in the reference.  Arrival mode (no time attribute) emits immediately
    in arrival order.
    """

    def __init__(self, specs: List[OverAggSpec],
                 partition_column: Optional[str],
                 event_time: bool = True, name: str = "sql-over-agg"):
        self.specs = specs
        self.partition_column = partition_column
        self.event_time = event_time
        self.name = name
        if not event_time and any(s.range_ms is not None for s in specs):
            raise ValueError("RANGE frames need an event-time ORDER BY")
        # per-partition-key state:
        self._pending: Dict[Any, List[RecordBatch]] = {}
        # spec index -> key -> running acc (unbounded) or None
        self._accs: List[Dict[Any, Any]] = [dict() for _ in specs]
        # spec index -> key -> (ts_buf, val_buf) tail kept for bounded frames
        self._tails: List[Dict[Any, Any]] = [dict() for _ in specs]
        self._last_wm = LONG_MIN
        self._dropped_late = 0

    # ------------------------------------------------------------- ingest
    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        if not self.event_time:
            return self._emit(batch, order=np.arange(len(batch)))
        ts = np.asarray(batch.timestamps)
        fresh = ts > self._last_wm
        if not fresh.all():
            self._dropped_late += int((~fresh).sum())
            batch = batch.select(fresh)
            if len(batch) == 0:
                return []
        if self.partition_column is None:
            self._pending.setdefault(None, []).append(batch)
            return []
        keys = np.asarray(batch.columns[self.partition_column])
        uniq, inv = np.unique(keys, return_inverse=True)
        for i, k in enumerate(uniq.tolist()):
            self._pending.setdefault(k, []).append(batch.select(inv == i))
        return []

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        out = self._flush(watermark.timestamp)
        self._last_wm = max(self._last_wm, watermark.timestamp)
        return out

    def end_input(self) -> List[StreamElement]:
        return self._flush(None)

    def _flush(self, up_to: Optional[int]) -> List[StreamElement]:
        out: List[StreamElement] = []
        for key in list(self._pending):
            merged = RecordBatch.concat(self._pending[key])
            ts = np.asarray(merged.timestamps)
            if up_to is None:
                ready, rest = merged, None
            else:
                mask = ts <= up_to
                if not mask.any():
                    continue
                ready = merged.select(mask)
                rest = merged.select(~mask) if not mask.all() else None
            if rest is not None and len(rest):
                self._pending[key] = [rest]
            else:
                del self._pending[key]
            order = np.argsort(np.asarray(ready.timestamps), kind="stable")
            out.extend(self._emit(ready, order, key=key))
        return out

    # ------------------------------------------------------------ compute
    def _emit(self, batch: RecordBatch, order: np.ndarray,
              key: Any = None) -> List[StreamElement]:
        batch = batch.take(order)
        m = len(batch)
        ts = (np.asarray(batch.timestamps) if batch.timestamps is not None
              else np.arange(m, dtype=np.int64))
        cols = dict(batch.columns)
        if not self.event_time and self.partition_column is not None:
            # arrival mode still aggregates per partition value
            keys = np.asarray(cols[self.partition_column])
            uniq, inv = np.unique(keys, return_inverse=True)
            if len(uniq) > 1:
                parts = [self._emit(batch.select(inv == i), np.arange(int((inv == i).sum())), key=k)
                         for i, k in enumerate(uniq.tolist())]
                return [RecordBatch.concat([p for part in parts for p in part])]
            key = uniq[0].item() if len(uniq) else None
        for i, spec in enumerate(self.specs):
            vals = (np.asarray(cols[spec.in_col], np.float64)
                    if spec.in_col is not None else np.ones(m, np.float64))
            if spec.func == "ROW_NUMBER":
                start = self._accs[i].get(key, 0)
                cols[spec.out_name] = start + np.arange(1, m + 1, dtype=np.int64)
                self._accs[i][key] = start + m
            elif spec.rows is not None:
                cols[spec.out_name] = self._rows_frame(i, spec, key, vals)
            elif spec.range_ms is not None:
                cols[spec.out_name] = self._range_frame(i, spec, key, ts, vals)
            else:
                cols[spec.out_name] = self._unbounded(i, spec, key, ts, vals)
        return [RecordBatch(cols, batch.timestamps, batch.key_ids,
                            batch.key_groups)]

    def _unbounded(self, i: int, spec: OverAggSpec, key: Any, ts, vals):
        """UNBOUNDED PRECEDING: running accumulator carried across flushes;
        RANGE flavor gives every peer group (equal ts) the group's total."""
        func = spec.func
        if func in ("SUM", "AVG", "COUNT"):
            ps, pc = self._accs[i].get(key, (0.0, 0))
            cum_s = ps + np.cumsum(vals)
            cum_c = pc + np.arange(1, len(vals) + 1, dtype=np.int64)
            self._accs[i][key] = (float(cum_s[-1]), int(cum_c[-1]))
        elif func == "MIN":
            prev = self._accs[i].get(key, np.inf)
            cum_s = np.minimum.accumulate(np.minimum(vals, prev))
            self._accs[i][key] = float(cum_s[-1])
            cum_c = None
        elif func == "MAX":
            prev = self._accs[i].get(key, -np.inf)
            cum_s = np.maximum.accumulate(np.maximum(vals, prev))
            self._accs[i][key] = float(cum_s[-1])
            cum_c = None
        else:
            raise ValueError(f"unsupported OVER aggregate {func}")
        if not spec.is_rows and self.event_time:
            # peer-inclusive: each row reads the value at its LAST peer
            last_peer = np.searchsorted(ts, ts, side="right") - 1
            cum_s = cum_s[last_peer]
            if cum_c is not None:
                cum_c = cum_c[last_peer]
        if func == "COUNT":
            return cum_c.astype(np.int64)
        if func == "AVG":
            return cum_s / cum_c
        return cum_s

    def _rows_frame(self, i: int, spec: OverAggSpec, key: Any, vals):
        """ROWS n PRECEDING AND CURRENT ROW: NaN-padded sliding window over
        (kept tail ++ new rows); the tail keeps the last n values."""
        n = spec.rows
        tail = self._tails[i].get(key)
        prev = tail if tail is not None else np.empty(0, np.float64)
        allv = np.concatenate([prev, vals])
        # windows of width n+1 ending at each NEW row
        width = n + 1
        padded = np.concatenate([np.full(width - 1, np.nan), allv])
        win = _sliding_window(padded, width)[len(prev):]
        self._tails[i][key] = allv[-n:] if n > 0 else np.empty(0, np.float64)
        func = spec.func
        if func == "SUM":
            return np.nansum(win, axis=1)
        if func == "COUNT":
            return (~np.isnan(win)).sum(axis=1).astype(np.int64)
        if func == "AVG":
            return np.nansum(win, axis=1) / (~np.isnan(win)).sum(axis=1)
        if func == "MIN":
            return np.nanmin(win, axis=1)
        if func == "MAX":
            return np.nanmax(win, axis=1)
        raise ValueError(f"unsupported OVER aggregate {func}")

    def _range_frame(self, i: int, spec: OverAggSpec, key: Any, ts, vals):
        """RANGE r PRECEDING AND CURRENT ROW over event time, peer-inclusive;
        the tail keeps rows within r of the newest emitted timestamp."""
        r = spec.range_ms
        tail = self._tails[i].get(key)
        pts, pvs = tail if tail is not None else (np.empty(0, np.int64),
                                                 np.empty(0, np.float64))
        all_ts = np.concatenate([pts, np.asarray(ts, np.int64)])
        all_vs = np.concatenate([pvs, vals])
        lo = np.searchsorted(all_ts, np.asarray(ts, np.int64) - r, side="left")
        hi = np.searchsorted(all_ts, np.asarray(ts, np.int64), side="right")
        keep = all_ts > (all_ts[-1] - r if len(all_ts) else 0)
        self._tails[i][key] = (all_ts[keep], all_vs[keep])
        func = spec.func
        if func in ("SUM", "AVG", "COUNT"):
            cum = np.concatenate([[0.0], np.cumsum(all_vs)])
            s = cum[hi] - cum[lo]
            c = (hi - lo).astype(np.int64)
            if func == "SUM":
                return s
            if func == "COUNT":
                return c
            return s / c
        red = np.minimum if func == "MIN" else np.maximum
        out = np.empty(len(ts), np.float64)
        for j in range(len(ts)):
            out[j] = red.reduce(all_vs[lo[j]:hi[j]])
        return out

    # ------------------------------------------------------------ snapshot
    def snapshot_state(self) -> Dict[str, Any]:
        def pack(batches):
            b = RecordBatch.concat(batches)
            return ({k: np.asarray(v) for k, v in b.columns.items()},
                    None if b.timestamps is None else np.asarray(b.timestamps))
        return {"pending": {k: pack(v) for k, v in self._pending.items()},
                "accs": [dict(d) for d in self._accs],
                "tails": [dict(d) for d in self._tails],
                "last_wm": self._last_wm,
                "dropped_late": self._dropped_late}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._pending = {k: [RecordBatch(cols, timestamps=ts)]
                         for k, (cols, ts) in snap.get("pending", {}).items()}
        self._accs = [dict(d) for d in snap.get(
            "accs", [dict() for _ in self.specs])]
        self._tails = [dict(d) for d in snap.get(
            "tails", [dict() for _ in self.specs])]
        self._last_wm = snap.get("last_wm", LONG_MIN)
        self._dropped_late = snap.get("dropped_late", 0)


class BranchMergeOperator(StreamOperator):
    """Streaming inner merge of two aggregate branches on a merge-key column
    — the glue for mixed DISTINCT/plain aggregate queries, where the planner
    splits one logical group-aggregate into a plain branch and a
    dedup-then-aggregate branch (the reference folds both into one
    ``AggsHandleFunction`` with distinct-state MapViews; here each branch
    stays a dense vectorized aggregate and the fired rows re-join).

    Both branches fire the same (key, window) set, so every buffered row
    pairs up exactly once; ``extra_cols`` names the columns only the right
    branch contributes.  Column data moves by vectorized fancy-indexing —
    the only per-row Python is a key-hash probe into the pending index."""

    is_two_input = True

    def __init__(self, merge_column: str, extra_cols: List[str],
                 name: str = "sql-branch-merge"):
        self.merge_column = merge_column
        self.extra_cols = extra_cols
        self.name = name
        #: per side: buffered batches with un-merged rows, and an index
        #: key -> (batch position in the buffer, row) of those rows
        self._bufs: Tuple[List[RecordBatch], List[RecordBatch]] = ([], [])
        self._unmatched: Tuple[Dict[Any, Tuple[int, int]],
                               Dict[Any, Tuple[int, int]]] = ({}, {})

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        s = input_index
        o = 1 - s
        keys = np.asarray(batch.columns[self.merge_column])
        other_idx = self._unmatched[o]
        mine_rows: List[int] = []              # rows of THIS batch that matched
        other_rows: List[Tuple[int, int]] = []  # (buf_i, row_i) on the other side
        buf_pos = len(self._bufs[s])
        mine_idx = self._unmatched[s]
        for i in range(len(keys)):
            hit = other_idx.pop(keys[i], None)
            if hit is None:
                mine_idx[keys[i]] = (buf_pos, i)
            else:
                mine_rows.append(i)
                other_rows.append(hit)
        if len(mine_rows) < len(keys):
            self._bufs[s].append(batch)
        if not mine_rows:
            return []

        # gather the other side's matched rows per buffered batch (vectorized)
        order = np.argsort([b * (1 << 32) + r for b, r in other_rows],
                           kind="stable")
        mine_sel = np.asarray(mine_rows, np.int64)[order]
        other_sorted = [other_rows[i] for i in order]
        other_parts: List[RecordBatch] = []
        mine_parts: List[np.ndarray] = []
        j = 0
        while j < len(other_sorted):
            bi = other_sorted[j][0]
            k = j
            while k < len(other_sorted) and other_sorted[k][0] == bi:
                k += 1
            rows = np.asarray([r for _, r in other_sorted[j:k]], np.int64)
            other_parts.append(self._bufs[o][bi].take(rows))
            mine_parts.append(mine_sel[j:k])
            j = k
        mine_take = batch.take(np.concatenate(mine_parts))
        other_take = RecordBatch.concat(other_parts)
        left, right = ((mine_take, other_take) if s == 0
                       else (other_take, mine_take))
        cols = dict(left.columns)
        for c in self.extra_cols:
            cols[c] = np.asarray(right.columns[c])
        if not other_idx and not mine_idx:
            # everything paired up — drop the consumed buffers
            self._bufs[0].clear()
            self._bufs[1].clear()
        return [RecordBatch(cols)]

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)

    def _pack_pending(self, side: int) -> List[Dict[str, Any]]:
        rows = []
        for k, (bi, ri) in self._unmatched[side].items():
            b = self._bufs[side][bi]
            rows.append({n: np.asarray(v)[ri] for n, v in b.columns.items()})
        return rows

    def snapshot_state(self) -> Dict[str, Any]:
        # persist only un-merged rows, materialized (small residual set)
        return {"left_rows": self._pack_pending(0),
                "right_rows": self._pack_pending(1)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._bufs = ([], [])
        self._unmatched = ({}, {})
        for side, field in ((0, "left_rows"), (1, "right_rows")):
            rows = snap.get(field) or []
            if not rows:
                continue
            cols: Dict[str, np.ndarray] = {}
            for n in rows[0]:
                vals = [r[n] for r in rows]
                if any(isinstance(v, tuple) for v in vals):
                    # tuple cells (composite keys) must stay 1-D object
                    arr = np.empty(len(vals), object)
                    arr[:] = vals
                else:
                    arr = np.asarray(vals)
                cols[n] = arr
            b = RecordBatch(cols)
            self._bufs[side].append(b)
            keys = np.asarray(b.columns[self.merge_column])
            for i in range(len(b)):
                self._unmatched[side][keys[i]] = (0, i)
