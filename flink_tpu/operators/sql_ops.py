"""SQL runtime operators: joins, changelog aggregation, Top-N, dedup,
mini-batch bundling.

Analogs of the blink table runtime (``flink-table-runtime-blink``):
``StreamingJoinOperator`` (regular equi-join), ``GroupAggFunction`` with
retraction (``+I/-U/+U/-D`` changelog rows), ``AppendOnlyTopNFunction`` /
``RankOperator``, ``DeduplicateKeepFirstRow/KeepLastRow`` functions, and the
``bundle/`` mini-batch operators.  Batched columnar: each structure keys on
vectorized column ops, not per-record state probes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import (LONG_MIN, RecordBatch, StreamElement,
                                  Watermark)
from flink_tpu.operators.base import StreamOperator
from flink_tpu.operators.joins import _join_pairs, _merge_columns


from flink_tpu.ops.shapes import next_pow2


def _next_pow2_sql(n: int) -> int:
    return next_pow2(n, 64)


def _infer_column(vals: List[Any]) -> np.ndarray:
    """Row-dict values -> a column with a NATURAL dtype: numeric columns
    must come out float/int (downstream jitted aggregates cannot consume
    object arrays); None-padded or string columns stay object."""
    if any(v is None for v in vals):
        return np.asarray(vals, object)
    try:
        a = np.asarray(vals)
    except (TypeError, ValueError):
        return np.asarray(vals, object)
    if a.dtype.kind in ("U", "S", "O"):
        return np.asarray(vals, object)
    return a


class SqlJoinOperator(StreamOperator):
    """Bounded-table equi-join (``StreamExecJoin`` over bounded inputs):
    both sides buffer; the join emits once at end-of-input — batch SQL
    semantics.  ``how``: inner / left / right / full."""

    is_two_input = True

    def __init__(self, left_key: str, right_key: str, how: str = "inner",
                 right_rename: Optional[Dict[str, str]] = None,
                 left_columns: Optional[List[str]] = None,
                 right_columns: Optional[List[str]] = None,
                 name: str = "sql-join"):
        self.left_key = left_key
        self.right_key = right_key
        self.how = how
        self.right_rename = right_rename or {}
        #: declared schemas: outer joins must emit null-filled columns for an
        #: EMPTY side, which cannot be inferred from received batches
        self.left_columns = left_columns
        self.right_columns = right_columns
        self.name = name
        self._left: List[RecordBatch] = []
        self._right: List[RecordBatch] = []
        self._ended = 0

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        if len(batch):
            (self._left if input_index == 0 else self._right).append(batch)
        return []

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)

    def end_input(self) -> List[StreamElement]:
        # called once per vertex after ALL inputs ended
        l = RecordBatch.concat(self._left) if self._left else None
        r = RecordBatch.concat(self._right) if self._right else None
        self._left, self._right = [], []
        return self._join(l, r)

    def _rename_right(self, cols: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return {self.right_rename.get(k, k): v for k, v in cols.items()}

    def _join(self, l: Optional[RecordBatch],
              r: Optional[RecordBatch]) -> List[StreamElement]:
        nl = len(l) if l is not None else 0
        nr = len(r) if r is not None else 0
        parts: List[Dict[str, np.ndarray]] = []
        li = ri = np.zeros(0, np.int64)
        if nl and nr:
            li, ri = _join_pairs(np.asarray(l.column(self.left_key)),
                                 np.asarray(r.column(self.right_key)))
        lcols = (self.left_columns if self.left_columns is not None
                 else (list(l.columns) if l is not None else []))
        rcols = (self.right_columns if self.right_columns is not None
                 else (list(r.columns) if r is not None else []))
        if li.size:
            cols = {k: np.asarray(v)[li] for k, v in l.columns.items()}
            cols.update(self._rename_right(
                {k: np.asarray(v)[ri] for k, v in r.columns.items()}))
            parts.append(cols)
        if self.how in ("left", "full") and nl:
            unmatched = np.setdiff1d(np.arange(nl), li)
            if unmatched.size:
                cols = {k: np.asarray(v)[unmatched]
                        for k, v in l.columns.items()}
                cols.update(self._rename_right(
                    {k: np.full(unmatched.size, None, object) for k in rcols}))
                parts.append(cols)
        if self.how in ("right", "full") and nr:
            unmatched = np.setdiff1d(np.arange(nr), ri)
            if unmatched.size:
                cols = {k: np.full(unmatched.size, None, object)
                        for k in lcols}
                cols.update(self._rename_right(
                    {k: np.asarray(v)[unmatched]
                     for k, v in r.columns.items()}))
                parts.append(cols)
        if not parts:
            return []
        batches = [RecordBatch(c) for c in parts]
        return [RecordBatch.concat(batches) if len(batches) > 1 else batches[0]]

    def snapshot_state(self) -> Dict[str, Any]:
        def pack(bs):
            if not bs:
                return None
            b = RecordBatch.concat(bs)
            return {k: np.asarray(v) for k, v in b.columns.items()}
        return {"left": pack(self._left), "right": pack(self._right)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._left = ([RecordBatch(snap["left"])] if snap.get("left") else [])
        self._right = ([RecordBatch(snap["right"])] if snap.get("right") else [])


class _JoinSideState:
    """One side of an unbounded streaming join: a row-instance table with a
    per-key live index, association counts for outer padding, and optional
    processing-time TTL (``JoinRecordStateView`` /
    ``OuterJoinRecordStateView`` analog — state rows + numOfAssociations)."""

    def __init__(self, columns: List[str], key_col: str):
        self.columns = list(columns)
        self.key_at = self.columns.index(key_col)
        self.rows: List[Optional[tuple]] = []   # row tuples; None = freed
        self.assoc: List[int] = []              # matches on the other side
        self.ts: List[int] = []                 # last-touch ms (TTL)
        self.by_key: Dict[Any, List[int]] = {}  # key -> live row indices
        self.free: List[int] = []

    def add(self, row: tuple, assoc: int, now_ms: int) -> int:
        if self.free:
            i = self.free.pop()
            self.rows[i] = row
            self.assoc[i] = assoc
            self.ts[i] = now_ms
        else:
            i = len(self.rows)
            self.rows.append(row)
            self.assoc.append(assoc)
            self.ts.append(now_ms)
        self.by_key.setdefault(row[self.key_at], []).append(i)
        return i

    def remove_one(self, row: tuple) -> Optional[int]:
        """Retract ONE instance equal to ``row``; returns its index (its
        assoc count is still readable) or None if no instance is live."""
        key = row[self.key_at]
        idxs = self.by_key.get(key)
        if not idxs:
            return None
        for pos, i in enumerate(idxs):
            if self.rows[i] == row:
                idxs.pop(pos)
                if not idxs:
                    del self.by_key[key]
                self.rows[i] = None
                self.free.append(i)
                return i
        return None

    def matches(self, key: Any,
                cutoff_ms: Optional[int] = None) -> List[int]:
        """Live rows under ``key``; with a TTL cutoff, expired rows are
        filtered at access time (exact semantics) while ``expire`` sweeps
        reclaim their memory on an amortized cadence."""
        idxs = self.by_key.get(key, [])
        if cutoff_ms is None:
            return idxs
        return [i for i in idxs if self.ts[i] >= cutoff_ms]

    def expire(self, cutoff_ms: int) -> int:
        """Drop rows last touched before ``cutoff_ms`` (state TTL: silent
        eviction, like the reference's StateTtlConfig on join state — no
        retractions are emitted for expired rows)."""
        dropped = 0
        for key in list(self.by_key):
            idxs = self.by_key[key]
            keep = []
            for i in idxs:
                if self.ts[i] < cutoff_ms:
                    self.rows[i] = None
                    self.free.append(i)
                    dropped += 1
                else:
                    keep.append(i)
            if keep:
                self.by_key[key] = keep
            else:
                del self.by_key[key]
        return dropped

    def snapshot(self) -> Dict[str, Any]:
        live = [i for i, r in enumerate(self.rows) if r is not None]
        return {
            "cols": {c: np.asarray([self.rows[i][j] for i in live], object)
                     for j, c in enumerate(self.columns)},
            "assoc": np.asarray([self.assoc[i] for i in live], np.int64),
            "ts": np.asarray([self.ts[i] for i in live], np.int64),
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        cols = [snap["cols"][c] for c in self.columns]
        n = len(cols[0]) if cols else 0
        self.rows = [tuple(col[i] for col in cols) for i in range(n)]
        self.assoc = [int(a) for a in snap["assoc"]]
        self.ts = [int(t) for t in snap["ts"]]
        self.by_key = {}
        self.free = []
        for i, row in enumerate(self.rows):
            self.by_key.setdefault(row[self.key_at], []).append(i)


class StreamingJoinOperator(StreamOperator):
    """Unbounded two-stream equi-join emitting an incremental CHANGELOG —
    the ``StreamingJoinOperator`` analog
    (``flink-table-runtime-blink/.../join/stream/StreamingJoinOperator.java:36``
    with the ``JoinRecordStateView`` association counting of
    ``OuterJoinRecordStateView.java``).

    Both sides live in keyed state forever (or until ``state_ttl_ms``); each
    arriving row emits joined rows immediately.  The ``op`` output column
    carries the change kind: ``+I`` insert, ``-D`` delete, and the outer-join
    padding transitions ride ``-U``/``+U`` pairs — when a null-padded outer
    row gains its FIRST match the padded row downgrades out (``-U``) and the
    joined row upgrades in (``+U``); losing the LAST match reverses it.
    Inputs may themselves be changelogs: a batch with an ``op`` column
    retracts on ``-D``/``-U`` and accumulates on ``+I``/``+U`` (RowKind
    folding, ``AbstractStreamingJoinOperator.java``).

    Append-only inner joins take a vectorized fast path (no association
    bookkeeping is needed without padding): incoming batch keys hash-join
    against the stored other side via ``_join_pairs`` in one shot.
    """

    is_two_input = True

    def __init__(self, left_key: str, right_key: str, how: str = "inner",
                 right_rename: Optional[Dict[str, str]] = None,
                 left_columns: Optional[List[str]] = None,
                 right_columns: Optional[List[str]] = None,
                 state_ttl_ms: int = 0,
                 name: str = "streaming-join"):
        if left_columns is None or right_columns is None:
            raise ValueError("streaming join requires declared schemas "
                             "(outer padding cannot be inferred)")
        self.left_key = left_key
        self.right_key = right_key
        self.how = how
        self.right_rename = right_rename or {}
        self.left_columns = list(left_columns)
        self.right_columns = list(right_columns)
        self.state_ttl_ms = state_ttl_ms
        self.name = name
        self._left = _JoinSideState(self.left_columns, left_key)
        self._right = _JoinSideState(self.right_columns, right_key)
        #: retractions for rows never accumulated (e.g. expired by TTL) are
        #: dropped, counted here (the reference logs & skips the same way)
        self.stale_retractions = 0
        #: last full-expire sweep time: expiry is amortized (a sweep per
        #: ttl/4, like the reference's timer-driven StateTtlConfig), never
        #: an O(total state) scan on every batch
        self._last_expire_ms = 0
        self._out_columns = (["op"] + self.left_columns
                             + [self.right_rename.get(c, c)
                                for c in self.right_columns])

    # -- helpers -------------------------------------------------------------
    def _now_ms(self) -> int:
        import time
        return int(time.time() * 1000)

    def _outer(self, side: int) -> bool:
        """Is ``side`` (0=left, 1=right) an outer side (emits padding)?"""
        return self.how in (("left", "full") if side == 0
                            else ("right", "full"))

    def _cutoff(self, now_ms: int) -> Optional[int]:
        return (now_ms - self.state_ttl_ms) if self.state_ttl_ms > 0 else None

    def _joined(self, op: str, lrow: Optional[tuple],
                rrow: Optional[tuple]) -> tuple:
        l = lrow if lrow is not None else (None,) * len(self.left_columns)
        r = rrow if rrow is not None else (None,) * len(self.right_columns)
        return (op,) + l + r

    def _to_batch(self, out: List[tuple]) -> List[StreamElement]:
        if not out:
            return []
        cols = {c: np.asarray([row[j] for row in out], object)
                for j, c in enumerate(self._out_columns)}
        return [RecordBatch(cols)]

    # -- per-row semantics ---------------------------------------------------
    def _accumulate(self, side: int, row: tuple, out: List[tuple],
                    now_ms: int) -> None:
        own = self._left if side == 0 else self._right
        other = self._right if side == 0 else self._left
        pair = ((lambda o, a, b: self._joined(o, a, b)) if side == 0
                else (lambda o, a, b: self._joined(o, b, a)))
        matches = list(other.matches(row[own.key_at], self._cutoff(now_ms)))
        if matches:
            for m in matches:
                mrow = other.rows[m]
                if self._outer(1 - side) and other.assoc[m] == 0:
                    # the other side's null-padded row gains its first match:
                    # downgrade the padding out, upgrade the joined row in
                    out.append(pair("-U", None, mrow))
                    out.append(pair("+U", row, mrow))
                else:
                    out.append(pair("+I", row, mrow))
                other.assoc[m] += 1
                other.ts[m] = now_ms
        elif self._outer(side):
            out.append(pair("+I", row, None))
        own.add(row, len(matches), now_ms)

    def _retract(self, side: int, row: tuple, out: List[tuple],
                 now_ms: int) -> None:
        own = self._left if side == 0 else self._right
        other = self._right if side == 0 else self._left
        pair = ((lambda o, a, b: self._joined(o, a, b)) if side == 0
                else (lambda o, a, b: self._joined(o, b, a)))
        if own.remove_one(row) is None:
            self.stale_retractions += 1
            return
        matches = list(other.matches(row[own.key_at], self._cutoff(now_ms)))
        if matches:
            for m in matches:
                mrow = other.rows[m]
                other.assoc[m] -= 1
                other.ts[m] = now_ms
                if self._outer(1 - side) and other.assoc[m] == 0:
                    # last match gone: the joined row downgrades out, the
                    # null-padded row upgrades back in
                    out.append(pair("-U", row, mrow))
                    out.append(pair("+U", None, mrow))
                else:
                    out.append(pair("-D", row, mrow))
        elif self._outer(side):
            out.append(pair("-D", row, None))

    # -- batch entry ---------------------------------------------------------
    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        now = self._now_ms()
        if self.state_ttl_ms > 0 \
                and now - self._last_expire_ms >= self.state_ttl_ms // 4:
            self._last_expire_ms = now
            cutoff = now - self.state_ttl_ms
            self._left.expire(cutoff)
            self._right.expire(cutoff)
        own = self._left if input_index == 0 else self._right
        col_names = own.columns
        data = [np.asarray(batch.column(c)) for c in col_names]
        ops = (np.asarray(batch.column("op"))
               if "op" in batch.columns else None)
        out: List[tuple] = []
        if ops is None and self.how == "inner":
            self._accumulate_append_inner(input_index, data, now, out)
            return self._to_batch(out)
        n = len(batch)
        for i in range(n):
            row = tuple(col[i] for col in data)
            op = "+I" if ops is None else str(ops[i])
            if op in ("+I", "+U"):
                self._accumulate(input_index, row, out, now)
            elif op in ("-D", "-U"):
                self._retract(input_index, row, out, now)
            else:
                raise ValueError(f"unknown changelog op {op!r}")
        return self._to_batch(out)

    def _accumulate_append_inner(self, side: int, data: List[np.ndarray],
                                 now_ms: int, out: List[tuple]) -> None:
        """Vectorized append-only inner path: one hash join of the incoming
        batch against the stored other side (no padding → no association
        counts to maintain)."""
        own = self._left if side == 0 else self._right
        other = self._right if side == 0 else self._left
        keys = data[own.key_at]
        cut = self._cutoff(now_ms)
        cand = [i for k in dict.fromkeys(keys.tolist())
                for i in other.matches(k, cut)]
        if cand:
            other_keys = np.asarray([other.rows[i][other.key_at]
                                     for i in cand], object)
            bi, ci = _join_pairs(keys, other_keys)
            for b, c in zip(bi.tolist(), ci.tolist()):
                row = tuple(col[b] for col in data)
                mrow = other.rows[cand[c]]
                other.ts[cand[c]] = now_ms   # TTL touch, same as slow path
                out.append(self._joined("+I", row, mrow) if side == 0
                           else self._joined("+I", mrow, row))
        for i in range(len(keys)):
            own.add(tuple(col[i] for col in data), 0, now_ms)

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)

    # -- lifecycle -----------------------------------------------------------
    def snapshot_state(self) -> Dict[str, Any]:
        return {"left": self._left.snapshot(),
                "right": self._right.snapshot(),
                "stale_retractions": self.stale_retractions}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._left.restore(snap["left"])
        self._right.restore(snap["right"])
        self.stale_retractions = int(snap.get("stale_retractions", 0))


class LookupJoinOperator(StreamOperator):
    """Dimension (lookup) join — the ``StreamExecLookupJoin`` /
    ``LookupJoinRunner`` analog: each probe row looks its key up in an
    EXTERNAL system (e.g. the wire-real Postgres connector) through a
    TTL'd cache; the dimension is observed at processing time
    (``FOR SYSTEM_TIME AS OF o.proctime`` semantics).

    ``lookup_fn(key) -> list[dict]`` returns the dimension rows for a key
    (empty list = no match).  The cache bounds external round-trips:
    entries expire after ``cache_ttl_ms`` and the cache holds at most
    ``max_cache_rows`` keys (LRU eviction), mirroring
    ``LookupCacheManager`` / ``table.exec.lookup.cache`` options."""

    def __init__(self, key_column: str,
                 lookup_fn: Callable[[Any], List[dict]],
                 right_columns: List[str],
                 right_rename: Optional[Dict[str, str]] = None,
                 how: str = "inner",
                 cache_ttl_ms: int = 60_000,
                 max_cache_rows: int = 10_000,
                 name: str = "lookup-join"):
        if how not in ("inner", "left"):
            raise ValueError("lookup join supports INNER and LEFT only")
        self.key_column = key_column
        self.lookup_fn = lookup_fn
        self.right_columns = list(right_columns)
        self.right_rename = right_rename or {}
        self.how = how
        self.cache_ttl_ms = cache_ttl_ms
        self.max_cache_rows = max_cache_rows
        self.name = name
        #: key -> (fetched_at_ms, rows); insertion order doubles as LRU
        self._cache: Dict[Any, Tuple[int, List[dict]]] = {}
        self.cache_hits = 0
        self.cache_misses = 0

    def _probe(self, key: Any, now_ms: int) -> List[dict]:
        hit = self._cache.get(key)
        if hit is not None and (self.cache_ttl_ms <= 0
                                or now_ms - hit[0] < self.cache_ttl_ms):
            self.cache_hits += 1
            self._cache[key] = self._cache.pop(key)   # LRU touch
            return hit[1]
        self.cache_misses += 1
        rows = list(self.lookup_fn(key))
        self._cache.pop(key, None)
        self._cache[key] = (now_ms, rows)
        while len(self._cache) > self.max_cache_rows:
            self._cache.pop(next(iter(self._cache)))
        return rows

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        import time
        if len(batch) == 0:
            return []
        now = int(time.time() * 1000)
        keys = np.asarray(batch.column(self.key_column))
        lcols = list(batch.columns)
        larrs = [np.asarray(batch.column(c)) for c in lcols]
        by_key = {k: self._probe(k, now)
                  for k in dict.fromkeys(keys.tolist())}
        out: List[dict] = []
        for i in range(len(batch)):
            matches = by_key[keys[i] if not isinstance(keys[i], np.generic)
                             else keys[i].item()]
            lrow = {c: a[i] for c, a in zip(lcols, larrs)}
            if matches:
                for m in matches:
                    row = dict(lrow)
                    for c in self.right_columns:
                        row[self.right_rename.get(c, c)] = m.get(c)
                    out.append(row)
            elif self.how == "left":
                row = dict(lrow)
                for c in self.right_columns:
                    row[self.right_rename.get(c, c)] = None
                out.append(row)
        if not out:
            return []
        cols = {c: _infer_column([r[c] for r in out]) for c in out[0]}
        return [RecordBatch(cols)]

    # the cache is NOT state: a restore re-probes the external system (the
    # dimension may have changed; the reference's cache is also transient)
    def snapshot_state(self) -> Dict[str, Any]:
        return {}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._cache = {}


class TemporalJoinOperator(StreamOperator):
    """Event-time temporal (versioned-table) join — the
    ``StreamExecTemporalJoin.java:67`` / ``TemporalRowTimeJoinOperator``
    analog: the right side is a VERSIONED table (append stream of versions
    keyed by ``right_key``, version time = ``right_time_column``); each
    left row at time t joins the latest right version with
    ``version_ts <= t``.  Left rows buffer until the watermark passes
    their time (both inputs' watermarks merge through the two-input
    valve), so late-arriving versions still win; versions older than the
    one valid at the watermark are pruned (state cleanup,
    ``TemporalRowTimeJoinOperator.cleanupState``)."""

    is_two_input = True

    def __init__(self, left_key: str, right_key: str,
                 left_time_column: str, right_time_column: str,
                 right_columns: List[str],
                 right_rename: Optional[Dict[str, str]] = None,
                 how: str = "inner",
                 name: str = "temporal-join"):
        if how not in ("inner", "left"):
            raise ValueError("temporal join supports INNER and LEFT only")
        self.left_key = left_key
        self.right_key = right_key
        self.left_time_column = left_time_column
        self.right_time_column = right_time_column
        self.right_columns = list(right_columns)
        self.right_rename = right_rename or {}
        self.how = how
        self.name = name
        #: right: key -> (sorted version ts list, parallel row list)
        self._versions: Dict[Any, Tuple[List[int], List[dict]]] = {}
        #: left rows waiting for the watermark: [(t, row), ...]
        self._pending: List[Tuple[int, dict]] = []
        self.watermark = LONG_MIN
        self._wm_calls = 0

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        import bisect
        if len(batch) == 0:
            return []
        cols = list(batch.columns)
        arrs = [np.asarray(batch.column(c)) for c in cols]
        rows = [{c: a[i] for c, a in zip(cols, arrs)}
                for i in range(len(batch))]
        if input_index == 1:
            for r in rows:
                vts = int(r[self.right_time_column])
                ts_list, row_list = self._versions.setdefault(
                    r[self.right_key], ([], []))
                i = bisect.bisect_right(ts_list, vts)
                ts_list.insert(i, vts)
                row_list.insert(i, r)
            return []
        for r in rows:
            self._pending.append((int(r[self.left_time_column]), r))
        return []

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        self.watermark = max(self.watermark, watermark.timestamp)
        self._wm_calls += 1
        if self._wm_calls % 64 == 0:
            # amortized sweep for keys never probed (probe-time pruning
            # below covers the active ones) — never a full scan per
            # watermark on the hot path
            self._prune_all(self.watermark)
        return self._emit_ready(self.watermark)

    def end_input(self) -> List[StreamElement]:
        return self._emit_ready(2 ** 62)

    def _emit_ready(self, up_to: int) -> List[StreamElement]:
        import bisect
        ready = [(t, r) for t, r in self._pending if t <= up_to]
        if not ready:
            return []
        self._pending = [(t, r) for t, r in self._pending if t > up_to]
        ready.sort(key=lambda e: e[0])
        out: List[dict] = []
        out_ts: List[int] = []
        probed = set()
        for t, lrow in ready:
            key = lrow[self.left_key]
            probed.add(key)
            entry = self._versions.get(key)
            i = bisect.bisect_right(entry[0], t) if entry else 0
            if i > 0:
                vrow = entry[1][i - 1]
                row = dict(lrow)
                for c in self.right_columns:
                    row[self.right_rename.get(c, c)] = vrow.get(c)
            elif self.how == "left":
                row = dict(lrow)
                for c in self.right_columns:
                    row[self.right_rename.get(c, c)] = None
            else:
                continue
            out.append(row)
            out_ts.append(t)
        if up_to < 2 ** 62:
            for key in probed:        # lazy per-key state cleanup
                self._prune_key(key, up_to)
        if not out:
            return []
        cols = {c: _infer_column([r[c] for r in out]) for c in out[0]}
        return [RecordBatch(cols, timestamps=np.asarray(out_ts, np.int64))]

    def _prune_key(self, key, wm: int) -> None:
        """Drop versions older than the one valid AT the watermark — they
        can never be joined again (``TemporalRowTimeJoinOperator``'s state
        cleanup)."""
        import bisect
        entry = self._versions.get(key)
        if not entry:
            return
        ts_list, row_list = entry
        cut = bisect.bisect_right(ts_list, wm) - 1
        if cut > 0:
            del ts_list[:cut]
            del row_list[:cut]

    def _prune_all(self, wm: int) -> None:
        for key in list(self._versions):
            self._prune_key(key, wm)

    def snapshot_state(self) -> Dict[str, Any]:
        return {"versions": {k: (list(ts), list(rows))
                             for k, (ts, rows) in self._versions.items()},
                "pending": list(self._pending),
                "watermark": self.watermark}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._versions = {k: (list(v[0]), list(v[1]))
                          for k, v in snap["versions"].items()}
        self._pending = list(snap["pending"])
        self.watermark = snap["watermark"]


class ChangelogGroupAggOperator(StreamOperator):
    """Non-windowed group aggregate emitting a CHANGELOG (retraction) stream
    — the device-resident ``StreamExecGroupAggregate`` / ``GroupAggFunction``
    analog (``flink-table-runtime-blink/.../operators/aggregate/``).

    TPU design (same pattern as ``window_agg.py``): group state is a dense
    ``[K]`` device array per aggregate; one jitted step per micro-batch
    segment-reduces the batch into per-group partials, gathers the OLD
    values, combines, scatters the NEW values back — and returns only the
    ``[U]`` touched-group old/new pairs (U = distinct groups in the batch),
    which is exactly the set changelog semantics must emit.  The host emits
    ``+I`` for groups whose dense slot id is new (slot ids are
    insertion-ordered, so "new since the previous batch" is a host-known
    comparison — no seen-flag download), ``-U``/``+U`` pairs for changed
    ones.  The ``op`` column carries the change kind."""

    #: combine modes per aggregate kind (identity, jnp combine)
    _MODES = {"sum": "add", "count": "add", "min": "min", "max": "max"}

    def __init__(self, key_column: str, agg_columns: Dict[str, Tuple[str, str]],
                 name: str = "changelog-group-agg",
                 initial_capacity: int = 1 << 10,
                 consume_retractions: bool = False):
        """agg_columns: out_name -> (input column, how in sum/count/min/max).

        ``consume_retractions=True``: the INPUT is itself a changelog (an
        ``op`` column with +I/-U/+U/-D — a CDC ingress or an upstream
        retracting operator); retraction rows contribute NEGATED values, a
        hidden per-group row count detects group deletion (``-D`` emitted
        when it reaches zero) and re-insertion (``+I``).  Only invertible
        aggregates (sum/count) can consume retractions — min/max would
        need the full value multiset (the reference's retract-agg rule)."""
        import jax.numpy as jnp  # noqa: F401 — device runtime

        for out, (_c, how) in agg_columns.items():
            if how not in self._MODES:
                raise ValueError(f"unsupported changelog aggregate {how!r}")
        self.consume_retractions = consume_retractions
        self.output_names = list(agg_columns)
        if consume_retractions:
            bad = [o for o, (_c, how) in agg_columns.items()
                   if self._MODES[how] != "add"]
            if bad:
                raise ValueError(
                    f"aggregates {bad} cannot consume retractions "
                    f"(min/max are not invertible); use sum/count")
            agg_columns = dict(agg_columns)
            agg_columns["__rows"] = (None, "count")   # hidden liveness count
        self.key_column = key_column
        self.agg_columns = agg_columns
        self.name = name
        self._K = initial_capacity
        self.key_index = None
        self._state = None  # tuple of jnp [K] per agg column

    def _identity(self, how: str) -> float:
        return 0.0 if how in ("sum", "count") else (
            np.inf if how == "min" else -np.inf)

    def _alloc(self, K: int):
        """TWO f32 words (hi, lo) per column.  sum/count: double-single
        (compensated) accumulation; min/max: Dekker-split pairs combined
        lexicographically.  Both keep ~48 bits of precision without float64
        (jnp defaults to 32-bit): a count or an integer-valued min/max is
        exact up to 2^48, where a plain f32 would lose integers above
        2^24."""
        import jax.numpy as jnp

        arrs = []
        for out, (_c, how) in self.agg_columns.items():
            arrs.append(jnp.full((K,), self._identity(how), jnp.float32))
            arrs.append(jnp.zeros((K,), jnp.float32))  # low word
        return tuple(arrs)

    def _ensure(self, needed: int):
        import jax.numpy as jnp  # noqa: F401

        if self._state is None:
            while self._K < needed:
                self._K <<= 1
            self._state = self._alloc(self._K)
            return
        if needed <= self._K:
            return
        oldK = self._state[0].shape[0]
        while self._K < needed:
            self._K <<= 1
        fresh = self._alloc(self._K)
        self._state = tuple(f.at[:oldK].set(o)
                            for f, o in zip(fresh, self._state))

    @staticmethod
    def _lex_pick(jnp, ah, al, bh, bl, mode):
        """Element-wise lexicographic min/max over Dekker pairs (hi, lo):
        normalized pairs (|lo| <= ulp(hi)/2) order exactly like the f64
        values they represent, so comparing (hi, then lo on hi-ties) picks
        the true extremum without 64-bit arithmetic."""
        if mode == "min":
            take_a = (ah < bh) | ((ah == bh) & (al <= bl))
        else:
            take_a = (ah > bh) | ((ah == bh) & (al >= bl))
        return jnp.where(take_a, ah, bh), jnp.where(take_a, al, bl)

    def _seg_reduce_pair(self, jnp, hi, lo, inv, U, mode, identity):
        """Per-batch segment reduce of Dekker pairs: two scatter-extrema —
        first the hi words, then the lo words of rows WHOSE hi attained the
        segment extremum (rows off the extremum are masked to identity)."""
        if mode == "add":
            return (jnp.zeros((U,), jnp.float32).at[inv].add(hi),
                    jnp.zeros((U,), jnp.float32).at[inv].add(lo))
        red = (lambda a, i, v: a.at[i].min(v)) if mode == "min" \
            else (lambda a, i, v: a.at[i].max(v))
        hi_x = red(jnp.full((U,), identity, jnp.float32), inv, hi)
        on_x = hi == jnp.take(hi_x, inv)
        lo_masked = jnp.where(on_x, lo,
                              jnp.float32(np.inf if mode == "min"
                                          else -np.inf))
        lo_x = red(jnp.full((U,), np.inf if mode == "min" else -np.inf,
                            jnp.float32), inv, lo_masked)
        # identity segments (no rows): lo back to 0 so hi+lo stays finite
        return hi_x, jnp.where(jnp.isfinite(lo_x), lo_x, 0.0)

    def _update_step_impl(self, state, uniq_slots, inv, values, U):
        """state': scatter combined; returns (state', old[U], new[U]) per
        state array (every column contributes an (hi, lo) pair)."""
        import jax.numpy as jnp

        olds, news, out_state = [], [], []
        si = 0
        for out, (_c, how) in self.agg_columns.items():
            mode = self._MODES[how]
            ident = self._identity(how)
            vhi, vlo = values[out]
            phi, plo = self._seg_reduce_pair(jnp, vhi, vlo, inv, U, mode,
                                             ident)
            hi_arr, lo_arr = state[si], state[si + 1]
            si += 2
            hi = jnp.take(hi_arr, uniq_slots, mode="clip")
            lo = jnp.take(lo_arr, uniq_slots, mode="clip")
            if mode == "add":
                # double-single += f32 (2Sum): exact error of hi+partial
                # folds into the low word
                s = hi + phi
                v = s - hi
                e = (hi - (s - v)) + (phi - v)
                lo2 = (lo + plo) + e
                nh = s + lo2
                nl = lo2 - (nh - s)
            else:
                nh, nl = self._lex_pick(jnp, hi, lo, phi, plo, mode)
            out_state.append(hi_arr.at[uniq_slots].set(nh, mode="drop"))
            out_state.append(lo_arr.at[uniq_slots].set(nl, mode="drop"))
            olds.extend([hi, lo])
            news.extend([nh, nl])
        return tuple(out_state), tuple(olds), tuple(news)

    def _jitted(self):
        import jax

        fn = getattr(self, "_jit_cache", None)
        if fn is None:
            fn = self._jit_cache = jax.jit(
                self._update_step_impl, static_argnums=(4,),
                donate_argnums=(0,))
        return fn

    #: per-batch partials reduce in plain f32 (exact for counts up to 2^24
    #: per batch); batches beyond this bound chunk so the within-chunk
    #: reduction stays exact and the double-single merge carries precision
    #: across chunks
    _MAX_CHUNK = 1 << 22

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        import jax.numpy as jnp

        if len(batch) == 0:
            return []
        if len(batch) > self._MAX_CHUNK:
            out: List[StreamElement] = []
            n = len(batch)
            idx = np.arange(n)
            for lo in range(0, n, self._MAX_CHUNK):
                m = (idx >= lo) & (idx < lo + self._MAX_CHUNK)
                out.extend(self.process_batch(batch.select(m)))
            return out
        from flink_tpu.state.keyindex import make_key_index

        keys = np.asarray(batch.column(self.key_column))
        if self.key_index is None:
            self.key_index = make_key_index(keys[0] if keys.ndim else keys)
        prev_n = self.key_index.num_keys
        slots = self.key_index.lookup_or_insert(keys)
        self._ensure(self.key_index.num_keys)
        uniq_slots, inv = np.unique(slots, return_inverse=True)
        U = int(uniq_slots.size)
        Up = _next_pow2_sql(U)
        uniq_p = np.full(Up, self._K, np.int32)  # pad: dropped by scatter
        uniq_p[:U] = uniq_slots
        # pad the batch dim too (quantized): varying micro-batch sizes must
        # not each compile a fresh XLA program.  Padding rows carry each
        # column's identity and inv=0, a no-op contribution to group 0.
        from flink_tpu.ops.shapes import quantize_pow2
        B = len(batch)
        Bp = quantize_pow2(B, floor=64, steps=4)
        inv_p = np.zeros(Bp, np.int64)
        inv_p[:B] = inv
        sign = None
        if self.consume_retractions and "op" in batch.columns:
            # retraction rows contribute negated values (invertible aggs
            # only — enforced at construction)
            ops = np.asarray(batch.column("op"))
            sign = np.where(np.isin(ops, ["-D", "-U"]), -1.0, 1.0)
        values = {}
        for out, (col, how) in self.agg_columns.items():
            # Dekker split on the host: hi = f32(v), lo = f32(v - hi) — the
            # pair carries ~48 bits, so integer inputs above 2^24 stay exact
            # through min/max and into compensated sums
            v64 = np.full(Bp, 0.0 if self._MODES[how] == "add"
                          else self._identity(how), np.float64)
            vals = (1.0 if col is None
                    else np.asarray(batch.column(col), np.float64))
            v64[:B] = vals * sign if sign is not None else vals
            vhi = v64.astype(np.float32)
            with np.errstate(invalid="ignore"):  # inf - inf pads -> 0 below
                vlo = (v64 - vhi.astype(np.float64)).astype(np.float32)
            vlo[~np.isfinite(vlo)] = 0.0
            values[out] = (jnp.asarray(vhi), jnp.asarray(vlo))
        self._state, olds, news = self._jitted()(
            self._state, jnp.asarray(uniq_p), jnp.asarray(inv_p, jnp.int32),
            values, Up)
        # ---- host emit: only the [U] touched groups come back; (hi, lo)
        # pairs collapse to f64 (recovering the compensated precision)
        olds_f, news_f = [], []
        for i in range(0, len(olds), 2):
            olds_f.append(np.asarray(olds[i], np.float64)[:U]
                          + np.asarray(olds[i + 1], np.float64)[:U])
            news_f.append(np.asarray(news[i], np.float64)[:U]
                          + np.asarray(news[i + 1], np.float64)[:U])
        names = list(self.agg_columns)
        if self.consume_retractions:
            return self._emit_retract_mode(names, uniq_slots, olds_f,
                                           news_f, U)
        is_new = uniq_slots >= prev_n
        changed = ~is_new & np.logical_or.reduce(
            [o != n for o, n in zip(olds_f, news_f)])
        if not (is_new.any() or changed.any()):
            return []
        rev = self._reverse_keys()
        out_rows: List[Dict[str, Any]] = []
        for gi in range(U):
            key = rev[uniq_slots[gi]]
            if is_new[gi]:
                out_rows.append({"op": "+I", self.key_column: key,
                                 **{names[j]: news_f[j][gi]
                                    for j in range(len(names))}})
            elif changed[gi]:
                out_rows.append({"op": "-U", self.key_column: key,
                                 **{names[j]: olds_f[j][gi]
                                    for j in range(len(names))}})
                out_rows.append({"op": "+U", self.key_column: key,
                                 **{names[j]: news_f[j][gi]
                                    for j in range(len(names))}})
        cols = {c: np.asarray([r[c] for r in out_rows]) for c in out_rows[0]}
        return [RecordBatch(cols)]

    def _reverse_keys(self):
        rev = getattr(self, "_rev_cache", None)
        if rev is None or len(rev) < self.key_index.num_keys:
            # O(N) reverse-table copy only when new keys appeared
            rev = self._rev_cache = np.asarray(self.key_index.reverse_keys())
        return rev

    def _emit_retract_mode(self, names, uniq_slots, olds_f, news_f,
                           U: int) -> List[StreamElement]:
        """Changelog-consuming emission: the hidden ``__rows`` count drives
        group liveness — 0→n emits ``+I``, n→0 emits ``-D`` (with the OLD
        values, the row downstream must revoke), live-and-changed emits the
        ``-U``/``+U`` pair (``GroupAggFunction`` with
        ``countIsZero``/``firstRow`` logic)."""
        ri = names.index("__rows")
        out_idx = [j for j, nm in enumerate(names) if nm != "__rows"]
        old_r, new_r = olds_f[ri], news_f[ri]
        val_changed = (np.logical_or.reduce(
            [olds_f[j] != news_f[j] for j in out_idx])
            if out_idx else np.zeros(U, bool))
        appear = (old_r <= 0) & (new_r > 0)
        disappear = (old_r > 0) & (new_r <= 0)
        update = (old_r > 0) & (new_r > 0) & val_changed
        if not (appear.any() or disappear.any() or update.any()):
            return []
        rev = self._reverse_keys()
        onames = self.output_names
        out_rows: List[Dict[str, Any]] = []
        for gi in range(U):
            key = rev[uniq_slots[gi]]
            if appear[gi]:
                out_rows.append({"op": "+I", self.key_column: key,
                                 **{onames[j2]: news_f[out_idx[j2]][gi]
                                    for j2 in range(len(onames))}})
            elif disappear[gi]:
                out_rows.append({"op": "-D", self.key_column: key,
                                 **{onames[j2]: olds_f[out_idx[j2]][gi]
                                    for j2 in range(len(onames))}})
            elif update[gi]:
                out_rows.append({"op": "-U", self.key_column: key,
                                 **{onames[j2]: olds_f[out_idx[j2]][gi]
                                    for j2 in range(len(onames))}})
                out_rows.append({"op": "+U", self.key_column: key,
                                 **{onames[j2]: news_f[out_idx[j2]][gi]
                                    for j2 in range(len(onames))}})
        if not out_rows:
            return []
        cols = {c: np.asarray([r[c] for r in out_rows])
                for c in out_rows[0]}
        return [RecordBatch(cols)]

    def snapshot_state(self) -> Dict[str, Any]:
        snap: Dict[str, Any] = {}
        if self.key_index is not None:
            n = self.key_index.num_keys
            snap["key_index"] = self.key_index.snapshot()
            snap["key_index_kind"] = type(self.key_index).__name__
            if self._state is not None:
                snap["state"] = [np.asarray(a)[:n] for a in self._state]
        return snap

    def restore_state(self, snap: Dict[str, Any]) -> None:
        import jax.numpy as jnp

        from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex

        if "groups" in snap:  # legacy host-dict snapshot format
            groups = snap["groups"]
            if groups:
                keys = np.asarray(list(groups))
                from flink_tpu.state.keyindex import make_key_index
                self.key_index = make_key_index(keys[0])
                slots = jnp.asarray(self.key_index.lookup_or_insert(keys))
                self._ensure(self.key_index.num_keys)
                state = list(self._state)
                si = 0
                for out, (_c, how) in self.agg_columns.items():
                    vals = np.asarray([groups[k][out] for k in groups],
                                      np.float32)
                    state[si] = state[si].at[slots].set(jnp.asarray(vals))
                    si += 2  # lo word stays 0 (normalized pair)
                self._state = tuple(state)
            return
        if "key_index" not in snap:
            return
        if snap["key_index_kind"] == "ObjectKeyIndex":
            self.key_index = ObjectKeyIndex.restore(snap["key_index"])
        else:
            self.key_index = KeyIndex.restore(snap["key_index"])
        n = self.key_index.num_keys
        self._state = None
        self._ensure(max(n, 1))
        if "state" in snap:
            arrs = list(snap["state"])
            if len(arrs) != 2 * len(self.agg_columns):
                # pre-r3 layout: min/max columns had a single word — insert
                # zero low words so every column is an (hi, lo) pair
                upgraded, i = [], 0
                for out, (_c, how) in self.agg_columns.items():
                    upgraded.append(arrs[i])
                    if self._MODES[how] == "add":
                        upgraded.append(arrs[i + 1])
                        i += 2
                    else:
                        upgraded.append(np.zeros_like(arrs[i]))
                        i += 1
                arrs = upgraded
            self._state = tuple(
                a.at[:n].set(jnp.asarray(s))
                for a, s in zip(self._state, arrs))


class TopNOperator(StreamOperator):
    """Streaming Top-N per partition (``AppendOnlyTopNFunction`` /
    ``StreamExecRank`` analog): keeps the best ``n`` rows per partition key,
    emits changelog rows (``+I`` entering, ``-D`` leaving) as ranks change;
    ``end_input`` emits the final ranked table (rank column included)."""

    def __init__(self, n: int, partition_column: Optional[str],
                 order_column: str, ascending: bool = False,
                 emit_changelog: bool = True, name: str = "top-n"):
        self.n = n
        self.partition_column = partition_column
        self.order_column = order_column
        self.ascending = ascending
        self.emit_changelog = emit_changelog
        self.name = name
        #: partition -> list of (sort_value, seq, row) kept sorted best-first
        self._tops: Dict[Any, List[Tuple[Any, int, dict]]] = {}
        self._seq = 0

    def _better(self, a, b) -> bool:
        return a < b if self.ascending else a > b

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        # vectorized pre-filter: rows strictly worse than a FULL partition's
        # current cutoff can never enter — drop them before the per-row
        # merge (the merge itself is inherently sequential: each admission
        # can change the cutoff)
        vals = np.asarray(batch.column(self.order_column))
        if self.partition_column is None:
            top = self._tops.get(None)
            if top is not None and len(top) >= self.n:
                thr = top[-1][0]
                keep = vals < thr if self.ascending else vals > thr
                if not keep.all():
                    batch = batch.select(keep)
                    if len(batch) == 0:
                        return []
        elif getattr(self, "_any_full", False):
            # only worth the per-row threshold lookup once SOME partition
            # filled up (before that the filter can never drop anything)
            parts_col = np.asarray(batch.column(self.partition_column))
            thr = np.asarray([
                (self._tops[p][-1][0]
                 if p in self._tops and len(self._tops[p]) >= self.n
                 else None)
                for p in parts_col.tolist()], object)
            has = np.asarray([t is not None for t in thr.tolist()])
            if has.any():
                tv = np.where(has, thr, vals[0]).astype(vals.dtype)
                keep = ~has | (vals < tv if self.ascending else vals > tv)
                if not keep.all():
                    batch = batch.select(keep)
                    if len(batch) == 0:
                        return []
        rows = batch.to_rows()
        out_rows: List[Dict[str, Any]] = []
        for row in rows:
            part = (row[self.partition_column]
                    if self.partition_column else None)
            top = self._tops.setdefault(part, [])
            val = row[self.order_column]
            self._seq += 1
            if len(top) < self.n or self._better(val, top[-1][0]):
                top.append((val, self._seq, row))
                top.sort(key=lambda e: (e[0], e[1]),
                         reverse=not self.ascending)
                if self.emit_changelog:
                    out_rows.append({"op": "+I", **row})
                if len(top) >= self.n:
                    self._any_full = True
                if len(top) > self.n:
                    _, _, evicted = top.pop()
                    if self.emit_changelog:
                        out_rows.append({"op": "-D", **evicted})
        if not out_rows or not self.emit_changelog:
            return []
        cols = {c: np.asarray([r.get(c) for r in out_rows])
                for c in out_rows[0]}
        return [RecordBatch(cols)]

    def end_input(self) -> List[StreamElement]:
        out_rows = []
        for part in sorted(self._tops, key=lambda p: (p is None, p)):
            for rank, (_v, _s, row) in enumerate(self._tops[part], start=1):
                out_rows.append({**row, "rank": rank, "op": "final"})
        if not out_rows:
            return []
        cols = {c: np.asarray([r.get(c) for r in out_rows])
                for c in out_rows[0]}
        return [RecordBatch(cols)]

    def snapshot_state(self) -> Dict[str, Any]:
        return {"tops": {k: list(v) for k, v in self._tops.items()},
                "seq": self._seq}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._tops = {k: list(v) for k, v in snap.get("tops", {}).items()}
        self._seq = snap.get("seq", 0)


class DeduplicateOperator(StreamOperator):
    """Deduplication per key (``DeduplicateKeepFirstRow/KeepLastRow``):
    ``keep='first'`` emits a key's first row immediately and drops the rest;
    ``keep='last'`` retains the latest row per key and emits the final table
    at end-of-input (streaming updates would be a changelog; bounded gives
    batch semantics)."""

    def __init__(self, key_column: str, keep: str = "first",
                 order_column: Optional[str] = None, name: str = "deduplicate"):
        if keep not in ("first", "last"):
            raise ValueError("keep must be 'first' or 'last'")
        self.key_column = key_column
        self.keep = keep
        self.order_column = order_column
        self.name = name
        #: vectorized membership: key -> dense slot (insertion-ordered), the
        #: same probe the window state uses (state/keyindex) — no per-row
        #: Python dict lookups
        self._ki = None
        #: keep='last': columnar current-row store, one array per column,
        #: indexed by key slot; plus the per-slot order value
        self._cols: Dict[str, np.ndarray] = {}
        self._ordv: Optional[np.ndarray] = None

    def _slots(self, keys: np.ndarray) -> np.ndarray:
        from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex

        if self._ki is None:
            # dtype (not a sample element) decides: an object array of
            # tuples (composite DISTINCT keys) must use the object index
            self._ki = (KeyIndex() if keys.dtype.kind in "iu"
                        else ObjectKeyIndex())
        return self._ki.lookup_or_insert(keys)

    @staticmethod
    def _grow(arr: np.ndarray, n: int, fill) -> np.ndarray:
        if arr.shape[0] >= n:
            return arr
        out = np.full((max(n, arr.shape[0] * 2),) + arr.shape[1:], fill,
                      dtype=arr.dtype)
        out[: arr.shape[0]] = arr
        return out

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        keys = np.asarray(batch.column(self.key_column))
        prev_n = self._ki.num_keys if self._ki is not None else 0
        slots = self._slots(keys)
        if self.keep == "first":
            # first occurrence in-batch of a key unseen before this batch
            _, first_idx = np.unique(slots, return_index=True)
            mask = np.zeros(len(batch), bool)
            mask[first_idx] = True
            mask &= slots >= prev_n
            return [batch.select(mask)] if mask.any() else []
        # keep == 'last': per batch, the winning row per key is the max by
        # (order value, position); then compare against the retained order
        n = len(batch)
        if self.order_column is not None:
            ordv = np.asarray(batch.column(self.order_column))
        else:
            # arrival order must be GLOBAL across batches, not in-batch row
            # position — a later batch's row always beats an earlier one
            base = getattr(self, "_arrival", 0)
            ordv = base + np.arange(n)
            self._arrival = base + n
        # lexsort: last key per (slot, order, position) group wins
        order = np.lexsort((np.arange(n), ordv, slots))
        ss = slots[order]
        last_mask = np.r_[ss[1:] != ss[:-1], True]
        win = order[last_mask]                    # winning row index per slot
        wslots, word = slots[win], ordv[win]
        nk = self._ki.num_keys
        if self._ordv is None:
            self._ordv = np.full(max(nk, 64), None, object)
        self._ordv = self._grow(self._ordv, nk, None)
        cur = self._ordv[wslots]
        upd = np.asarray([c is None or o >= c
                          for o, c in zip(word.tolist(), cur.tolist())])
        if not upd.any():
            return []
        uw, uord = wslots[upd], word[upd]
        self._ordv[uw] = uord
        for c, v in batch.columns.items():
            arr = self._cols.get(c)
            if arr is None:
                arr = np.full(max(nk, 64), None, object)
            arr = self._grow(arr, nk, None)
            arr[uw] = np.asarray(v, object)[win[upd]]
            self._cols[c] = arr
        return []

    def end_input(self) -> List[StreamElement]:
        if self.keep == "first" or self._ki is None:
            return []
        n = self._ki.num_keys
        if n == 0 or not self._cols:
            return []

        def densify(a: np.ndarray) -> np.ndarray:
            # the store is object-dtype (mixed batches may differ); emit
            # with the natural inferred dtype so downstream device
            # consumers can jnp.asarray the column
            try:
                out = np.asarray(a.tolist())
            except (ValueError, TypeError):
                return a
            return a if out.dtype.kind == "O" and a.dtype.kind == "O" else out

        cols = {c: densify(arr[:n]) for c, arr in self._cols.items()}
        return [RecordBatch(cols)]

    def snapshot_state(self) -> Dict[str, Any]:
        snap: Dict[str, Any] = {}
        if self._ki is not None:
            snap["key_index"] = self._ki.snapshot()
            snap["key_index_kind"] = type(self._ki).__name__
            n = self._ki.num_keys
            # COPIES, not views: later batches mutate the store in place,
            # which must never reach into an already-taken checkpoint
            snap["cols"] = {c: np.asarray(a[:n]).copy()
                            for c, a in self._cols.items()}
            snap["ordv"] = (None if self._ordv is None
                            else np.asarray(self._ordv[:n]).copy())
            snap["arrival"] = getattr(self, "_arrival", 0)
        return snap

    def restore_state(self, snap: Dict[str, Any]) -> None:
        from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex

        if "seen" in snap:  # legacy dict snapshot
            seen = snap["seen"]
            if seen:
                keys = np.asarray(list(seen))
                self._slots(keys)
                rows = list(seen.values())
                if rows and rows[0]:
                    n = self._ki.num_keys
                    for c in rows[0]:
                        arr = np.full(max(n, 64), None, object)
                        arr[:n] = [r.get(c) for r in rows]
                        self._cols[c] = arr
                order = snap.get("order", {})
                self._ordv = np.full(max(len(seen), 64), None, object)
                for i, k in enumerate(seen):
                    self._ordv[i] = order.get(k)
            return
        if "key_index" not in snap:
            return
        cls = (ObjectKeyIndex if snap["key_index_kind"] == "ObjectKeyIndex"
               else KeyIndex)
        self._ki = cls.restore(snap["key_index"])
        self._cols = {c: np.asarray(a, object).copy()
                      for c, a in snap.get("cols", {}).items()}
        ov = snap.get("ordv")
        self._ordv = None if ov is None else np.asarray(ov, object).copy()
        self._arrival = snap.get("arrival", 0)


class SortLimitOperator(StreamOperator):
    """Bounded ORDER BY / LIMIT inside a query pipeline (subquery result
    semantics): buffer, sort at end of input, truncate."""

    def __init__(self, order_by: List[Tuple[str, bool]],
                 limit: Optional[int], name: str = "sort-limit"):
        self.order_by = list(order_by)
        self.limit = limit
        self.name = name
        self._buf: List[RecordBatch] = []

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch):
            self._buf.append(batch)
        return []

    def end_input(self) -> List[StreamElement]:
        if not self._buf:
            return []
        b = RecordBatch.concat(self._buf)
        self._buf = []
        order = np.arange(len(b))
        for name, asc in reversed(self.order_by):
            col = np.asarray(b.column(name))[order]
            o = np.argsort(col, kind="stable")
            if not asc:
                o = o[::-1]
            order = order[o]
        if self.limit is not None:
            order = order[: self.limit]
        return [b.take(order)]

    def snapshot_state(self) -> Dict[str, Any]:
        if not self._buf:
            return {}
        b = RecordBatch.concat(self._buf)
        return {"cols": {k: np.asarray(v) for k, v in b.columns.items()},
                "ts": None if b.timestamps is None else np.asarray(b.timestamps)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        if snap.get("cols"):
            self._buf = [RecordBatch(snap["cols"], timestamps=snap.get("ts"))]


class MiniBatchOperator(StreamOperator):
    """Bundle small batches into bigger ones before an expensive stateful
    operator (``MiniBatch`` bundle operators, ``operators/bundle/``):
    flushes at ``max_rows`` OR on any watermark/barrier boundary — control
    elements must never overtake their data."""

    is_stateless = True

    def __init__(self, max_rows: int = 16_384, name: str = "mini-batch"):
        self.max_rows = max_rows
        self.name = name
        self._buf: List[RecordBatch] = []
        self._rows = 0

    def _flush(self) -> List[StreamElement]:
        if not self._buf:
            return []
        out = [RecordBatch.concat(self._buf)] if len(self._buf) > 1 \
            else [self._buf[0]]
        self._buf = []
        self._rows = 0
        return out

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        self._buf.append(batch)
        self._rows += len(batch)
        if self._rows >= self.max_rows:
            return self._flush()
        return []

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        return self._flush()

    def end_input(self) -> List[StreamElement]:
        return self._flush()

    def snapshot_state(self) -> Dict[str, Any]:
        # barrier boundary: flush downstream is not possible from snapshot;
        # persist the bundle instead (reference finishes bundles pre-barrier)
        if not self._buf:
            return {}
        b = RecordBatch.concat(self._buf)
        return {"bundle": {k: np.asarray(v) for k, v in b.columns.items()},
                "ts": None if b.timestamps is None else np.asarray(b.timestamps)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        if snap.get("bundle"):
            self._buf = [RecordBatch(snap["bundle"], timestamps=snap.get("ts"))]
            self._rows = sum(len(b) for b in self._buf)


class OverAggSpec:
    """One aggregate column of an OVER window (``StreamExecOverAggregate``).

    ``func``: SUM/COUNT/AVG/MIN/MAX/ROW_NUMBER; ``in_col``: pre-projected
    numeric input column (None for COUNT(*)/ROW_NUMBER).  Frame: both bounds
    None = unbounded preceding; ``rows`` = ROWS n PRECEDING AND CURRENT ROW;
    ``range_ms`` = RANGE INTERVAL n PRECEDING AND CURRENT ROW.  ``is_rows``
    picks per-row vs peer-inclusive semantics for unbounded frames
    (``RowTimeRowsUnboundedPrecedingFunction`` vs ``RowTimeRange...``)."""

    __slots__ = ("out_name", "func", "in_col", "rows", "range_ms", "is_rows",
                 "distinct")

    def __init__(self, out_name: str, func: str, in_col: Optional[str],
                 rows: Optional[int] = None, range_ms: Optional[int] = None,
                 is_rows: bool = False, distinct: bool = False):
        self.out_name = out_name
        self.func = func
        self.in_col = in_col
        self.rows = rows
        self.range_ms = range_ms
        self.is_rows = is_rows
        #: agg(DISTINCT x) over an UNBOUNDED frame: only each value's FIRST
        #: occurrence per partition contributes (SUM/COUNT/AVG); MIN/MAX are
        #: distinct-invariant
        self.distinct = distinct


def _sliding_window(padded: np.ndarray, width: int) -> np.ndarray:
    from numpy.lib.stride_tricks import sliding_window_view
    return sliding_window_view(padded, width)


class OverAggregateOperator(StreamOperator):
    """Per-partition running aggregates over time-ordered rows — the
    ``StreamExecOverAggregate`` analog (reference:
    ``flink-table-planner-blink/.../stream/StreamExecOverAggregate.java``,
    runtime ``RowTime{Range,Rows}{Unbounded,Bounded}PrecedingFunction``).

    Event-time mode buffers rows per partition and, on each watermark,
    emits every buffered row with ``ts <= watermark`` in timestamp order,
    each extended with its frame aggregates (vectorized: cumulative sums /
    sliding-window reductions over the sorted flush, not a per-row state
    probe).  Late rows (ts at or below the last watermark) are dropped, as
    in the reference.  Arrival mode (no time attribute) emits immediately
    in arrival order.
    """

    def __init__(self, specs: List[OverAggSpec],
                 partition_column: Optional[str],
                 event_time: bool = True, name: str = "sql-over-agg"):
        self.specs = specs
        self.partition_column = partition_column
        self.event_time = event_time
        self.name = name
        if not event_time and any(s.range_ms is not None for s in specs):
            raise ValueError("RANGE frames need an event-time ORDER BY")
        # per-partition-key state:
        self._pending: Dict[Any, List[RecordBatch]] = {}
        # spec index -> key -> running acc (unbounded) or None
        self._accs: List[Dict[Any, Any]] = [dict() for _ in specs]
        # spec index -> key -> (ts_buf, val_buf) tail kept for bounded frames
        self._tails: List[Dict[Any, Any]] = [dict() for _ in specs]
        # DISTINCT specs: spec index -> key -> set of values already seen
        # (the reference's distinct-state MapView)
        self._seen: List[Dict[Any, set]] = [dict() for _ in specs]
        self._last_wm = LONG_MIN
        self._dropped_late = 0

    # ------------------------------------------------------------- ingest
    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        if not self.event_time:
            return self._emit(batch, order=np.arange(len(batch)))
        ts = np.asarray(batch.timestamps)
        fresh = ts > self._last_wm
        if not fresh.all():
            self._dropped_late += int((~fresh).sum())
            batch = batch.select(fresh)
            if len(batch) == 0:
                return []
        if self.partition_column is None:
            self._pending.setdefault(None, []).append(batch)
            return []
        keys = np.asarray(batch.columns[self.partition_column])
        uniq, inv = np.unique(keys, return_inverse=True)
        for i, k in enumerate(uniq.tolist()):
            self._pending.setdefault(k, []).append(batch.select(inv == i))
        return []

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        out = self._flush(watermark.timestamp)
        self._last_wm = max(self._last_wm, watermark.timestamp)
        return out

    def end_input(self) -> List[StreamElement]:
        return self._flush(None)

    def _flush(self, up_to: Optional[int]) -> List[StreamElement]:
        out: List[StreamElement] = []
        for key in list(self._pending):
            merged = RecordBatch.concat(self._pending[key])
            ts = np.asarray(merged.timestamps)
            if up_to is None:
                ready, rest = merged, None
            else:
                mask = ts <= up_to
                if not mask.any():
                    continue
                ready = merged.select(mask)
                rest = merged.select(~mask) if not mask.all() else None
            if rest is not None and len(rest):
                self._pending[key] = [rest]
            else:
                del self._pending[key]
            order = np.argsort(np.asarray(ready.timestamps), kind="stable")
            out.extend(self._emit(ready, order, key=key))
        return out

    # ------------------------------------------------------------ compute
    def _emit(self, batch: RecordBatch, order: np.ndarray,
              key: Any = None) -> List[StreamElement]:
        batch = batch.take(order)
        m = len(batch)
        ts = (np.asarray(batch.timestamps) if batch.timestamps is not None
              else np.arange(m, dtype=np.int64))
        cols = dict(batch.columns)
        if not self.event_time and self.partition_column is not None:
            # arrival mode still aggregates per partition value
            keys = np.asarray(cols[self.partition_column])
            uniq, inv = np.unique(keys, return_inverse=True)
            if len(uniq) > 1:
                parts = [self._emit(batch.select(inv == i), np.arange(int((inv == i).sum())), key=k)
                         for i, k in enumerate(uniq.tolist())]
                return [RecordBatch.concat([p for part in parts for p in part])]
            key = uniq[0].item() if len(uniq) else None
        for i, spec in enumerate(self.specs):
            vals = (np.asarray(cols[spec.in_col], np.float64)
                    if spec.in_col is not None else np.ones(m, np.float64))
            if spec.func == "ROW_NUMBER":
                start = self._accs[i].get(key, 0)
                cols[spec.out_name] = start + np.arange(1, m + 1, dtype=np.int64)
                self._accs[i][key] = start + m
            elif spec.rows is not None:
                cols[spec.out_name] = self._rows_frame(i, spec, key, vals)
            elif spec.range_ms is not None:
                cols[spec.out_name] = self._range_frame(i, spec, key, ts, vals)
            else:
                first = (self._first_occurrence(i, key, vals)
                         if spec.distinct and spec.func not in ("MIN", "MAX")
                         else None)
                cols[spec.out_name] = self._unbounded(i, spec, key, ts, vals,
                                                      first)
        return [RecordBatch(cols, batch.timestamps, batch.key_ids,
                            batch.key_groups)]

    def _first_occurrence(self, i: int, key: Any,
                          vals: np.ndarray) -> np.ndarray:
        """bool mask: row carries the FIRST occurrence of its value in this
        partition (across flushes, via the per-spec seen set)."""
        seen = self._seen[i].setdefault(key, set())
        uniq, first_idx = np.unique(vals, return_index=True)
        novel = np.asarray([v not in seen for v in uniq.tolist()])
        seen.update(uniq[novel].tolist())
        mask = np.zeros(len(vals), bool)
        mask[first_idx[novel]] = True
        return mask

    def _unbounded(self, i: int, spec: OverAggSpec, key: Any, ts, vals,
                   first: Optional[np.ndarray] = None):
        """UNBOUNDED PRECEDING: running accumulator carried across flushes;
        RANGE flavor gives every peer group (equal ts) the group's total.
        ``first`` (DISTINCT): only first-occurrence rows contribute."""
        func = spec.func
        if func in ("SUM", "AVG", "COUNT"):
            if first is not None:
                vals = np.where(first, vals, 0.0)
            ps, pc = self._accs[i].get(key, (0.0, 0))
            cum_s = ps + np.cumsum(vals)
            cum_c = pc + (np.cumsum(first).astype(np.int64)
                          if first is not None
                          else np.arange(1, len(vals) + 1, dtype=np.int64))
            self._accs[i][key] = (float(cum_s[-1]), int(cum_c[-1]))
        elif func == "MIN":
            prev = self._accs[i].get(key, np.inf)
            cum_s = np.minimum.accumulate(np.minimum(vals, prev))
            self._accs[i][key] = float(cum_s[-1])
            cum_c = None
        elif func == "MAX":
            prev = self._accs[i].get(key, -np.inf)
            cum_s = np.maximum.accumulate(np.maximum(vals, prev))
            self._accs[i][key] = float(cum_s[-1])
            cum_c = None
        else:
            raise ValueError(f"unsupported OVER aggregate {func}")
        if not spec.is_rows and self.event_time:
            # peer-inclusive: each row reads the value at its LAST peer
            last_peer = np.searchsorted(ts, ts, side="right") - 1
            cum_s = cum_s[last_peer]
            if cum_c is not None:
                cum_c = cum_c[last_peer]
        if func == "COUNT":
            return cum_c.astype(np.int64)
        if func == "AVG":
            return cum_s / cum_c
        return cum_s

    def _rows_frame(self, i: int, spec: OverAggSpec, key: Any, vals):
        """ROWS n PRECEDING AND CURRENT ROW: NaN-padded sliding window over
        (kept tail ++ new rows); the tail keeps the last n values."""
        n = spec.rows
        tail = self._tails[i].get(key)
        prev = tail if tail is not None else np.empty(0, np.float64)
        allv = np.concatenate([prev, vals])
        # windows of width n+1 ending at each NEW row
        width = n + 1
        padded = np.concatenate([np.full(width - 1, np.nan), allv])
        win = _sliding_window(padded, width)[len(prev):]
        self._tails[i][key] = allv[-n:] if n > 0 else np.empty(0, np.float64)
        func = spec.func
        if spec.distinct and func in ("SUM", "COUNT", "AVG"):
            # per-frame dedup: sort each window row (NaN pads sort last),
            # NaN out equal neighbours — each distinct value counts once
            # INSIDE its frame, whatever its multiplicity
            sw = np.sort(win, axis=1)
            dup = np.zeros(sw.shape, bool)
            dup[:, 1:] = sw[:, 1:] == sw[:, :-1]
            win = np.where(dup, np.nan, sw)
        if func == "SUM":
            return np.nansum(win, axis=1)
        if func == "COUNT":
            return (~np.isnan(win)).sum(axis=1).astype(np.int64)
        if func == "AVG":
            return np.nansum(win, axis=1) / (~np.isnan(win)).sum(axis=1)
        if func == "MIN":
            return np.nanmin(win, axis=1)
        if func == "MAX":
            return np.nanmax(win, axis=1)
        raise ValueError(f"unsupported OVER aggregate {func}")

    def _range_frame(self, i: int, spec: OverAggSpec, key: Any, ts, vals):
        """RANGE r PRECEDING AND CURRENT ROW over event time, peer-inclusive;
        the tail keeps rows within r of the newest emitted timestamp."""
        r = spec.range_ms
        tail = self._tails[i].get(key)
        pts, pvs = tail if tail is not None else (np.empty(0, np.int64),
                                                 np.empty(0, np.float64))
        all_ts = np.concatenate([pts, np.asarray(ts, np.int64)])
        all_vs = np.concatenate([pvs, vals])
        lo = np.searchsorted(all_ts, np.asarray(ts, np.int64) - r, side="left")
        hi = np.searchsorted(all_ts, np.asarray(ts, np.int64), side="right")
        keep = all_ts > (all_ts[-1] - r if len(all_ts) else 0)
        self._tails[i][key] = (all_ts[keep], all_vs[keep])
        func = spec.func
        if spec.distinct and func in ("SUM", "AVG", "COUNT"):
            # variable-width frames: per-row distinct set (the per-frame
            # multiset, same per-row granularity as the MIN/MAX path below)
            s = np.empty(len(ts), np.float64)
            c = np.empty(len(ts), np.int64)
            for j in range(len(ts)):
                u = np.unique(all_vs[lo[j]:hi[j]])
                s[j] = u.sum()
                c[j] = u.size
            if func == "SUM":
                return s
            if func == "COUNT":
                return c
            return s / c
        if func in ("SUM", "AVG", "COUNT"):
            cum = np.concatenate([[0.0], np.cumsum(all_vs)])
            s = cum[hi] - cum[lo]
            c = (hi - lo).astype(np.int64)
            if func == "SUM":
                return s
            if func == "COUNT":
                return c
            return s / c
        red = np.minimum if func == "MIN" else np.maximum
        out = np.empty(len(ts), np.float64)
        for j in range(len(ts)):
            out[j] = red.reduce(all_vs[lo[j]:hi[j]])
        return out

    # ------------------------------------------------------------ snapshot
    def snapshot_state(self) -> Dict[str, Any]:
        def pack(batches):
            b = RecordBatch.concat(batches)
            return ({k: np.asarray(v) for k, v in b.columns.items()},
                    None if b.timestamps is None else np.asarray(b.timestamps))
        return {"pending": {k: pack(v) for k, v in self._pending.items()},
                "accs": [dict(d) for d in self._accs],
                "tails": [dict(d) for d in self._tails],
                "seen": [{k: sorted(s) for k, s in d.items()}
                         for d in self._seen],
                "last_wm": self._last_wm,
                "dropped_late": self._dropped_late}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._pending = {k: [RecordBatch(cols, timestamps=ts)]
                         for k, (cols, ts) in snap.get("pending", {}).items()}
        self._accs = [dict(d) for d in snap.get(
            "accs", [dict() for _ in self.specs])]
        self._tails = [dict(d) for d in snap.get(
            "tails", [dict() for _ in self.specs])]
        self._seen = [{k: set(s) for k, s in d.items()}
                      for d in snap.get("seen",
                                        [dict() for _ in self.specs])]
        self._last_wm = snap.get("last_wm", LONG_MIN)
        self._dropped_late = snap.get("dropped_late", 0)


class HopWindowExpandOperator(StreamOperator):
    """Row → per-covering-HOP-window copies, for window-scoped dedup
    (DISTINCT aggregates in HOP windows).

    Each copy carries a synthetic timestamp ``t' = w*slide + size - 1``
    (its window's max timestamp) in a ``__hopts`` column AND as the batch
    timestamp, so a TUMBLE(slide) aggregation downstream buckets each copy
    into a bucket unique to its window: the bucket's end is ``>= t'``, so a
    REAL-time watermark never fires a window before its true close (at most
    ``slide-1`` ms after), and a copy whose real window already closed is
    late by exactly the reference's rule.  The real HOP bounds are
    recovered from the bucket start downstream
    (``w = bucket_start/slide - (size-1)//slide``)."""

    def __init__(self, size_ms: int, slide_ms: int,
                 time_col: str = "__hopts", name: str = "hop-expand"):
        self.size_ms = int(size_ms)
        self.slide_ms = int(slide_ms)
        self.time_col = time_col
        self.name = name

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        if batch.timestamps is None:
            raise ValueError("HOP expansion needs event-time timestamps")
        ts = np.asarray(batch.timestamps, np.int64)
        size, slide = self.size_ms, self.slide_ms
        max_covers = -(-size // slide)
        out: List[StreamElement] = []
        base_w = np.floor_divide(ts, slide)
        for k in range(max_covers):
            w = base_w - k
            valid = w * slide + size > ts
            if not valid.any():
                continue
            tprime = (w * slide + size - 1)[valid]
            cols = {c: np.asarray(v)[valid]
                    for c, v in batch.columns.items()}
            cols[self.time_col] = tprime
            out.append(RecordBatch(cols, timestamps=tprime))
        return out


class BranchMergeOperator(StreamOperator):
    """Streaming inner merge of two aggregate branches on a merge-key column
    — the glue for mixed DISTINCT/plain aggregate queries, where the planner
    splits one logical group-aggregate into a plain branch and a
    dedup-then-aggregate branch (the reference folds both into one
    ``AggsHandleFunction`` with distinct-state MapViews; here each branch
    stays a dense vectorized aggregate and the fired rows re-join).

    Both branches fire the same (key, window) set, so every buffered row
    pairs up exactly once; ``extra_cols`` names the columns only the right
    branch contributes.  Column data moves by vectorized fancy-indexing —
    the only per-row Python is a key-hash probe into the pending index."""

    is_two_input = True

    def __init__(self, merge_column: str, extra_cols: List[str],
                 name: str = "sql-branch-merge"):
        self.merge_column = merge_column
        self.extra_cols = extra_cols
        self.name = name
        #: per side: buffered batches with un-merged rows, and an index
        #: key -> (batch position in the buffer, row) of those rows
        self._bufs: Tuple[List[RecordBatch], List[RecordBatch]] = ([], [])
        self._unmatched: Tuple[Dict[Any, Tuple[int, int]],
                               Dict[Any, Tuple[int, int]]] = ({}, {})

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        s = input_index
        o = 1 - s
        keys = np.asarray(batch.columns[self.merge_column])
        other_idx = self._unmatched[o]
        mine_rows: List[int] = []              # rows of THIS batch that matched
        other_rows: List[Tuple[int, int]] = []  # (buf_i, row_i) on the other side
        buf_pos = len(self._bufs[s])
        mine_idx = self._unmatched[s]
        for i in range(len(keys)):
            hit = other_idx.pop(keys[i], None)
            if hit is None:
                mine_idx[keys[i]] = (buf_pos, i)
            else:
                mine_rows.append(i)
                other_rows.append(hit)
        if len(mine_rows) < len(keys):
            self._bufs[s].append(batch)
        if not mine_rows:
            return []

        # gather the other side's matched rows per buffered batch (vectorized)
        order = np.argsort([b * (1 << 32) + r for b, r in other_rows],
                           kind="stable")
        mine_sel = np.asarray(mine_rows, np.int64)[order]
        other_sorted = [other_rows[i] for i in order]
        other_parts: List[RecordBatch] = []
        mine_parts: List[np.ndarray] = []
        j = 0
        while j < len(other_sorted):
            bi = other_sorted[j][0]
            k = j
            while k < len(other_sorted) and other_sorted[k][0] == bi:
                k += 1
            rows = np.asarray([r for _, r in other_sorted[j:k]], np.int64)
            other_parts.append(self._bufs[o][bi].take(rows))
            mine_parts.append(mine_sel[j:k])
            j = k
        mine_take = batch.take(np.concatenate(mine_parts))
        other_take = RecordBatch.concat(other_parts)
        left, right = ((mine_take, other_take) if s == 0
                       else (other_take, mine_take))
        cols = dict(left.columns)
        for c in self.extra_cols:
            cols[c] = np.asarray(right.columns[c])
        if not other_idx and not mine_idx:
            # everything paired up — drop the consumed buffers
            self._bufs[0].clear()
            self._bufs[1].clear()
        return [RecordBatch(cols)]

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)

    def _pack_pending(self, side: int) -> List[Dict[str, Any]]:
        rows = []
        for k, (bi, ri) in self._unmatched[side].items():
            b = self._bufs[side][bi]
            rows.append({n: np.asarray(v)[ri] for n, v in b.columns.items()})
        return rows

    def snapshot_state(self) -> Dict[str, Any]:
        # persist only un-merged rows, materialized (small residual set)
        return {"left_rows": self._pack_pending(0),
                "right_rows": self._pack_pending(1)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._bufs = ([], [])
        self._unmatched = ({}, {})
        for side, field in ((0, "left_rows"), (1, "right_rows")):
            rows = snap.get(field) or []
            if not rows:
                continue
            cols: Dict[str, np.ndarray] = {}
            for n in rows[0]:
                vals = [r[n] for r in rows]
                if any(isinstance(v, tuple) for v in vals):
                    # tuple cells (composite keys) must stay 1-D object
                    arr = np.empty(len(vals), object)
                    arr[:] = vals
                else:
                    arr = np.asarray(vals)
                cols[n] = arr
            b = RecordBatch(cols)
            self._bufs[side].append(b)
            keys = np.asarray(b.columns[self.merge_column])
            for i in range(len(b)):
                self._unmatched[side][keys[i]] = (0, i)
