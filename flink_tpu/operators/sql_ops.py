"""SQL runtime operators: joins, changelog aggregation, Top-N, dedup,
mini-batch bundling.

Analogs of the blink table runtime (``flink-table-runtime-blink``):
``StreamingJoinOperator`` (regular equi-join), ``GroupAggFunction`` with
retraction (``+I/-U/+U/-D`` changelog rows), ``AppendOnlyTopNFunction`` /
``RankOperator``, ``DeduplicateKeepFirstRow/KeepLastRow`` functions, and the
``bundle/`` mini-batch operators.  Batched columnar: each structure keys on
vectorized column ops, not per-record state probes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import (LONG_MIN, RecordBatch, StreamElement,
                                  Watermark)
from flink_tpu.operators.base import StreamOperator
from flink_tpu.operators.joins import _join_pairs, _merge_columns


class SqlJoinOperator(StreamOperator):
    """Bounded-table equi-join (``StreamExecJoin`` over bounded inputs):
    both sides buffer; the join emits once at end-of-input — batch SQL
    semantics.  ``how``: inner / left / right / full."""

    is_two_input = True

    def __init__(self, left_key: str, right_key: str, how: str = "inner",
                 right_rename: Optional[Dict[str, str]] = None,
                 left_columns: Optional[List[str]] = None,
                 right_columns: Optional[List[str]] = None,
                 name: str = "sql-join"):
        self.left_key = left_key
        self.right_key = right_key
        self.how = how
        self.right_rename = right_rename or {}
        #: declared schemas: outer joins must emit null-filled columns for an
        #: EMPTY side, which cannot be inferred from received batches
        self.left_columns = left_columns
        self.right_columns = right_columns
        self.name = name
        self._left: List[RecordBatch] = []
        self._right: List[RecordBatch] = []
        self._ended = 0

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        if len(batch):
            (self._left if input_index == 0 else self._right).append(batch)
        return []

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)

    def end_input(self) -> List[StreamElement]:
        # called once per vertex after ALL inputs ended
        l = RecordBatch.concat(self._left) if self._left else None
        r = RecordBatch.concat(self._right) if self._right else None
        self._left, self._right = [], []
        return self._join(l, r)

    def _rename_right(self, cols: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return {self.right_rename.get(k, k): v for k, v in cols.items()}

    def _join(self, l: Optional[RecordBatch],
              r: Optional[RecordBatch]) -> List[StreamElement]:
        nl = len(l) if l is not None else 0
        nr = len(r) if r is not None else 0
        parts: List[Dict[str, np.ndarray]] = []
        li = ri = np.zeros(0, np.int64)
        if nl and nr:
            li, ri = _join_pairs(np.asarray(l.column(self.left_key)),
                                 np.asarray(r.column(self.right_key)))
        lcols = (self.left_columns if self.left_columns is not None
                 else (list(l.columns) if l is not None else []))
        rcols = (self.right_columns if self.right_columns is not None
                 else (list(r.columns) if r is not None else []))
        if li.size:
            cols = {k: np.asarray(v)[li] for k, v in l.columns.items()}
            cols.update(self._rename_right(
                {k: np.asarray(v)[ri] for k, v in r.columns.items()}))
            parts.append(cols)
        if self.how in ("left", "full") and nl:
            unmatched = np.setdiff1d(np.arange(nl), li)
            if unmatched.size:
                cols = {k: np.asarray(v)[unmatched]
                        for k, v in l.columns.items()}
                cols.update(self._rename_right(
                    {k: np.full(unmatched.size, None, object) for k in rcols}))
                parts.append(cols)
        if self.how in ("right", "full") and nr:
            unmatched = np.setdiff1d(np.arange(nr), ri)
            if unmatched.size:
                cols = {k: np.full(unmatched.size, None, object)
                        for k in lcols}
                cols.update(self._rename_right(
                    {k: np.asarray(v)[unmatched]
                     for k, v in r.columns.items()}))
                parts.append(cols)
        if not parts:
            return []
        batches = [RecordBatch(c) for c in parts]
        return [RecordBatch.concat(batches) if len(batches) > 1 else batches[0]]

    def snapshot_state(self) -> Dict[str, Any]:
        def pack(bs):
            if not bs:
                return None
            b = RecordBatch.concat(bs)
            return {k: np.asarray(v) for k, v in b.columns.items()}
        return {"left": pack(self._left), "right": pack(self._right)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._left = ([RecordBatch(snap["left"])] if snap.get("left") else [])
        self._right = ([RecordBatch(snap["right"])] if snap.get("right") else [])


class ChangelogGroupAggOperator(StreamOperator):
    """Non-windowed group aggregate emitting a CHANGELOG (retraction) stream
    (``GroupAggFunction`` analog): every batch updates the affected groups
    and emits ``+I`` for new groups, ``-U`` (old value) + ``+U`` (new value)
    for changed ones.  The ``op`` column carries the change kind."""

    def __init__(self, key_column: str, agg_columns: Dict[str, Tuple[str, str]],
                 name: str = "changelog-group-agg"):
        """agg_columns: out_name -> (input column, how in sum/count/min/max)."""
        self.key_column = key_column
        self.agg_columns = agg_columns
        self.name = name
        #: key -> {out_name: value}
        self._groups: Dict[Any, Dict[str, float]] = {}

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        keys = np.asarray(batch.column(self.key_column))
        uniq, inv = np.unique(keys, return_inverse=True)
        # per-batch partial per group
        partials: Dict[str, np.ndarray] = {}
        for out, (col, how) in self.agg_columns.items():
            vals = (np.ones(len(batch)) if col is None
                    else np.asarray(batch.column(col), np.float64))
            if how in ("sum", "count"):
                partials[out] = np.bincount(inv, weights=vals,
                                            minlength=len(uniq))
            elif how == "min":
                agg = np.full(len(uniq), np.inf)
                np.minimum.at(agg, inv, vals)
                partials[out] = agg
            elif how == "max":
                agg = np.full(len(uniq), -np.inf)
                np.maximum.at(agg, inv, vals)
                partials[out] = agg
            else:
                raise ValueError(f"unsupported changelog aggregate {how!r}")
        out_rows: List[Dict[str, Any]] = []
        for gi, key in enumerate(uniq.tolist()):
            old = self._groups.get(key)
            if old is None:
                new = {out: float(partials[out][gi])
                       for out in self.agg_columns}
                self._groups[key] = new
                out_rows.append({"op": "+I", self.key_column: key, **new})
            else:
                new = {}
                for out, (col, how) in self.agg_columns.items():
                    p = float(partials[out][gi])
                    new[out] = (old[out] + p if how in ("sum", "count")
                                else (min(old[out], p) if how == "min"
                                      else max(old[out], p)))
                if new != old:
                    out_rows.append({"op": "-U", self.key_column: key, **old})
                    out_rows.append({"op": "+U", self.key_column: key, **new})
                    self._groups[key] = new
        if not out_rows:
            return []
        cols = {c: np.asarray([r[c] for r in out_rows]) for c in out_rows[0]}
        return [RecordBatch(cols)]

    def snapshot_state(self) -> Dict[str, Any]:
        return {"groups": dict(self._groups)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._groups = dict(snap.get("groups", {}))


class TopNOperator(StreamOperator):
    """Streaming Top-N per partition (``AppendOnlyTopNFunction`` /
    ``StreamExecRank`` analog): keeps the best ``n`` rows per partition key,
    emits changelog rows (``+I`` entering, ``-D`` leaving) as ranks change;
    ``end_input`` emits the final ranked table (rank column included)."""

    def __init__(self, n: int, partition_column: Optional[str],
                 order_column: str, ascending: bool = False,
                 emit_changelog: bool = True, name: str = "top-n"):
        self.n = n
        self.partition_column = partition_column
        self.order_column = order_column
        self.ascending = ascending
        self.emit_changelog = emit_changelog
        self.name = name
        #: partition -> list of (sort_value, seq, row) kept sorted best-first
        self._tops: Dict[Any, List[Tuple[Any, int, dict]]] = {}
        self._seq = 0

    def _better(self, a, b) -> bool:
        return a < b if self.ascending else a > b

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        rows = batch.to_rows()
        out_rows: List[Dict[str, Any]] = []
        for row in rows:
            part = (row[self.partition_column]
                    if self.partition_column else None)
            top = self._tops.setdefault(part, [])
            val = row[self.order_column]
            self._seq += 1
            if len(top) < self.n or self._better(val, top[-1][0]):
                top.append((val, self._seq, row))
                top.sort(key=lambda e: (e[0], e[1]),
                         reverse=not self.ascending)
                if self.emit_changelog:
                    out_rows.append({"op": "+I", **row})
                if len(top) > self.n:
                    _, _, evicted = top.pop()
                    if self.emit_changelog:
                        out_rows.append({"op": "-D", **evicted})
        if not out_rows or not self.emit_changelog:
            return []
        cols = {c: np.asarray([r.get(c) for r in out_rows])
                for c in out_rows[0]}
        return [RecordBatch(cols)]

    def end_input(self) -> List[StreamElement]:
        out_rows = []
        for part in sorted(self._tops, key=lambda p: (p is None, p)):
            for rank, (_v, _s, row) in enumerate(self._tops[part], start=1):
                out_rows.append({**row, "rank": rank, "op": "final"})
        if not out_rows:
            return []
        cols = {c: np.asarray([r.get(c) for r in out_rows])
                for c in out_rows[0]}
        return [RecordBatch(cols)]

    def snapshot_state(self) -> Dict[str, Any]:
        return {"tops": {k: list(v) for k, v in self._tops.items()},
                "seq": self._seq}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._tops = {k: list(v) for k, v in snap.get("tops", {}).items()}
        self._seq = snap.get("seq", 0)


class DeduplicateOperator(StreamOperator):
    """Deduplication per key (``DeduplicateKeepFirstRow/KeepLastRow``):
    ``keep='first'`` emits a key's first row immediately and drops the rest;
    ``keep='last'`` retains the latest row per key and emits the final table
    at end-of-input (streaming updates would be a changelog; bounded gives
    batch semantics)."""

    def __init__(self, key_column: str, keep: str = "first",
                 order_column: Optional[str] = None, name: str = "deduplicate"):
        if keep not in ("first", "last"):
            raise ValueError("keep must be 'first' or 'last'")
        self.key_column = key_column
        self.keep = keep
        self.order_column = order_column
        self.name = name
        self._seen: Dict[Any, dict] = {}
        self._order: Dict[Any, Any] = {}

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        keys = np.asarray(batch.column(self.key_column))
        if self.keep == "first":
            # vectorized: first occurrence in-batch AND not seen before
            _, first_idx = np.unique(keys, return_index=True)
            mask = np.zeros(len(batch), bool)
            mask[first_idx] = True
            unseen = np.asarray([k not in self._seen for k in keys.tolist()])
            mask &= unseen
            for k in keys[mask].tolist():
                self._seen[k] = {}
            return [batch.select(mask)] if mask.any() else []
        # keep == 'last': retain latest (by order column or arrival)
        rows = batch.to_rows()
        for i, row in enumerate(rows):
            k = keys[i].item() if isinstance(keys[i], np.generic) else keys[i]
            if self.order_column is not None:
                o = row[self.order_column]
                if k in self._order and not o >= self._order[k]:
                    continue
                self._order[k] = o
            self._seen[k] = row
        return []

    def end_input(self) -> List[StreamElement]:
        if self.keep == "first" or not self._seen:
            return []
        rows = list(self._seen.values())
        cols = {c: np.asarray([r.get(c) for r in rows]) for c in rows[0]}
        return [RecordBatch(cols)]

    def snapshot_state(self) -> Dict[str, Any]:
        return {"seen": dict(self._seen), "order": dict(self._order)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._seen = dict(snap.get("seen", {}))
        self._order = dict(snap.get("order", {}))


class SortLimitOperator(StreamOperator):
    """Bounded ORDER BY / LIMIT inside a query pipeline (subquery result
    semantics): buffer, sort at end of input, truncate."""

    def __init__(self, order_by: List[Tuple[str, bool]],
                 limit: Optional[int], name: str = "sort-limit"):
        self.order_by = list(order_by)
        self.limit = limit
        self.name = name
        self._buf: List[RecordBatch] = []

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch):
            self._buf.append(batch)
        return []

    def end_input(self) -> List[StreamElement]:
        if not self._buf:
            return []
        b = RecordBatch.concat(self._buf)
        self._buf = []
        order = np.arange(len(b))
        for name, asc in reversed(self.order_by):
            col = np.asarray(b.column(name))[order]
            o = np.argsort(col, kind="stable")
            if not asc:
                o = o[::-1]
            order = order[o]
        if self.limit is not None:
            order = order[: self.limit]
        return [b.take(order)]

    def snapshot_state(self) -> Dict[str, Any]:
        if not self._buf:
            return {}
        b = RecordBatch.concat(self._buf)
        return {"cols": {k: np.asarray(v) for k, v in b.columns.items()},
                "ts": None if b.timestamps is None else np.asarray(b.timestamps)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        if snap.get("cols"):
            self._buf = [RecordBatch(snap["cols"], timestamps=snap.get("ts"))]


class MiniBatchOperator(StreamOperator):
    """Bundle small batches into bigger ones before an expensive stateful
    operator (``MiniBatch`` bundle operators, ``operators/bundle/``):
    flushes at ``max_rows`` OR on any watermark/barrier boundary — control
    elements must never overtake their data."""

    is_stateless = True

    def __init__(self, max_rows: int = 16_384, name: str = "mini-batch"):
        self.max_rows = max_rows
        self.name = name
        self._buf: List[RecordBatch] = []
        self._rows = 0

    def _flush(self) -> List[StreamElement]:
        if not self._buf:
            return []
        out = [RecordBatch.concat(self._buf)] if len(self._buf) > 1 \
            else [self._buf[0]]
        self._buf = []
        self._rows = 0
        return out

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        self._buf.append(batch)
        self._rows += len(batch)
        if self._rows >= self.max_rows:
            return self._flush()
        return []

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        return self._flush()

    def end_input(self) -> List[StreamElement]:
        return self._flush()

    def snapshot_state(self) -> Dict[str, Any]:
        # barrier boundary: flush downstream is not possible from snapshot;
        # persist the bundle instead (reference finishes bundles pre-barrier)
        if not self._buf:
            return {}
        b = RecordBatch.concat(self._buf)
        return {"bundle": {k: np.asarray(v) for k, v in b.columns.items()},
                "ts": None if b.timestamps is None else np.asarray(b.timestamps)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        if snap.get("bundle"):
            self._buf = [RecordBatch(snap["bundle"], timestamps=snap.get("ts"))]
            self._rows = sum(len(b) for b in self._buf)
