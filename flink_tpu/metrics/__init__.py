"""Metrics system: types, hierarchical groups, registry, reporters.

Reference analogs: ``flink-metrics-core`` (types + reporter SPI),
``runtime/metrics/`` (registry + scoped groups), ``flink-metrics-prometheus``
(exposition reporter). See SURVEY §2.2 "Metrics core" / §5.5.
"""

from flink_tpu.metrics.core import (Counter, Gauge, Histogram, Meter, Metric,
                                    SettableGauge)
from flink_tpu.metrics.groups import (BUSY_TIME, CURRENT_WATERMARK,
                                      NUM_LATE_RECORDS_DROPPED,
                                      NUM_RECORDS_IN, NUM_RECORDS_OUT,
                                      MetricGroup, MetricRegistry,
                                      OperatorIOMetrics, task_metric_group)
from flink_tpu.metrics.reporters import (GraphiteReporter,
                                         InfluxDBReporter, LoggingReporter,
                                         MetricReporter, PrometheusReporter,
                                         StatsDReporter)

__all__ = [
    "Counter", "Gauge", "SettableGauge", "Meter", "Histogram", "Metric",
    "MetricGroup", "MetricRegistry", "OperatorIOMetrics", "task_metric_group",
    "MetricReporter", "LoggingReporter", "PrometheusReporter",
    "StatsDReporter", "GraphiteReporter", "InfluxDBReporter",
    "NUM_RECORDS_IN", "NUM_RECORDS_OUT", "NUM_LATE_RECORDS_DROPPED",
    "CURRENT_WATERMARK", "BUSY_TIME",
]
