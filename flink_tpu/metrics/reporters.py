"""Metric reporters — the reporter SPI + shipped implementations.

Analog of the ``MetricReporter`` SPI (``flink-metrics-core``) and the
reference's shipped reporters (``flink-metrics/``): a logging reporter
(slf4j analog), a Prometheus reporter serving the text exposition format
over HTTP (``flink-metrics-prometheus``), and the line-protocol push
reporters — StatsD over UDP (``flink-metrics-statsd``), Graphite
plaintext over TCP/UDP (``flink-metrics-graphite``), and InfluxDB line
protocol over HTTP (``flink-metrics-influxdb``).  Each push reporter
exposes ``render()`` returning the wire lines so tests and in-process
consumers can assert the exact protocol bytes without a live server.
"""

from __future__ import annotations

import logging
import re
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from flink_tpu.metrics.core import Counter, Gauge, Histogram, Meter, Metric

log = logging.getLogger("flink_tpu.metrics")


class MetricReporter:
    """SPI: ``notify_of_added_metric`` on registration, ``report`` on each
    reporting tick (scheduled reporters), ``close`` on shutdown."""

    def notify_of_added_metric(self, metric: Metric, name: str, group) -> None:
        pass

    def report(self, metrics: Dict[str, Metric]) -> None:
        pass

    def close(self) -> None:
        pass


class LoggingReporter(MetricReporter):
    def __init__(self, level: int = logging.INFO):
        self.level = level

    def report(self, metrics: Dict[str, Metric]) -> None:
        for ident, m in sorted(metrics.items()):
            log.log(self.level, "%s = %s", ident, _render(m))


def _render(m: Metric):
    if isinstance(m, Counter):
        return m.get_count()
    if isinstance(m, Meter):
        return f"{m.get_rate():.1f}/s (n={m.get_count()})"
    if isinstance(m, Histogram):
        s = m.get_statistics()
        return f"p50={s['p50']:.2f} p99={s['p99']:.2f} n={s['count']}"
    if isinstance(m, Gauge):
        return m.get_value()
    return m


_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(ident: str) -> str:
    return "flink_tpu_" + _INVALID.sub("_", ident)


class PrometheusReporter(MetricReporter):
    """Prometheus text exposition; optionally serves GET /metrics."""

    def __init__(self, registry=None, port: Optional[int] = None):
        self._registry = registry
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread = None
        if port is not None:
            self.start_server(port)

    def bind(self, registry) -> None:
        self._registry = registry

    def render(self, metrics: Dict[str, Metric]) -> List[str]:
        """Exposition-format lines for ``metrics`` — the same wire-level
        seam the push reporters expose, so tests can assert exact
        protocol bytes without an HTTP server.  Histograms ship as proper
        Prometheus SUMMARY families: ``{quantile="0.5|0.95|0.99"}``
        series plus the ``_sum`` / ``_count`` conventions."""
        lines: List[str] = []
        for ident, m in sorted(metrics.items()):
            name = _prom_name(ident)
            if isinstance(m, Counter):
                lines += [f"# TYPE {name} counter", f"{name} {m.get_count()}"]
            elif isinstance(m, Meter):
                lines += [f"# TYPE {name} gauge", f"{name} {m.get_rate()}"]
            elif isinstance(m, Histogram):
                s = m.get_statistics()
                lines.append(f"# TYPE {name} summary")
                for q, k in (("0.5", "p50"), ("0.95", "p95"),
                             ("0.99", "p99")):
                    lines.append(f'{name}{{quantile="{q}"}} {s[k]}')
                lines.append(f"{name}_sum {m.get_sum()}")
                lines.append(f"{name}_count {s['count']}")
            elif isinstance(m, Gauge):
                v = m.get_value()
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    lines += [f"# TYPE {name} gauge", f"{name} {v}"]
        return lines

    def scrape(self) -> str:
        metrics = self._registry.all_metrics() if self._registry else {}
        return "\n".join(self.render(metrics)) + "\n"

    # -- HTTP ---------------------------------------------------------------
    def start_server(self, port: int) -> int:
        reporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") in ("", "/metrics"):
                    body = reporter.scrape().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *a):  # silence per-request logging
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._server.server_address[1]

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


def _numeric_points(metrics: Dict[str, Metric]):
    """Flatten metrics to (identifier, field, numeric value) points — the
    shared shape every line-protocol reporter pushes."""
    for ident, m in sorted(metrics.items()):
        if isinstance(m, Counter):
            yield ident, "count", m.get_count()
        elif isinstance(m, Meter):
            yield ident, "rate", m.get_rate()
            yield ident, "count", m.get_count()
        elif isinstance(m, Histogram):
            s = m.get_statistics()
            for k in ("p50", "p95", "p99"):
                yield ident, k, s[k]
            yield ident, "count", s["count"]
        elif isinstance(m, Gauge):
            v = m.get_value()
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                yield ident, "value", v


class StatsDReporter(MetricReporter):
    """StatsD datagrams (``flink-metrics-statsd`` analog):
    ``<name>.<field>:<value>|g``, one metric per UDP datagram.
    EVERYTHING ships as a gauge — counters here are CUMULATIVE snapshots,
    and StatsD ``|c`` sums deltas, so reporting running totals as ``|c``
    would inflate without bound (the reference's StatsD reporter makes
    the same all-gauges choice)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "flink_tpu"):
        self.addr = (host, port)
        self.prefix = prefix
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def _name(self, ident: str, field: str) -> str:
        safe = re.sub(r"[:|@]", "_", ident).replace(" ", "_")
        return f"{self.prefix}.{safe}.{field}"

    def render(self, metrics: Dict[str, Metric]) -> List[str]:
        out = []
        for ident, field, v in _numeric_points(metrics):
            val = int(v) if field == "count" else round(float(v), 6)
            out.append(f"{self._name(ident, field)}:{val}|g")
        return out

    def report(self, metrics: Dict[str, Metric]) -> None:
        for line in self.render(metrics):
            try:
                self._sock.sendto(line.encode(), self.addr)
            except OSError:
                pass                   # metrics must never fail the job

    def close(self) -> None:
        self._sock.close()


class GraphiteReporter(MetricReporter):
    """Graphite plaintext protocol (``flink-metrics-graphite`` analog):
    ``<path> <value> <unix-ts>\\n`` over one TCP connection, re-dialed on
    error."""

    def __init__(self, host: str = "127.0.0.1", port: int = 2003,
                 prefix: str = "flink_tpu"):
        self.addr = (host, port)
        self.prefix = prefix
        self._sock: Optional[socket.socket] = None

    def _name(self, ident: str, field: str) -> str:
        safe = re.sub(r"[^A-Za-z0-9_\-.]", "_", ident)
        return f"{self.prefix}.{safe}.{field}"

    def render(self, metrics: Dict[str, Metric],
               now: Optional[int] = None) -> List[str]:
        ts = int(now if now is not None else time.time())
        return [f"{self._name(ident, field)} "
                f"{int(v) if field == 'count' else round(float(v), 6)} {ts}"
                for ident, field, v in _numeric_points(metrics)]

    def report(self, metrics: Dict[str, Metric]) -> None:
        payload = ("\n".join(self.render(metrics)) + "\n").encode()
        try:
            if self._sock is None:
                self._sock = socket.create_connection(self.addr, timeout=5)
            self._sock.sendall(payload)
        except OSError:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None      # re-dial on the next tick

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None


class InfluxDBReporter(MetricReporter):
    """InfluxDB line protocol (``flink-metrics-influxdb`` analog):
    ``<measurement>[,tag=v] field=value <ns-timestamp>`` POSTed to
    ``/write?db=<db>``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8086,
                 db: str = "flink", tags: Optional[Dict[str, str]] = None):
        self.host, self.port, self.db = host, port, db
        self.tags = dict(tags or {})

    @staticmethod
    def _escape(s: str) -> str:
        return s.replace(" ", "\\ ").replace(",", "\\,").replace("=", "\\=")

    def render(self, metrics: Dict[str, Metric],
               now_ns: Optional[int] = None) -> List[str]:
        ts = int(now_ns if now_ns is not None else time.time() * 1e9)
        tagstr = "".join(f",{self._escape(k)}={self._escape(v)}"
                         for k, v in sorted(self.tags.items()))
        by_ident: Dict[str, List[str]] = {}
        for ident, field, v in _numeric_points(metrics):
            val = f"{int(v)}i" if isinstance(v, int) else repr(float(v))
            by_ident.setdefault(ident, []).append(f"{field}={val}")
        return [f"{self._escape(ident)}{tagstr} {','.join(fields)} {ts}"
                for ident, fields in sorted(by_ident.items())]

    def report(self, metrics: Dict[str, Metric]) -> None:
        import urllib.request
        body = ("\n".join(self.render(metrics)) + "\n").encode()
        url = f"http://{self.host}:{self.port}/write?db={self.db}"
        try:
            req = urllib.request.Request(url, data=body, method="POST")
            urllib.request.urlopen(req, timeout=5).close()
        except OSError:
            pass                       # metrics must never fail the job
