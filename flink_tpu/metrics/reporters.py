"""Metric reporters — the reporter SPI + shipped implementations.

Analog of the ``MetricReporter`` SPI (``flink-metrics-core``) and two of the
reference's shipped reporters (``flink-metrics/``): a logging reporter
(slf4j reporter analog) and a Prometheus reporter serving the text exposition
format over HTTP (``flink-metrics-prometheus``).  ``PrometheusReporter.scrape()``
returns the exposition text directly so tests and in-process consumers don't
need the HTTP server.
"""

from __future__ import annotations

import logging
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from flink_tpu.metrics.core import Counter, Gauge, Histogram, Meter, Metric

log = logging.getLogger("flink_tpu.metrics")


class MetricReporter:
    """SPI: ``notify_of_added_metric`` on registration, ``report`` on each
    reporting tick (scheduled reporters), ``close`` on shutdown."""

    def notify_of_added_metric(self, metric: Metric, name: str, group) -> None:
        pass

    def report(self, metrics: Dict[str, Metric]) -> None:
        pass

    def close(self) -> None:
        pass


class LoggingReporter(MetricReporter):
    def __init__(self, level: int = logging.INFO):
        self.level = level

    def report(self, metrics: Dict[str, Metric]) -> None:
        for ident, m in sorted(metrics.items()):
            log.log(self.level, "%s = %s", ident, _render(m))


def _render(m: Metric):
    if isinstance(m, Counter):
        return m.get_count()
    if isinstance(m, Meter):
        return f"{m.get_rate():.1f}/s (n={m.get_count()})"
    if isinstance(m, Histogram):
        s = m.get_statistics()
        return f"p50={s['p50']:.2f} p99={s['p99']:.2f} n={s['count']}"
    if isinstance(m, Gauge):
        return m.get_value()
    return m


_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(ident: str) -> str:
    return "flink_tpu_" + _INVALID.sub("_", ident)


class PrometheusReporter(MetricReporter):
    """Prometheus text exposition; optionally serves GET /metrics."""

    def __init__(self, registry=None, port: Optional[int] = None):
        self._registry = registry
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread = None
        if port is not None:
            self.start_server(port)

    def bind(self, registry) -> None:
        self._registry = registry

    def scrape(self) -> str:
        metrics = self._registry.all_metrics() if self._registry else {}
        lines = []
        for ident, m in sorted(metrics.items()):
            name = _prom_name(ident)
            if isinstance(m, Counter):
                lines += [f"# TYPE {name} counter", f"{name} {m.get_count()}"]
            elif isinstance(m, Meter):
                lines += [f"# TYPE {name} gauge", f"{name} {m.get_rate()}"]
            elif isinstance(m, Histogram):
                s = m.get_statistics()
                lines.append(f"# TYPE {name} summary")
                for q, k in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                    lines.append(f'{name}{{quantile="{q}"}} {s[k]}')
                lines.append(f"{name}_count {s['count']}")
            elif isinstance(m, Gauge):
                v = m.get_value()
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    lines += [f"# TYPE {name} gauge", f"{name} {v}"]
        return "\n".join(lines) + "\n"

    # -- HTTP ----------------------------------------------------------------
    def start_server(self, port: int) -> int:
        reporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") in ("", "/metrics"):
                    body = reporter.scrape().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *a):  # silence per-request logging
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._server.server_address[1]

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None
