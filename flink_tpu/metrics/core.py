"""Metric types — Counter / Gauge / Meter / Histogram.

Analog of ``flink-metrics/flink-metrics-core`` (``Counter.java``,
``Gauge.java``, ``Meter.java``, ``Histogram.java``) plus the reference's
``DescriptiveStatisticsHistogram``: a numpy ring-buffer reservoir with
vectorized percentile queries (the batched runtime records whole arrays of
latencies at once, so ``update_all`` is the hot path, not ``update``).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, Optional

import numpy as np


class Metric:
    pass


class Counter(Metric):
    __slots__ = ("_count",)

    def __init__(self):
        self._count = 0

    def inc(self, n: int = 1) -> None:
        self._count += n

    def dec(self, n: int = 1) -> None:
        self._count -= n

    def get_count(self) -> int:
        return self._count


class Gauge(Metric):
    """Wraps a supplier; ``get_value`` reads it lazily (``Gauge.java``)."""

    def __init__(self, supplier: Callable[[], Any]):
        self._supplier = supplier

    def get_value(self):
        return self._supplier()


class SettableGauge(Gauge):
    def __init__(self, initial=0):
        self._value = initial
        super().__init__(lambda: self._value)

    def set(self, value) -> None:
        self._value = value


class Meter(Metric):
    """Events-per-second over a sliding time window (``MeterView`` analog:
    the reference updates a rate from a counter once per view interval)."""

    def __init__(self, window_s: float = 60.0, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._window_s = window_s
        self._count = 0
        # (t, cumulative count) checkpoints; deque so the window trim is
        # O(1) per event (list.pop(0) was O(n) on hot meters)
        self._marks: deque = deque()

    def mark_event(self, n: int = 1) -> None:
        self._count += n
        now = self._clock()
        self._marks.append((now, self._count))
        cutoff = now - self._window_s
        while len(self._marks) > 2 and self._marks[0][0] < cutoff:
            self._marks.popleft()

    def get_count(self) -> int:
        return self._count

    def get_rate(self) -> float:
        if len(self._marks) < 2:
            return 0.0
        (t0, c0), (t1, c1) = self._marks[0], self._marks[-1]
        dt = t1 - t0
        return (c1 - c0) / dt if dt > 0 else 0.0


class Histogram(Metric):
    """Ring-buffer reservoir with vectorized bulk update."""

    def __init__(self, size: int = 10_000):
        self._buf = np.zeros(size, np.float64)
        self._n = 0          # total updates ever
        self._pos = 0
        self._sum = 0.0      # lifetime sum (Prometheus summary `_sum`)

    def update(self, value: float) -> None:
        self._buf[self._pos] = value
        self._pos = (self._pos + 1) % self._buf.size
        self._n += 1
        self._sum += value

    def update_all(self, values: np.ndarray) -> None:
        """Bulk insert (the batched-runtime hot path)."""
        values = np.asarray(values, np.float64).ravel()
        if values.size >= self._buf.size:
            self._buf[:] = values[-self._buf.size:]
            self._pos = 0
        else:
            end = self._pos + values.size
            if end <= self._buf.size:
                self._buf[self._pos:end] = values
            else:
                k = self._buf.size - self._pos
                self._buf[self._pos:] = values[:k]
                self._buf[: end - self._buf.size] = values[k:]
            self._pos = end % self._buf.size
        self._n += values.size
        self._sum += float(values.sum())

    def clear(self) -> None:
        """Back to empty (count, sum, reservoir).  Per-execution latency
        views reuse their already-registered Histogram objects across
        resets — reporters see a counter reset, not a new series."""
        self._buf[:] = 0.0
        self._n = 0
        self._pos = 0
        self._sum = 0.0

    def get_count(self) -> int:
        return self._n

    def get_sum(self) -> float:
        """Lifetime sum of every recorded value (not just the reservoir) —
        the Prometheus summary ``_sum`` series."""
        return self._sum

    def _values(self) -> np.ndarray:
        return self._buf[: min(self._n, self._buf.size)]

    def get_statistics(self) -> Dict[str, float]:
        v = self._values()
        if v.size == 0:
            return {"count": 0, "min": 0.0, "max": 0.0, "mean": 0.0,
                    "p50": 0.0, "p95": 0.0, "p99": 0.0, "p999": 0.0}
        q = np.percentile(v, [50, 95, 99, 99.9])
        return {"count": self._n, "min": float(v.min()), "max": float(v.max()),
                "mean": float(v.mean()), "p50": float(q[0]),
                "p95": float(q[1]), "p99": float(q[2]), "p999": float(q[3])}
