"""Hierarchical metric groups + registry.

Analog of ``runtime/metrics/groups/`` + ``MetricRegistryImpl.java:67``: every
metric lives in a scope tree (jobmanager|taskmanager → job → task → operator,
plus free-form user groups); the registry fans registrations out to reporters
and owns the scope-string formatting (``runtime/metrics/scope/``).

System metric names follow the reference's ``MetricNames.java``
(numRecordsIn/Out, numLateRecordsDropped, currentWatermark, busyTimeMsPerSecond)
so dashboards translate one-to-one.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_tpu.metrics.core import (Counter, Gauge, Histogram, Meter, Metric,
                                    SettableGauge)

# MetricNames.java analogs
NUM_RECORDS_IN = "numRecordsIn"
NUM_RECORDS_OUT = "numRecordsOut"
NUM_LATE_RECORDS_DROPPED = "numLateRecordsDropped"
CURRENT_WATERMARK = "currentInputWatermark"
BUSY_TIME = "busyTimeMsPerSecond"
NUM_RESTARTS = "numRestarts"
CHECKPOINT_DURATION = "lastCheckpointDuration"
CHECKPOINT_SIZE = "lastCheckpointSize"
NUM_COMPLETED_CHECKPOINTS = "numberOfCompletedCheckpoints"
NUM_FAILED_CHECKPOINTS = "numberOfFailedCheckpoints"
# device-state paging occupancy (state/paging.py; RocksDB block-cache
# hit/miss counter analogs for the HBM pane-ring cache)
PAGING_RESIDENT_KEYS = "paging.resident_keys"
PAGING_SPILLED_KEYS = "paging.spilled_keys"
PAGING_EVICTIONS = "paging.evictions"
PAGING_PROMOTIONS = "paging.promotions"
# device-lane health (runtime/device_health.py): watchdog + quarantine/
# heal cycle of the process's accelerator tier
DEVICE_HEALTH_STATE = "device_health.state"          # 0 healthy, 1 quarantined
DEVICE_HEALTH_QUARANTINES = "device_health.quarantines"
DEVICE_HEALTH_HEALS = "device_health.heals"
DEVICE_HEALTH_WATCHDOG_TIMEOUTS = "device_health.watchdog_timeouts"
DEVICE_HEALTH_NEAR_MISSES = "device_health.near_misses"
DEVICE_HEALTH_TRANSIENT_RETRIES = "device_health.transient_retries"
DEVICE_HEALTH_OOM_PAGEOUTS = "device_health.oom_pageouts"
DEVICE_HEALTH_DEGRADED_OPERATORS = "device_health.degraded_operators"
# checkpoint alignment / unaligned-checkpoint accounting (the reference's
# checkpointAlignmentTime + lastCheckpointPersistedData analogs)
CHECKPOINT_ALIGNMENT_TIME = "lastCheckpointAlignmentTime"
CHECKPOINT_OVERTAKEN_BYTES = "lastCheckpointOvertakenBytes"
CHECKPOINT_PERSISTED_INFLIGHT = "lastCheckpointPersistedInFlightBytes"
NUM_UNALIGNED_CHECKPOINTS = "numberOfUnalignedCheckpoints"
# channel backpressure (backPressuredTimeMsPerSecond family, folded to
# job scope: totals + the deepest input queue + alignment buffering)
BACKPRESSURED_TIME_MS = "backpressure.total_backpressured_ms"
BACKPRESSURE_MAX_QUEUE_DEPTH = "backpressure.max_queue_depth"
BACKPRESSURE_ALIGNMENT_QUEUED = "backpressure.alignment_queued_elements"
# queryable serving tier (queryable/service.py): lookup volume + latency
# percentiles and the read replicas' staleness (checkpoints behind the
# stream head, and for how long)
QUERYABLE_LOOKUPS = "queryable.lookups_total"
QUERYABLE_QPS = "queryable.lookups_per_sec"
QUERYABLE_P50 = "queryable.lookup_p50_ms"
QUERYABLE_P99 = "queryable.lookup_p99_ms"
QUERYABLE_REPLICA_LAG_CHECKPOINTS = "queryable.replica_lag_checkpoints"
QUERYABLE_REPLICA_LAG_MS = "queryable.replica_lag_ms"
# server-side SERVICE time (lookup + serialization, measured in the TCP
# handler) — the honest serve latency next to the client-side ring, whose
# p99 on a GIL-loaded box measures the box, not the server
QUERYABLE_SERVE_P50 = "queryable.serve_p50_ms"
QUERYABLE_SERVE_P99 = "queryable.serve_p99_ms"
QUERYABLE_CACHE_HIT_RATE = "queryable.cache_hit_rate"
# reactive autoscaler (cluster/adaptive.ReactiveAutoscaler): the rescale
# lifecycle's health — current vs target parallelism, how often the job
# rescaled, how long the last rescale window was, and how many rescales
# rolled back / re-triggered inside the window
AUTOSCALER_CURRENT_PARALLELISM = "autoscaler.current_parallelism"
AUTOSCALER_TARGET_PARALLELISM = "autoscaler.target_parallelism"
AUTOSCALER_RESCALES = "autoscaler.rescales_total"
AUTOSCALER_ROLLBACKS = "autoscaler.rollbacks_total"
AUTOSCALER_RETRIGGERS = "autoscaler.retriggers_total"
AUTOSCALER_LAST_RESCALE_MS = "autoscaler.last_rescale_duration_ms"
AUTOSCALER_COOLDOWN_REMAINING_MS = "autoscaler.cooldown_remaining_ms"
# coordinator high availability (runtime/ha.py): the leader's fencing
# epoch, demotion state, and how much stale-epoch traffic was rejected
HA_LEADER_EPOCH = "ha.leader_epoch"
HA_DEMOTED = "ha.demoted"                           # 0 leading, 1 demoted
HA_FENCED_COMPLETIONS = "ha.fenced_completions"
HA_FENCED_WORKER_MSGS = "ha.fenced_worker_msgs"


class MetricGroup:
    """One node of the scope tree (``AbstractMetricGroup`` analog)."""

    def __init__(self, registry: "MetricRegistry", scope: Tuple[str, ...],
                 parent: Optional["MetricGroup"] = None):
        self._registry = registry
        self._scope = scope
        self._parent = parent
        self._metrics: Dict[str, Metric] = {}
        self._groups: Dict[str, "MetricGroup"] = {}

    # -- structure -----------------------------------------------------------
    def add_group(self, name: str) -> "MetricGroup":
        g = self._groups.get(name)
        if g is None:
            g = MetricGroup(self._registry, self._scope + (str(name),), self)
            self._groups[name] = g
        return g

    @property
    def scope(self) -> Tuple[str, ...]:
        return self._scope

    def metric_identifier(self, name: str, delimiter: str = ".") -> str:
        return delimiter.join(self._scope + (name,))

    # -- registration --------------------------------------------------------
    def _register(self, name: str, metric: Metric) -> Metric:
        existing = self._metrics.get(name)
        if existing is not None:
            return existing
        self._metrics[name] = metric
        self._registry.register(metric, name, self)
        return metric

    def counter(self, name: str) -> Counter:
        return self._register(name, Counter())

    def gauge(self, name: str, supplier: Optional[Callable[[], Any]] = None):
        if supplier is None:
            return self._register(name, SettableGauge())
        return self._register(name, Gauge(supplier))

    def meter(self, name: str, **kw) -> Meter:
        return self._register(name, Meter(**kw))

    def histogram(self, name: str, **kw) -> Histogram:
        return self._register(name, Histogram(**kw))

    # -- introspection -------------------------------------------------------
    def metrics(self) -> Dict[str, Metric]:
        return dict(self._metrics)

    def all_metrics(self) -> Dict[str, Metric]:
        """Fully-qualified identifier -> metric, for this subtree."""
        out = {self.metric_identifier(n): m for n, m in self._metrics.items()}
        for g in self._groups.values():
            out.update(g.all_metrics())
        return out


class MetricRegistry:
    """Fan-out hub: registrations notify every reporter
    (``MetricRegistryImpl`` analog; reporting runs on a timer thread when an
    interval is configured, like the reference's reporter scheduler)."""

    def __init__(self, reporters: Optional[List] = None,
                 report_interval_s: float = 0.0):
        self.reporters = list(reporters or [])
        self._roots: List[MetricGroup] = []
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self._stopped = False
        self._interval = report_interval_s
        if report_interval_s > 0 and self.reporters:
            self._schedule()

    # -- scope roots ---------------------------------------------------------
    def task_manager_group(self, tm_id: str = "tm-0") -> MetricGroup:
        g = MetricGroup(self, ("taskmanager", tm_id))
        self._roots.append(g)
        return g

    def job_manager_group(self) -> MetricGroup:
        g = MetricGroup(self, ("jobmanager",))
        self._roots.append(g)
        return g

    def register(self, metric: Metric, name: str, group: MetricGroup) -> None:
        with self._lock:
            for r in self.reporters:
                r.notify_of_added_metric(metric, name, group)

    def all_metrics(self) -> Dict[str, Metric]:
        out: Dict[str, Metric] = {}
        for g in self._roots:
            out.update(g.all_metrics())
        return out

    # -- periodic reporting --------------------------------------------------
    def _schedule(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._timer = threading.Timer(self._interval, self._tick)
            self._timer.daemon = True
            self._timer.start()

    def _tick(self) -> None:
        self.report_now()
        self._schedule()

    def report_now(self) -> None:
        for r in self.reporters:
            r.report(self.all_metrics())

    def close(self) -> None:
        # _stopped gates _schedule so a _tick racing close() cannot re-arm
        # the timer after it was cancelled
        with self._lock:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
        for r in self.reporters:
            close = getattr(r, "close", None)
            if close:
                close()


def task_metric_group(registry: MetricRegistry, job_name: str,
                      task_name: str, subtask_index: int) -> MetricGroup:
    """taskmanager.<tm>.<job>.<task>.<subtask> — the scope format of
    ``TaskMetricGroup`` (``runtime/metrics/scope/ScopeFormats``)."""
    return (registry.task_manager_group()
            .add_group(job_name).add_group(task_name)
            .add_group(str(subtask_index)))


class OperatorIOMetrics:
    """numRecordsIn/Out + rates for one operator (``OperatorIOMetricGroup``)."""

    def __init__(self, group: MetricGroup):
        self.group = group
        self.records_in = group.counter(NUM_RECORDS_IN)
        self.records_out = group.counter(NUM_RECORDS_OUT)
        self.late_dropped = group.counter(NUM_LATE_RECORDS_DROPPED)
        self.watermark = group.gauge(CURRENT_WATERMARK)


def paging_metrics(group: MetricGroup,
                   stats_supplier: Callable[[], Optional[Dict[str, int]]]
                   ) -> MetricGroup:
    """Register the device-paging occupancy gauges on a (job-scope) group:
    ``paging.resident_keys`` / ``paging.spilled_keys`` / ``paging.evictions``
    / ``paging.promotions``.  ``stats_supplier`` returns the aggregated
    :meth:`WindowAggOperator.paging_stats` dict (or None/empty -> 0s)."""
    def _read(key: str) -> Callable[[], int]:
        return lambda: int((stats_supplier() or {}).get(key, 0))

    for name, key in ((PAGING_RESIDENT_KEYS, "resident_keys"),
                      (PAGING_SPILLED_KEYS, "spilled_keys"),
                      (PAGING_EVICTIONS, "evictions"),
                      (PAGING_PROMOTIONS, "promotions")):
        group.gauge(name, _read(key))
    return group


def device_health_metrics(group: MetricGroup,
                          status_supplier: Callable[[], Dict[str, Any]]
                          ) -> MetricGroup:
    """Register the device-lane health gauges on a (job-scope) group:
    state (0 healthy / 1 quarantined), quarantine + heal counters,
    watchdog timeouts/near-misses, transient retries, OOM page-outs, and
    the count of operators currently running degraded.  ``status_supplier``
    returns ``job_status()["device_health"]``-shaped dicts."""
    def _read(key: str, default: int = 0) -> Callable[[], int]:
        return lambda: int((status_supplier() or {}).get(key, default))

    group.gauge(DEVICE_HEALTH_STATE,
                lambda: int((status_supplier() or {}).get("state")
                            == "quarantined"))
    for name, key in ((DEVICE_HEALTH_QUARANTINES, "quarantines"),
                      (DEVICE_HEALTH_HEALS, "heals"),
                      (DEVICE_HEALTH_WATCHDOG_TIMEOUTS, "watchdog_timeouts"),
                      (DEVICE_HEALTH_NEAR_MISSES, "near_misses"),
                      (DEVICE_HEALTH_TRANSIENT_RETRIES, "transient_retries"),
                      (DEVICE_HEALTH_OOM_PAGEOUTS, "oom_pageouts"),
                      (DEVICE_HEALTH_DEGRADED_OPERATORS,
                       "degraded_operators")):
        group.gauge(name, _read(key))
    return group


def backpressure_metrics(group: MetricGroup,
                         totals_supplier: Callable[[], Dict[str, Any]]
                         ) -> MetricGroup:
    """Register the channel-backpressure gauges on a (job-scope) group:
    total producer credit-wait ms, deepest input queue, and elements
    buffered by barrier alignment.  ``totals_supplier`` returns
    ``MiniCluster.backpressure_totals()``-shaped dicts."""
    def _read(key: str) -> Callable[[], Any]:
        return lambda: (totals_supplier() or {}).get(key, 0)

    for name, key in ((BACKPRESSURED_TIME_MS, "total_backpressured_ms"),
                      (BACKPRESSURE_MAX_QUEUE_DEPTH, "max_queue_depth"),
                      (BACKPRESSURE_ALIGNMENT_QUEUED,
                       "alignment_queued_elements")):
        group.gauge(name, _read(key))
    return group


def queryable_metrics(group: MetricGroup,
                      stats_supplier: Callable[[], Optional[Dict[str, Any]]]
                      ) -> MetricGroup:
    """Register the queryable serving tier's gauges on a (job-scope)
    group: lookup volume/qps, p50/p99 lookup latency, and replica
    staleness.  ``stats_supplier`` returns
    :meth:`QueryableStateService.stats` dicts (or None -> 0s)."""
    def _read(key: str, default=0) -> Callable[[], Any]:
        def read():
            v = (stats_supplier() or {}).get(key)
            return default if v is None else v
        return read

    for name, key in ((QUERYABLE_LOOKUPS, "lookups_total"),
                      (QUERYABLE_QPS, "lookups_per_sec"),
                      (QUERYABLE_P50, "lookup_p50_ms"),
                      (QUERYABLE_P99, "lookup_p99_ms"),
                      (QUERYABLE_SERVE_P50, "serve_p50_ms"),
                      (QUERYABLE_SERVE_P99, "serve_p99_ms"),
                      (QUERYABLE_CACHE_HIT_RATE, "cache_hit_rate"),
                      (QUERYABLE_REPLICA_LAG_CHECKPOINTS,
                       "replica_lag_checkpoints"),
                      (QUERYABLE_REPLICA_LAG_MS, "replica_lag_ms")):
        group.gauge(name, _read(key))
    return group


def autoscaler_metrics(group: MetricGroup,
                       status_supplier: Callable[[], Optional[Dict[str, Any]]]
                       ) -> MetricGroup:
    """Register the reactive autoscaler's gauges on a (job-scope) group:
    current/target parallelism, rescale/rollback/re-trigger counters, the
    last rescale window's duration, and the cooldown remaining.
    ``status_supplier`` returns :meth:`ReactiveAutoscaler.status` dicts
    (or None -> 0s)."""
    def _read(key: str, default=0) -> Callable[[], Any]:
        def read():
            v = (status_supplier() or {}).get(key)
            return default if v is None else v
        return read

    for name, key in ((AUTOSCALER_CURRENT_PARALLELISM,
                       "current_parallelism"),
                      (AUTOSCALER_TARGET_PARALLELISM, "target_parallelism"),
                      (AUTOSCALER_RESCALES, "rescales"),
                      (AUTOSCALER_ROLLBACKS, "rollbacks"),
                      (AUTOSCALER_RETRIGGERS, "retriggers"),
                      (AUTOSCALER_LAST_RESCALE_MS,
                       "last_rescale_duration_ms"),
                      (AUTOSCALER_COOLDOWN_REMAINING_MS,
                       "cooldown_remaining_ms")):
        group.gauge(name, _read(key))
    return group


def ha_metrics(group: MetricGroup,
               status_supplier: Callable[[], Optional[Dict[str, Any]]]
               ) -> MetricGroup:
    """Register the coordinator-HA gauges on a (job-scope) group: the
    leader epoch every control message is fenced by, whether this
    coordinator has been demoted (lease lost), and the counts of
    stale-epoch completions / worker messages it rejected.
    ``status_supplier`` returns ``ha_status()``-shaped dicts (or None ->
    0s, e.g. HA disabled)."""
    def _read(key: str, default=0) -> Callable[[], Any]:
        def read():
            v = (status_supplier() or {}).get(key)
            return default if v is None else v
        return read

    group.gauge(HA_DEMOTED,
                lambda: int(bool((status_supplier() or {}).get("demoted"))))
    for name, key in ((HA_LEADER_EPOCH, "leader_epoch"),
                      (HA_FENCED_COMPLETIONS, "fenced_completions"),
                      (HA_FENCED_WORKER_MSGS, "fenced_worker_msgs")):
        group.gauge(name, _read(key))
    return group


def checkpoint_alignment_metrics(group: MetricGroup,
                                 stats_supplier: Callable[[], Dict[str, Any]]
                                 ) -> MetricGroup:
    """Register the unaligned-checkpoint accounting gauges on a (job-scope)
    group: alignment duration, overtaken bytes and persisted in-flight
    bytes of the last completed checkpoint, plus the lifetime count of
    checkpoints that escalated to unaligned."""
    def _read(key: str) -> Callable[[], Any]:
        return lambda: (stats_supplier() or {}).get(key, 0)

    for name, key in (
            (CHECKPOINT_ALIGNMENT_TIME, "last_alignment_duration_ms"),
            (CHECKPOINT_OVERTAKEN_BYTES, "last_overtaken_bytes"),
            (CHECKPOINT_PERSISTED_INFLIGHT,
             "last_persisted_inflight_bytes"),
            (NUM_UNALIGNED_CHECKPOINTS, "unaligned_checkpoints")):
        group.gauge(name, _read(key))
    return group


def job_checkpoint_metrics(group: MetricGroup, failure_manager,
                           restarts_supplier: Callable[[], int]) -> MetricGroup:
    """Register a CheckpointFailureManager's lifetime counters + the restart
    count on a job-scope group (``CheckpointStatsTracker`` /
    ``numRestarts`` analogs) so reporters export them; returns the group."""
    group._register(NUM_COMPLETED_CHECKPOINTS,
                    failure_manager.completed_counter)
    group._register(NUM_FAILED_CHECKPOINTS, failure_manager.failed_counter)
    group.gauge(NUM_RESTARTS, restarts_supplier)
    return group
