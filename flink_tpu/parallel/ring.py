"""Ring combine: blockwise partial aggregation rotated over ICI.

The ring-attention analog for streaming state (SURVEY §5.7): when one
logical window's panes span multiple chips (sequence/context parallelism —
the pane axis sharded instead of the key axis), the window total is the
monoid combine of per-chip partials.  Instead of an all-gather (O(D) memory
on every chip), partials rotate around the ring with ``lax.ppermute`` —
each step combines the neighbor's partial into the running accumulator, and
after D-1 rotations every chip holds the full combine.  Bandwidth per step
is one partial, exactly the blockwise-attention communication pattern.

Also provided: ``ring_all_reduce_sum`` (the reduce-scatter + all-gather
decomposition) for plain additive state.
"""

from __future__ import annotations

from typing import Callable

import jax
from flink_tpu.parallel.mesh import shard_map_compat
from jax.sharding import Mesh, PartitionSpec as P

from flink_tpu.parallel.mesh import KG_AXIS


def _ring_fold(leaves, combine_leaves: Callable, axis: str, D: int):
    """D-1 ppermute rotations folding every device's partial into all
    devices.  Arrival order is a per-device cyclic rotation, so
    ``combine_leaves`` must be associative AND COMMUTATIVE — the
    ``AggregateFunction.combine`` contract (core/functions.py); an
    order-sensitive combine would yield device-dependent results."""
    perm = [(i, (i + 1) % D) for i in range(D)]
    acc = leaves
    rotating = leaves
    for _ in range(D - 1):
        rotating = tuple(jax.lax.ppermute(l, axis, perm) for l in rotating)
        acc = combine_leaves(acc, rotating)
    return acc


def make_ring_combine(mesh: Mesh, combine_leaves: Callable,
                      num_leaves: int, axis: str = KG_AXIS):
    """Build a jitted ring combine over ``axis``.

    Input: per-device partial accumulator leaves (each [*leaf_shape], one
    partial per chip, sharded over ``axis`` with a leading device dim).
    Output: the SAME shape, every device holding the full combine of all
    partials.  ``combine_leaves`` must be associative AND commutative
    (the ``AggregateFunction.combine`` contract) — partials arrive in a
    per-device cyclic order.
    """
    D = mesh.shape[axis]

    def ring(*leaves):
        # leaves: per-device local partial (shard_map strips the device dim)
        return _ring_fold(leaves, combine_leaves, axis, D)

    specs = tuple(P(axis) for _ in range(num_leaves))
    fn = shard_map_compat(ring, mesh, specs, specs)
    return jax.jit(fn)


def make_ring_all_reduce_sum(mesh: Mesh, axis: str = KG_AXIS):
    """Additive special case: psum over the ring axis (XLA lowers this to
    the bidirectional ring reduce on ICI)."""

    def allreduce(x):
        return jax.lax.psum(x, axis)

    fn = shard_map_compat(allreduce, mesh, P(axis), P(axis))
    return jax.jit(fn)


def sharded_pane_window_total(mesh: Mesh, combine_leaves: Callable,
                              num_leaves: int, axis: str = KG_AXIS):
    """Sequence-parallel window fire: each chip holds a PANE SLICE of the
    window's accumulator state ``[K, panes_local, ...]``; the full window
    total per key = ring-combine of the per-chip pane combines.

    Returns a jitted fn(leaves...) -> combined leaves [K, ...] replicated
    across the ring (every chip can emit its key shard of the result).
    """
    from flink_tpu.ops.scatter import combine_along_axis

    D = mesh.shape[axis]

    def body(*leaves):
        # per-device view [1, K, panes_local, ...]: combine the LOCAL pane
        # slice first (blockwise partial) so the ring carries [1, K, ...],
        # not the full pane axis
        local = combine_along_axis(leaves, combine_leaves, axis=2)
        return _ring_fold(local, combine_leaves, axis, D)

    specs = tuple(P(axis) for _ in range(num_leaves))
    return jax.jit(shard_map_compat(body, mesh, specs, specs))
