"""Device-side record exchange: the data plane, on ICI instead of Netty.

The reference moves records between parallel subtasks through a Netty shuffle
with credit-based flow control (``NettyMessage.java``,
``RemoteInputChannel.java:302``).  On a TPU mesh the equivalent *intra-pod*
exchange is a bucketed ``all_to_all`` under ``shard_map``: each device sorts
its local records into per-destination buckets of fixed capacity and one XLA
collective rotates the buckets over ICI.  Capacity overflows are reported by
the raw exchange and handled by :class:`ResizingExchange`, which BLOCKS and
re-runs at doubled capacity instead of dropping — the analog of
credit-exhaustion blocking + floating-buffer redistribution under backlog
feedback (``RemoteInputChannel.java:302``,
``NettyShuffleEnvironmentOptions.java:167``).

All shapes are static (capacity per destination is fixed per compile), so the
exchange jits once; padding rows carry slot id == capacity sentinel and are
dropped by downstream scatters.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from flink_tpu.observability import tracing
from flink_tpu.parallel.mesh import KG_AXIS, shard_map_compat


def bucket_plan(dest: jnp.ndarray, num_shards: int, cap: int):
    """The shared bucketing plan of every keyed exchange: STABLE-sort local
    rows by destination shard and compute each row's flat position in the
    ``[num_shards, cap]`` send buckets.

    Returns ``(order, flat, valid_src)``: ``order`` is the stable row
    permutation, ``flat[i]`` the bucket cell of sorted row ``i`` (or the
    ``num_shards * cap`` drop sentinel once a destination's bucket is
    full), ``valid_src`` the per-sorted-row in-capacity mask.  Stability
    matters for more than determinism: records of one key keep their batch
    order through the exchange, which is what makes the sharded
    scatter-combine BIT-identical to the single-chip fold (same per-cell
    accumulation order) at any mesh size."""
    B = dest.shape[0]
    order = jnp.argsort(dest, stable=True)
    sdest = dest[order]
    # position of each row within its destination's bucket
    idx_in_dest = jnp.arange(B) - jnp.searchsorted(sdest, sdest, side="left")
    valid_src = idx_in_dest < cap
    flat = jnp.where(valid_src, sdest * cap + idx_in_dest, num_shards * cap)
    return order, flat, valid_src


def bucket_rows(a: jnp.ndarray, order: jnp.ndarray, flat: jnp.ndarray,
                num_shards: int, cap: int, fill) -> jnp.ndarray:
    """Place one row array into its ``[num_shards, cap, ...]`` send buckets
    under a :func:`bucket_plan`; unfilled cells carry ``fill`` (an id the
    receiving scatter drops, or a neutral value)."""
    buf = jnp.full((num_shards * cap,) + a.shape[1:], fill, a.dtype)
    return buf.at[flat].set(a[order], mode="drop").reshape(
        (num_shards, cap) + a.shape[1:])


def all_to_all_rows(bucketed: jnp.ndarray) -> jnp.ndarray:
    """The keyed exchange collective: rotate ``[D, cap, ...]`` send buckets
    over the mesh axis so row ``d`` of the result is what device ``d`` sent
    to THIS device — the record→owning-shard route on ICI, replacing the
    host-channel key-shuffle hop (``NettyMessage.java`` analog).  Must run
    inside ``shard_map`` over :data:`~flink_tpu.parallel.mesh.KG_AXIS`."""
    return jax.lax.all_to_all(bucketed, KG_AXIS, split_axis=0,
                              concat_axis=0, tiled=True)


def _bucket_local(dest: jnp.ndarray, leaves: Tuple[jnp.ndarray, ...],
                  num_shards: int, cap: int):
    """Sort local rows into [num_shards, cap] buckets by destination shard.

    Returns (bucketed_leaves, valid mask [num_shards, cap], overflow count).
    Rows beyond ``cap`` for a destination overflow (counted, not sent).
    """
    order, flat, valid_src = bucket_plan(dest, num_shards, cap)
    out_leaves = tuple(bucket_rows(l, order, flat, num_shards, cap, 0)
                       for l in leaves)
    vmask = jnp.zeros((num_shards * cap,), bool).at[flat].set(
        valid_src, mode="drop").reshape(num_shards, cap)
    overflow = jnp.sum(~valid_src)
    return out_leaves, vmask, overflow


def make_all_to_all_exchange(mesh: Mesh, num_leaves: int, cap: int):
    """Build the jitted exchange: local [B] records -> received [D*cap] rows.

    Inputs (per device, via shard_map):
      dest[B] int32   destination shard per local record
      leaves          tuple of [B, ...] value arrays
    Outputs (per device):
      rx_leaves       tuple of [D*cap, ...] received rows
      rx_valid[D*cap] bool
      overflow        int32 — local rows not sent (capacity exhausted)
    """
    D = mesh.devices.size

    def _exchange(dest, *leaves):
        bucketed, vmask, overflow = _bucket_local(dest, leaves, D, cap)
        # all_to_all over the kg axis: [D, cap, ...] -> [D, cap, ...] where
        # row d of the output came from device d's bucket for *this* device.
        rx = tuple(
            jax.lax.all_to_all(b, KG_AXIS, split_axis=0, concat_axis=0,
                               tiled=True)
            for b in bucketed)
        rx_valid = jax.lax.all_to_all(vmask, KG_AXIS, split_axis=0,
                                      concat_axis=0, tiled=True)
        rx_flat = tuple(r.reshape((D * cap,) + r.shape[2:]) for r in rx)
        return rx_flat, rx_valid.reshape(D * cap), overflow.reshape(1)

    in_specs = (P(KG_AXIS),) + (P(KG_AXIS),) * num_leaves
    out_specs = ((P(KG_AXIS),) * num_leaves, P(KG_AXIS), P(KG_AXIS))
    fn = shard_map_compat(_exchange, mesh, in_specs, out_specs)
    return jax.jit(fn)


class ResizingExchange:
    """Zero-loss all_to_all: overflow BLOCKS and renegotiates capacity, it
    never drops (the reference's credit semantics — a sender without credit
    waits, ``RemoteInputChannel.java:302``; floating buffers grow under
    backlog, ``NettyShuffleEnvironmentOptions.java:167``).

    The fixed-cap exchange is pure, so an overflowed round can simply be
    re-run at double capacity with the SAME inputs — one recompile per
    doubling, amortized O(log) over a run.  The overflow check is the one
    host sync per round (the credit check of the hot path); capacity only
    grows, so steady state pays a single scalar readback."""

    def __init__(self, mesh: Mesh, num_leaves: int, cap: int,
                 max_cap: int = 1 << 20):
        self.mesh = mesh
        self.num_leaves = num_leaves
        self.cap = cap
        self.max_cap = max_cap
        self._fn = make_all_to_all_exchange(mesh, num_leaves, cap)

    def __call__(self, dest, *leaves):
        """-> (rx_leaves, rx_valid, cap_used).  Every input row is delivered
        exactly once; raises only if ``max_cap`` cannot hold the skew."""
        while True:
            with tracing.span("mesh.exchange", cat="exchange",
                              cap=self.cap, rows=int(dest.shape[0])):
                rx, valid, overflow = self._fn(dest, *leaves)
            if int(jnp.max(overflow)) == 0:
                return rx, valid, self.cap
            if self.cap >= self.max_cap:
                raise RuntimeError(
                    f"exchange overflow at max capacity {self.max_cap}: "
                    f"destination skew exceeds the configured buffer budget")
            self.cap = min(self.cap * 2, self.max_cap)
            self._fn = make_all_to_all_exchange(self.mesh, self.num_leaves,
                                                self.cap)
