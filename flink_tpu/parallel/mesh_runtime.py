"""Mesh-sharded job runtime: the keyed exchange IS the execution path.

This module fuses the device data plane into the normal job runtime: a
``MeshWindowAggOperator`` is a drop-in ``WindowAggOperator`` whose micro-batch
step runs under ``shard_map`` over a ``jax.sharding.Mesh`` — records are
row-split over the devices (as a distributed source would produce them), an
``all_to_all`` collective re-keys each record to the device owning its key
group, and the owning device folds it into its LOCAL state shard.  This is
the TPU-native analog of the reference's keyed exchange being the runtime
(``KeyGroupStreamPartitioner.java`` + the Netty stack,
``NettyMessage.java:254``) rather than a detached demo: any
``env.execute()``-submitted windowed pipeline runs through it when the
environment is given a mesh (``StreamExecutionEnvironment(mesh=...)``).

Design notes (TPU-first):
- **No overflow, no flow-control sync in the hot loop.**  The host computes
  every record's destination shard (it assigns dense key slots anyway —
  the record-serializer role), so the per-``(src, dest)`` bucket capacity is
  KNOWN before dispatch; the exchange compiles at a quantized capacity that
  always fits.  The general device-side-destination case with capacity
  renegotiation lives in ``parallel/exchange.py`` (``ResizingExchange``).
- **One jitted step per micro-batch**: bucket → ``all_to_all`` (ICI) →
  local scatter-combine, all inside one ``shard_map`` — XLA overlaps the
  collective with the scatter epilogue.
- **State is globally addressed.**  Key slot ids are global ``[0, K)``;
  device ``d`` owns rows ``[d*K/D, (d+1)*K/D)``, the contiguous key-group
  ranges of ``KeyGroupRangeAssignment.java:50-84``.  Snapshots are therefore
  mesh-size-independent: a snapshot taken on 8 devices restores onto 4 (or
  1) unchanged — the key-group rescaling story
  (``StateAssignmentOperation.reDistributeKeyedStates``).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.operators.session_window import SessionWindowOperator
from flink_tpu.operators.window_agg import (WindowAggOperator, _next_pow2,
                                            _x64)
from flink_tpu.runtime.device_health import DeviceQuarantinedError
from flink_tpu.ops.scatter import scatter_fast, scatter_generic
from flink_tpu.parallel.mesh import KG_AXIS, make_mesh, state_sharding


from flink_tpu.ops.shapes import quantize_pow2


def _quantize(n: int, floor: int = 16) -> int:
    """pow2/4-step rounding: bounded compile count, <=25% padding."""
    return quantize_pow2(n, floor=floor, steps=4)


class MeshWindowAggOperator(WindowAggOperator):
    """``WindowAggOperator`` executing as ONE logical SPMD operator over a
    1-D key-group mesh: state sharded by key group, records re-keyed over
    ICI via ``all_to_all`` inside the update step.  API-compatible with the
    single-chip operator — graph translation swaps it in when the
    environment carries a mesh.

    Per-shard subsystems (ISSUE 6): the host emit tier, cold-key paging,
    and the degraded-tier migration all run against the SAME key-group-
    range layout the device state uses (``state/shard_layout.ShardLayout``):

    - **host tier**: the fused native probe/mirror pass shards by
      CONTIGUOUS slot range (``shard_div = K / D``), so probe shard ``t``
      maintains exactly the mirror rows whose device block lives on mesh
      device ``t`` — the probe_mirror wall becomes D independent, smaller
      probes (per-shard wall times in ``phase_shard_ns``), and the staging
      buffer each probe fills feeds the sharded scatter directly.
    - **paging**: the (host-side) ``DevicePager`` runs unchanged over
      global HBM rows; a record's destination shard is its resident row's
      owning block, so page-in/page-out gathers and the spilled-key fire
      are mesh-size independent (and digests stay bit-identical at any D).
    - **degraded tier**: a process-wide device quarantine degrades the
      WHOLE mesh — the live pane ring materializes shard-by-shard through
      the dense snapshot path into the host value mirror, fires continue
      bit-exactly from numpy, and re-promotion at the checkpoint-aligned
      safe point rebuilds the sharded state.
    - **snapshots** are per-shard slices with key-group-range manifests
      (``state/shard_layout.split_to_shard_slices``); restore at any mesh
      size (single-chip included) re-slices by the reader's layout.

    Chained dispatches stay pre-partitioned end-to-end: state flows out of
    the ``shard_map`` step with ``out_specs == in_specs`` (key-slot axis on
    ``KG_AXIS``), batch rows are ``device_put`` pre-partitioned onto the
    same axis, and nothing in between reshards — one XLA compile per
    (mesh size, K_cap, batch geometry), asserted by the tier-1 smoke via
    :meth:`mesh_step_cache_size`.
    """

    _SHARDED_HOST_TIER = True
    _SHARDED_PAGING = True
    _SHARDED_DEGRADE = True
    #: the single-dispatch ``lax.scan`` lane stays off on the mesh: the
    #: exchange routing (bucket plan, sticky capacity) is host-computed
    #: per batch.  Super-batch STAGING still applies — the fused host pass
    #: concatenates the staged batches, so the C probe, the all_to_all
    #: exchange, and (with the probe on) the device probe dispatch each
    #: run once per super-batch instead of once per micro-batch.
    _FUSED_SCAN = False

    def __init__(self, *args, mesh: Optional[Mesh] = None,
                 n_devices: Optional[int] = None, **kwargs):
        if mesh is None:
            mesh = make_mesh(n_devices)
        self.mesh = mesh
        self.n_shards = int(mesh.devices.size)
        kwargs.setdefault("sharding", state_sharding(mesh))
        super().__init__(*args, **kwargs)
        #: row sharding for the incoming batch (split over devices like a
        #: distributed source's partitions)
        self._row_sharding = NamedSharding(mesh, P(KG_AXIS))
        #: per-shard probe timing buffer (phase_shard_ns feed)
        self._shard_ns_buf = np.zeros(self.n_shards, np.int64)

    # ---------------------------------------------------------------- layout
    def shard_layout(self):
        """The key-group-range state layout (shared by snapshots, the
        sharded probe, and the record router)."""
        from flink_tpu.parallel.mesh import layout_for
        return layout_for(self.mesh, self._K)

    def _probe_shards(self):
        """Align the fused native probe with the mesh: by default one probe
        shard per device, owning the contiguous slot range
        [t*K/D, (t+1)*K/D) — the rows whose device state block lives on
        mesh device t.  The ownership divisor derives from the ACTUAL probe
        shard count (an explicit ``native_shards`` override, or the native
        pool's 16-shard cap on very wide meshes), so the ranges stay
        balanced when S != D; the last range is open-ended either way.
        The timing buffer feeds the per-shard probe_mirror breakdown."""
        S = min(self.native_shards or self.n_shards, 16)  # C pool cap
        if self._shard_ns_buf.size < S:
            self._shard_ns_buf = np.zeros(S, np.int64)
        return S, -(-self._K // S), self._shard_ns_buf

    def mesh_step_cache_size(self) -> int:
        """Compiled-variant count of the sharded update step (the tier-1
        recompile smoke: one batch geometry must compile exactly once —
        an implicit reshard would mint a second cache entry)."""
        fn = type(self)._mesh_update_step
        try:
            return int(fn._cache_size())
        except Exception:  # noqa: BLE001 — jax without the cache probe
            return -1

    # ------------------------------------------------------------- snapshots
    def snapshot_state(self):
        """Per-shard slices with key-group-range manifests instead of one
        dense array set (the dense layout is recovered by
        ``densify_keyed_snapshot`` on restore/rescale, so every consumer of
        the old format keeps working)."""
        snap = super().snapshot_state()
        # paged snapshots stay dense: their gid space exceeds K_cap and is
        # residency-independent — row-block ownership does not decompose it
        # (the spill store is the per-shard story there)
        if "counts" in snap and self._pager is None:
            from flink_tpu.state.shard_layout import split_to_shard_slices
            mp = getattr(getattr(self, "ctx", None), "max_parallelism", 128)
            snap = split_to_shard_slices(snap, self.shard_layout(), mp)
        return snap

    # ------------------------------------------------------------- device op
    @partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
    def _mesh_update_step(self, leaves_counts, batch, cap: int):
        """One sharded micro-batch: per-device bucket by destination →
        ``all_to_all`` over ICI → scatter-combine into the local state
        block.  ``batch`` = (dest, slots, pane_slots, values), each row-split
        over the mesh; ``cap`` = per-(src, dest) bucket capacity (host-known
        upper bound, so the exchange can never overflow)."""
        leaves, counts = leaves_counts
        D = self.n_shards
        K, Pn = counts.shape
        KD = K // D

        def step(leaves, counts, dest, slots, pane_slots, *values):
            from flink_tpu.parallel.exchange import (all_to_all_rows,
                                                     bucket_plan,
                                                     bucket_rows)
            # ---- bucket local rows by destination shard ([D, cap]); the
            # STABLE plan keeps each key's records in batch order through
            # the exchange (bit-identical per-cell accumulation at any D)
            order, flat, _valid = bucket_plan(dest, D, cap)
            bucket = lambda a, fill: bucket_rows(a, order, flat, D,  # noqa: E731
                                                 cap, fill)
            b_slots = bucket(slots, K)           # K = invalid sentinel
            b_panes = bucket(pane_slots, 0)
            b_vals = [bucket(v, 0) for v in values]
            # ---- the keyed exchange: one collective over ICI
            rx_slots = all_to_all_rows(b_slots).reshape(D * cap)
            rx_panes = all_to_all_rows(b_panes).reshape(D * cap)
            rx_vals = tuple(all_to_all_rows(v).reshape((D * cap,)
                                                       + v.shape[2:])
                            for v in b_vals)
            # ---- local scatter-combine (this device's key-slot block)
            lo = jax.lax.axis_index(KG_AXIS).astype(jnp.int32) * KD
            local = rx_slots - lo
            ok = (rx_slots < K) & (local >= 0) & (local < KD)
            lflat = jnp.where(ok, local * Pn + rx_panes, KD * Pn)
            lifted = tuple(jax.tree_util.tree_leaves(
                self.agg.lift(self._values_tree(rx_vals))))
            flat_leaves = tuple(
                l.reshape((KD * Pn,) + l.shape[2:]) for l in leaves)
            if self.kinds is not None:
                new_flat = scatter_fast(flat_leaves, lflat, lifted,
                                        self.kinds)
            else:
                new_flat = scatter_generic(flat_leaves, lflat, lifted,
                                           self.agg.combine_leaves, KD * Pn)
            new_leaves = tuple(
                l.reshape((KD, Pn) + l.shape[1:]) for l in new_flat)
            ones = jnp.where(ok, 1, 0).astype(jnp.int32)
            new_counts = counts.reshape(KD * Pn).at[lflat].add(
                ones, mode="drop").reshape(KD, Pn)
            return new_leaves, new_counts

        nv = len(batch) - 3
        state_spec = P(KG_AXIS)
        in_specs = ((state_spec,) * len(leaves), state_spec,
                    P(KG_AXIS), P(KG_AXIS), P(KG_AXIS)) \
            + (P(KG_AXIS),) * nv
        out_specs = ((state_spec,) * len(leaves), state_spec)
        from flink_tpu.parallel.mesh import shard_map_compat
        fn = shard_map_compat(step, self.mesh, in_specs, out_specs)
        return fn(leaves, counts, *batch)

    def _values_tree(self, flat_values):
        """Rebuild the user value tree from the flat leaves that rode the
        exchange (set by ``_flatten_values`` on the host side)."""
        treedef = self._values_treedef
        return jax.tree_util.tree_unflatten(treedef, list(flat_values))

    @partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
    def _mesh_delta_step(self, dleaves_counts, batch, cap: int):
        """Device-probe DELTA fold over the mesh: the same bucket →
        ``all_to_all`` → local scatter pipeline as ``_mesh_update_step``,
        but into the sharded delta ring (mirror dtypes — warm-row
        contributions carry the host mirror's f64/i64 precision and fold
        into it later via ``wm_apply_delta``)."""
        dleaves, dcounts = dleaves_counts
        D = self.n_shards
        K, Pn = dcounts.shape
        KD = K // D

        def step(dleaves, dcounts, dest, slots, pane_slots, *values):
            from flink_tpu.parallel.exchange import (all_to_all_rows,
                                                     bucket_plan,
                                                     bucket_rows)
            order, flat, _valid = bucket_plan(dest, D, cap)
            bucket = lambda a, fill: bucket_rows(a, order, flat, D,  # noqa: E731
                                                 cap, fill)
            b_slots = bucket(slots, K)
            b_panes = bucket(pane_slots, 0)
            b_vals = [bucket(v, 0) for v in values]
            rx_slots = all_to_all_rows(b_slots).reshape(D * cap)
            rx_panes = all_to_all_rows(b_panes).reshape(D * cap)
            rx_vals = tuple(all_to_all_rows(v).reshape((D * cap,)
                                                       + v.shape[2:])
                            for v in b_vals)
            lo = jax.lax.axis_index(KG_AXIS).astype(jnp.int32) * KD
            local = rx_slots - lo
            ok = (rx_slots < K) & (local >= 0) & (local < KD)
            lflat = jnp.where(ok, local * Pn + rx_panes, KD * Pn)
            lifted = tuple(jax.tree_util.tree_leaves(
                self.agg.lift(self._values_tree(rx_vals))))
            dflat = tuple(l.reshape(KD * Pn) for l in dleaves)
            new_flat = scatter_fast(dflat, lflat, lifted, self.kinds)
            new_leaves = tuple(l.reshape(KD, Pn) for l in new_flat)
            ones = jnp.where(ok, 1, 0).astype(jnp.int32)
            new_counts = dcounts.reshape(KD * Pn).at[lflat].add(
                ones, mode="drop").reshape(KD, Pn)
            return new_leaves, new_counts

        nv = len(batch) - 3
        state_spec = P(KG_AXIS)
        in_specs = ((state_spec,) * len(dleaves), state_spec,
                    P(KG_AXIS), P(KG_AXIS), P(KG_AXIS)) \
            + (P(KG_AXIS),) * nv
        out_specs = ((state_spec,) * len(dleaves), state_spec)
        from flink_tpu.parallel.mesh import shard_map_compat
        fn = shard_map_compat(step, self.mesh, in_specs, out_specs)
        return fn(dleaves, dcounts, *batch)

    @partial(jax.jit, static_argnums=(0,))
    def _mesh_probe_step(self, tab, b, key_lo, key_hi, start):
        """The device-resident key probe as its own dispatch: the mesh
        routing (bucket plan, sticky capacity) is host-computed from the
        resolved slots, so the probe runs once up front and the slots ride
        back with the scalar miss count."""
        from flink_tpu.state.device_keyindex import probe_impl
        _name, probe = probe_impl(int(tab[0].shape[0]))
        slot = probe(*tab, key_lo, key_hi, start)
        valid = jnp.arange(slot.shape[0], dtype=jnp.int32) < b
        miss = valid & (slot < 0)
        return slot, jnp.sum(miss, dtype=jnp.int32)

    def _hot_stage_devprobe(self, keys: np.ndarray, panes: np.ndarray,
                            values, B: int, sync: str) -> None:
        """Mesh device-probe hot stage: probe on device, route the warm
        rows' delta fold (and, under scatter sync, the full state fold)
        through the all_to_all exchange; the host C pass touches only the
        miss rows (sharded by the same contiguous slot ranges as ever)."""
        from flink_tpu.runtime import device_health
        self._ensure_alloc()
        self._ensure_delta()
        if self._dki is None:
            from flink_tpu.state.device_keyindex import DeviceKeyIndex
            self._dki = DeviceKeyIndex(
                initial_capacity=max(1 << 16, 2 * self._K),
                sharding=self._devprobe_table_sharding())
        self._dki.ensure_loaded(self.key_index)
        mi = np.empty(0, np.int64)
        with self._phase("device_probe"):
            key_lo, key_hi, start = self._dki.prepare_batch(keys)
            Bp = _next_pow2(B, 64)

            def pad32(a, fill=0):
                out = np.full(Bp, fill, np.int32)
                out[:B] = a
                return out

            klo_p, khi_p, st_p = pad32(key_lo), pad32(key_hi), pad32(start)
            geom = ("mesh_devprobe", self._dki.capacity, Bp)
            fresh_geom = geom != getattr(self, "_last_dispatch_geom", None)
            self._last_dispatch_geom = geom

            def thunk():
                slot_d, miss_d = self._mesh_probe_step(
                    self._dki.table(), np.int32(B), jnp.asarray(klo_p),
                    jnp.asarray(khi_p), jnp.asarray(st_p))
                return slot_d, int(miss_d)

            try:
                self._hot_dispatches += 1
                slot_d, mc = device_health.guarded_dispatch(
                    thunk, mb=12 * Bp / 1e6, on_oom=None,
                    label=f"{self.name}.device_probe",
                    compile_grace=fresh_geom)
            except DeviceQuarantinedError as err:
                self._devprobe_degrade(err, keys, panes, values)
                return
            slots = np.array(np.asarray(slot_d)[:B], np.int32)
            self._dp_stats["probe_hits"] += B - mc
            self._dp_stats["probe_misses"] += mc
        if mc:
            mi = np.flatnonzero(slots < 0)
            mkeys = np.ascontiguousarray(keys[mi])
            mpanes = np.ascontiguousarray(panes[mi])
            mvalues = jax.tree_util.tree_map(lambda a: np.asarray(a)[mi],
                                             values)
            slots[mi] = self._devprobe_absorb_misses(mkeys, mpanes, mvalues)
        panes_mod = (panes % self._P).astype(np.int32)
        hit_mask = np.ones(B, bool)
        if mc:
            hit_mask[mi] = False
        mb = sum(np.asarray(a).nbytes for a in
                 jax.tree_util.tree_leaves(values)) / 1e6
        if hit_mask.any():
            h_idx = np.flatnonzero(hit_mask)
            h_vals = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[h_idx], values)
            try:
                with self._phase("device_probe"):
                    self._hot_dispatches += 1
                    device_health.guarded_dispatch(
                        lambda: self._apply_delta_update(
                            h_vals, int(h_idx.size), slots[h_idx],
                            panes_mod[h_idx]),
                        mb=mb, label=f"{self.name}.delta_fold")
            except DeviceQuarantinedError as err:
                # warm rows never reached the delta (the chaos/dispatch
                # failure precedes execution): refold exactly those rows
                # on the host; misses are already in the mirror
                self._devprobe_degrade(
                    err, np.ascontiguousarray(keys[h_idx]),
                    np.ascontiguousarray(panes[h_idx]), h_vals)
                return
            self._delta_panes.update(
                int(p) for p in np.unique(panes[h_idx]).tolist())
        if sync == "deferred":
            self._device_stale = True
        else:
            values_np = jax.tree_util.tree_map(np.asarray, values)
            try:
                with self._phase("device_dispatch"):
                    self._hot_dispatches += 1
                    device_health.guarded_dispatch(
                        lambda: self._apply_update(values_np, B, slots,
                                                   panes_mod),
                        mb=mb, label=f"{self.name}.update_step")
            except DeviceQuarantinedError as err:
                # every record is in mirror-land already (delta + misses):
                # degrade without refolding
                self._devprobe_degrade(err)

    def devprobe_step_cache_size(self):
        """Mesh twin of the probed-step recompile smoke: the probe and
        delta steps must each compile once per (table capacity / batch
        geometry, exchange capacity)."""
        out = super().devprobe_step_cache_size()
        for name in ("_mesh_probe_step", "_mesh_delta_step"):
            fn = getattr(type(self), name)
            try:
                out[name] = int(fn._cache_size())
            except Exception:  # noqa: BLE001 — jax without the cache probe
                out[name] = -1
        return out

    # ------------------------------------------------------------- host side
    def _route_batch(self, values, B: int, slots: np.ndarray,
                     panes: np.ndarray):
        """Shared exchange routing for the state and delta folds: pad rows
        to the mesh, compute destination shards, pick the STICKY bucket
        capacity, and device_put the row-split batch.  Returns
        ``(batch, cap)`` for a ``_mesh_*_step`` dispatch."""
        D = self.n_shards
        K = self._K
        KD = K // D
        # pad rows to a multiple of D with invalid-slot sentinels (quantized
        # for a bounded compile count, then re-rounded: D may not be pow2)
        Bp = -(-_quantize(-(-B // D) * D, D) // D) * D

        def pad(a, fill, dtype):
            out = np.full((Bp,) + a.shape[1:], fill, dtype)
            out[:B] = a[:B]
            return out

        slots_p = pad(slots.astype(np.int32), K, np.int32)
        panes_p = pad(panes.astype(np.int32), 0, np.int32)
        dest = np.minimum(slots_p.astype(np.int64) // KD, D - 1).astype(
            np.int32)
        dest[B:] = np.arange(Bp - B) % D  # spread pad rows evenly
        # host-known capacity: max rows any (src block, dest) pair sends.
        # STICKY high-water (the credit-capacity-only-grows rule of
        # ResizingExchange): batch-to-batch skew wobble must not recompile
        # the step — steady state is exactly one compile per (mesh, K,
        # batch geometry), which the tier-1 recompile smoke asserts
        src = np.repeat(np.arange(D), Bp // D)
        per_pair = np.bincount(src * D + dest, minlength=D * D)
        cap = _quantize(int(per_pair.max()))
        cap = self._exchange_cap_hw = max(
            getattr(self, "_exchange_cap_hw", 0), cap)
        vleaves, self._values_treedef = jax.tree_util.tree_flatten(values)
        vpad = [jax.device_put(pad(np.asarray(v), 0, np.asarray(v).dtype),
                               self._row_sharding) for v in vleaves]
        put = lambda a: jax.device_put(a, self._row_sharding)  # noqa: E731
        return (put(dest), put(slots_p), put(panes_p), *vpad), cap

    def _apply_update(self, values, B: int,
                      slots: np.ndarray, panes: np.ndarray) -> None:
        """Mesh replacement for the single-chip ``_update_step`` dispatch:
        the records ride the all_to_all data plane to their owning shard.
        ``panes`` are ring slots (already mod P)."""
        batch, cap = self._route_batch(values, B, slots, panes)
        self._leaves, self._counts = self._mesh_update_step(
            (self._leaves, self._counts), batch, cap)

    def _apply_delta_update(self, values, B: int, slots: np.ndarray,
                            panes: np.ndarray) -> None:
        """Device-probe warm rows: fold into the SHARDED delta ring via the
        same exchange (mirror precision — x64-scoped trace)."""
        batch, cap = self._route_batch(values, B, slots, panes)
        with _x64():
            self._delta_leaves, self._delta_counts = self._mesh_delta_step(
                (self._delta_leaves, self._delta_counts), batch, cap)

    def _update_step(self, leaves, counts, flat_ids, values):  # type: ignore[override]
        """Intercept the base class's device dispatch (the rest of the host
        front — key probe, lateness, pane bookkeeping, growth — is reused
        verbatim from ``WindowAggOperator.process_batch``): decompose the
        flat ids back into (slot, pane) and route through the mesh
        exchange."""
        ids = np.asarray(flat_ids)
        B = ids.shape[0]
        sentinel = self._K * self._P
        valid = ids < sentinel
        slots = np.where(valid, ids // self._P, self._K).astype(np.int32)
        panes = np.where(valid, ids % self._P, 0).astype(np.int32)
        values_np = jax.tree_util.tree_map(np.asarray, values)
        self._apply_update(values_np, B, slots, panes)
        return self._leaves, self._counts

    def _round_key_capacity(self, needed: int) -> int:
        """Key capacity must stay divisible by the shard count (even state
        blocks per device): round the pow2 up to the next multiple of D
        (lcm), which pow2 meshes hit for free.  Paged state never grows —
        K_cap is the pinned resident capacity (overflow pages out)."""
        import math

        if self._pager is not None:
            return self._K
        newK = _next_pow2(max(needed, self.n_shards), self._K)
        return newK * self.n_shards // math.gcd(newK, self.n_shards)


class MeshSessionWindowOperator(SessionWindowOperator):
    """Session windows over a device mesh (VERDICT r2 #2).

    Split of responsibilities — the reference's merging-window path
    (``MergingWindowSet.java:62``, ``WindowOperator.java:311-411``) with the
    TPU-first layering of SURVEY §7.3 "Sessions":

    - **Merge decisions stay on the host** (data-dependent control flow —
      interval-set bookkeeping per key, exactly the ``MergingWindowSet``
      role), inherited unchanged from ``SessionWindowOperator``.
    - **The per-batch value FOLD rides the mesh**: the host sessionizes the
      batch (sort + gap breaks — it needs the boundaries for its merge
      anyway), assigns each batch-local session to the shard owning its key
      (``slot % D``), and ships (dest, local session id, values) through one
      ``shard_map`` step: bucket → ``all_to_all`` over ICI → per-shard
      ``segment_sum``/``min``/``max`` — the "device segment merge kernels".
      Only the folded per-session accumulators come back (orders of
      magnitude smaller than the rows).
    - Snapshots stay the base class's raw-key row format — mesh-size
      independent, rescale/split/merge logic reused verbatim.

    Requires declared scatter kinds (add/min/max); generic combines fall
    back to the host fold, which is still shard-partitioned state-wise.
    """

    def __init__(self, *args, mesh: Optional[Mesh] = None,
                 n_devices: Optional[int] = None, **kwargs):
        if mesh is None:
            mesh = make_mesh(n_devices)
        self.mesh = mesh
        self.n_shards = int(mesh.devices.size)
        super().__init__(*args, **kwargs)
        self._row_sharding = NamedSharding(mesh, P(KG_AXIS))
        self._values_treedef = None

    # ------------------------------------------------------------ device op
    @partial(jax.jit, static_argnums=(0, 2, 3))
    def _mesh_fold_step(self, batch, cap: int, cap_sess: int):
        """One sharded fold: per-device bucket rows by destination shard →
        ``all_to_all`` over ICI → per-shard segment combine keyed by the
        (host-assigned) shard-local session id.  ``batch`` = (dest, sid,
        *value_leaves), each row-split over the mesh; returns
        ``[D * cap_sess, *leaf]`` folded accumulators (shard-major)."""
        D = self.n_shards

        def step(dest, sid, *values):
            from flink_tpu.parallel.exchange import (all_to_all_rows,
                                                     bucket_plan,
                                                     bucket_rows)
            order, flat, _valid = bucket_plan(dest, D, cap)
            bucket = lambda a, fill: bucket_rows(a, order, flat, D,  # noqa: E731
                                                 cap, fill)
            rx_sid = all_to_all_rows(bucket(sid, cap_sess)).reshape(D * cap)
            rx_vals = tuple(
                all_to_all_rows(bucket(v, 0)).reshape((D * cap,)
                                                      + v.shape[2:])
                for v in values)
            lifted = tuple(jax.tree_util.tree_leaves(
                self.agg.lift(self._values_tree(rx_vals))))
            outs = []
            for l, kind, init in zip(lifted, self.kinds,
                                     self.spec.leaf_inits):
                acc = jnp.broadcast_to(
                    jnp.asarray(init, l.dtype),
                    (cap_sess,) + l.shape[1:]).copy()
                if kind == "add":
                    outs.append(acc.at[rx_sid].add(l, mode="drop"))
                elif kind == "min":
                    outs.append(acc.at[rx_sid].min(l, mode="drop"))
                else:
                    outs.append(acc.at[rx_sid].max(l, mode="drop"))
            return tuple(outs)

        nv = len(batch) - 2
        in_specs = (P(KG_AXIS), P(KG_AXIS)) + (P(KG_AXIS),) * nv
        out_specs = (P(KG_AXIS),) * self.spec.num_leaves
        from flink_tpu.parallel.mesh import shard_map_compat
        fn = shard_map_compat(step, self.mesh, in_specs, out_specs)
        return fn(*batch)

    def _values_tree(self, flat_values):
        return jax.tree_util.tree_unflatten(self._values_treedef,
                                            list(flat_values))

    # ------------------------------------------------------------ host side
    def _sessionize(self, slots, ts, values, bounds=None):
        if self.kinds is None:
            return super()._sessionize(slots, ts, values, bounds)  # host fold
        if self.distinct_column is not None and isinstance(values, dict):
            # the distinct column only feeds the HOST-side value sets
            # (_batch_distinct_sets); never ship it through the exchange
            # (string/object dtypes cannot ride the device anyway)
            values = {k: v for k, v in values.items()
                      if k != self.distinct_column}
        order, s_slots, s_ts, sess_id, firsts, lasts = \
            bounds if bounds is not None else self._session_bounds(slots, ts)
        n_sess = int(firsts.size)
        b_key = s_slots[firsts]
        b_start = s_ts[firsts]
        b_end = s_ts[lasts] + self.gap

        D = self.n_shards
        b_dest = (b_key % D).astype(np.int32)
        # shard-local session numbering (0..n_d-1 per shard)
        counts = np.bincount(b_dest, minlength=D)
        base = np.zeros(D, np.int64)
        base[1:] = np.cumsum(counts)[:-1]
        sess_order = np.argsort(b_dest, kind="stable")
        b_local = np.empty(n_sess, np.int64)
        b_local[sess_order] = np.arange(n_sess) - base[b_dest[sess_order]]
        cap_sess = _quantize(int(counts.max()))

        # per-row routing labels (rows in sorted order)
        row_dest = b_dest[sess_id]
        row_sid = b_local[sess_id].astype(np.int32)
        vleaves, self._values_treedef = jax.tree_util.tree_flatten(values)
        vleaves = [np.asarray(v)[order] for v in vleaves]

        # pad rows to a multiple of D; pad rows carry sid = cap_sess (the
        # segment scatter drops them)
        B = row_dest.size
        Bp = -(-_quantize(-(-B // D) * D, D) // D) * D

        def pad(a, fill, dtype):
            out = np.full((Bp,) + a.shape[1:], fill, dtype)
            out[:B] = a[:B]
            return out

        dest_p = pad(row_dest, 0, np.int32)
        dest_p[B:] = np.arange(Bp - B) % D
        sid_p = pad(row_sid, cap_sess, np.int32)
        src = np.repeat(np.arange(D), Bp // D)
        per_pair = np.bincount(src * D + dest_p, minlength=D * D)
        cap = _quantize(int(per_pair.max()))

        put = lambda a: jax.device_put(a, self._row_sharding)  # noqa: E731
        batch = (put(dest_p), put(sid_p),
                 *(put(pad(v, 0, v.dtype)) for v in vleaves))
        folded = self._mesh_fold_step(batch, cap, cap_sess)
        # gather each session's folded acc from its shard block
        flat_idx = b_dest.astype(np.int64) * cap_sess + b_local
        accs = [np.asarray(l)[flat_idx].astype(dt, copy=False)
                for l, dt in zip(folded, self.spec.leaf_dtypes)]
        return b_key, b_start, b_end, accs
