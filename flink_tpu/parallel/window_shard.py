"""Multi-chip windowed aggregation: placement-level sharding.

The single-chip ``WindowAggOperator`` kernels (scatter-combine, pane fire,
clear/purge) are placement-agnostic XLA programs.  Multi-chip execution is
therefore pure *data placement*: state arrays ``[K, P, ...]`` get a
``NamedSharding`` over the key-slot dimension (the key-group axis, SURVEY
§2.7/§7.1) and XLA's SPMD partitioner splits every step:

- scatter updates: indices replicated, each device applies the in-range rows
  of the batch to its local state slice — no collectives in the hot loop;
- fire/clear/purge: row-parallel over K, trivially partitioned;
- results come back sharded; the host emit path reads them once per fire.

This mirrors how the reference scales ``keyBy``: identical operator logic per
subtask, state split by key-group range (``KeyGroupRangeAssignment.java``).
Cross-host record routing (the Netty shuffle analog) is the separate
``parallel/exchange.py`` all_to_all path.
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.parallel.mesh import make_mesh, state_sharding


def sharded_window_operator(mesh: Optional[Mesh] = None, *,
                            n_devices: Optional[int] = None,
                            **kwargs) -> WindowAggOperator:
    """A ``WindowAggOperator`` whose keyed state is sharded over ``mesh``."""
    if mesh is None:
        mesh = make_mesh(n_devices)
    return WindowAggOperator(sharding=state_sharding(mesh), **kwargs)
