"""Multi-chip windowed aggregation: the sharding-aware operator factory.

Until ISSUE 6 this module built a PLACEMENT-only sharded operator (state
arrays carried a ``NamedSharding`` and XLA's SPMD partitioner split the
kernels, but the probe/mirror host path, paging, snapshots, and the record
route all stayed single-chip).  It now fronts the full mesh runtime
(``parallel/mesh_runtime.MeshWindowAggOperator``): one logical SPMD window
operator whose

- state layout is key-group-range blocks per device
  (``state/shard_layout.ShardLayout``),
- record→owning-shard route is an on-device ``all_to_all`` collective
  (``parallel/exchange``), not a host-channel hop,
- probe/mirror maintenance shards by the same contiguous slot ranges
  (per-shard probes; ``phase_shard_ns`` breakdown),
- snapshots are per-shard slices with key-group-range manifests,
  rescalable across mesh sizes,
- the one-dispatch fused lane (ISSUE-11) stages super-batches through the
  fused HOST pass: the C probe, the ``all_to_all`` exchange, and the
  device-probe dispatch each run once per super-batch (``superbatch=``
  kwarg; the single-dispatch ``lax.scan`` megastep itself stays off on
  the mesh — its exchange routing is host-computed per batch).

This mirrors how the reference scales ``keyBy``: identical operator logic
per subtask, state split by key-group range
(``KeyGroupRangeAssignment.java``), the Netty shuffle replaced by ICI.

``placement_sharded_window_operator`` keeps the old placement-only
construction for A/B comparisons (kernel-partitioning correctness without
the mesh runtime).
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.parallel.mesh import make_mesh, state_sharding


def sharded_window_operator(mesh: Optional[Mesh] = None, *,
                            n_devices: Optional[int] = None,
                            **kwargs) -> WindowAggOperator:
    """A window operator whose keyed state, probe path, and record route
    are sharded over ``mesh`` (the full mesh runtime).  ``superbatch=N``
    stages N micro-batches per fused pass (0 = auto-calibrated, the
    ISSUE-11 fused lane); all other ``WindowAggOperator`` kwargs pass
    through unchanged."""
    from flink_tpu.parallel.mesh_runtime import MeshWindowAggOperator
    if mesh is None:
        mesh = make_mesh(n_devices)
    return MeshWindowAggOperator(mesh=mesh, **kwargs)


def placement_sharded_window_operator(mesh: Optional[Mesh] = None, *,
                                      n_devices: Optional[int] = None,
                                      **kwargs) -> WindowAggOperator:
    """The pre-ISSUE-6 construction: single-chip operator logic with state
    arrays placed under a ``NamedSharding`` (XLA splits the kernels; the
    host paths stay unsharded).  Kept for A/B tests."""
    if mesh is None:
        mesh = make_mesh(n_devices)
    return WindowAggOperator(sharding=state_sharding(mesh), **kwargs)
