"""Device mesh + key-group sharding: the TPU analog of slot assignment.

The reference assigns contiguous key-group ranges to parallel subtasks
(``KeyGroupRangeAssignment.java:50-84``); here the same ranges map to devices
of a 1-D ``jax.sharding.Mesh`` over axis ``"kg"`` — state arrays are sharded
along their key-slot dimension, and the router (host side or ``all_to_all``
on device) moves each record to the device owning its key group.  Rescaling =
re-slicing ranges over a different mesh, exactly like the reference's
key-group remapping on restore (``StateAssignmentOperation.java``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.core import keygroups

KG_AXIS = "kg"


def shard_map_compat(fn, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` across jax versions: newer jax spells the replication
    check ``check_vma``, 0.4.x spells it ``check_rep`` (and hosts shard_map
    under ``jax.experimental``).  One shim so every exchange/runtime call
    site stays version-agnostic."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the key-group axis (data parallelism over keyed state)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (KG_AXIS,))


@dataclass(frozen=True)
class KeyGroupSharding:
    """key group -> mesh-position mapping (contiguous ranges, reference
    formula ``KeyGroupRangeAssignment.computeOperatorIndexForKeyGroup``)."""

    max_parallelism: int
    num_shards: int

    def shard_of_key_group(self, kg: np.ndarray) -> np.ndarray:
        kg = np.asarray(kg, np.int64)
        return (kg * self.num_shards // self.max_parallelism).astype(np.int32)

    def shard_of_keys(self, keys: np.ndarray) -> np.ndarray:
        kg = keygroups.assign_to_key_group(keygroups.hash_keys(keys),
                                           self.max_parallelism)
        return self.shard_of_key_group(kg)

    def ranges(self) -> List["keygroups.KeyGroupRange"]:
        return keygroups.key_group_ranges(self.max_parallelism, self.num_shards)


def state_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [K_total, ...] state: key-slot dim split over the mesh."""
    return NamedSharding(mesh, P(KG_AXIS))


def layout_for(mesh: Mesh, K: int):
    """The key-group-range state layout of a [K, ...] array over ``mesh``
    (``state/shard_layout.ShardLayout``): device ``d`` owns the contiguous
    slot block ``[d*K/D, (d+1)*K/D)`` — the rows ``state_sharding`` places
    on it.  The single source of row-ownership truth shared by snapshots
    (per-shard slices + manifests), the sharded probe (contiguous-range
    shard ownership), and the record router (dest = slot // (K/D))."""
    from flink_tpu.state.shard_layout import ShardLayout
    return ShardLayout(int(mesh.devices.size), K)
