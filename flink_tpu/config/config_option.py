"""Typed configuration system.

TPU-native analog of the reference's config layer
(``flink-core/src/main/java/org/apache/flink/configuration/ConfigOption.java``
and ``Configuration.java``): every option is a typed ``ConfigOption`` with a
default, description and optional deprecated/fallback keys; a ``Configuration``
is a string-keyed map read/written through options.  Option groups live in
``flink_tpu/config/options.py`` (the analog of the ~45 ``XxxOptions`` classes).
"""

from __future__ import annotations

import copy
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Generic, Iterator, List, Mapping, Optional, TypeVar

T = TypeVar("T")

_SENTINEL = object()


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("true", "1", "yes", "on"):
        return True
    if s in ("false", "0", "no", "off"):
        return False
    raise ValueError(f"cannot parse boolean from {v!r}")


_DURATION_SUFFIXES = {
    "ms": 1,
    "s": 1000,
    "sec": 1000,
    "min": 60_000,
    "m": 60_000,
    "h": 3_600_000,
    "d": 86_400_000,
}

_MEMORY_SUFFIXES = {
    "b": 1,
    "kb": 1 << 10,
    "k": 1 << 10,
    "mb": 1 << 20,
    "m": 1 << 20,
    "gb": 1 << 30,
    "g": 1 << 30,
    "tb": 1 << 40,
    "t": 1 << 40,
}


def parse_duration_ms(v: Any) -> int:
    """Parse ``"500 ms"``, ``"5 s"``, ``"1 min"``, or a bare number (ms)."""
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip().lower()
    for suffix in sorted(_DURATION_SUFFIXES, key=len, reverse=True):
        if s.endswith(suffix):
            num = s[: -len(suffix)].strip()
            if num:
                return int(float(num) * _DURATION_SUFFIXES[suffix])
    return int(float(s))


def parse_memory_bytes(v: Any) -> int:
    """Parse ``"64 mb"``, ``"1g"``, or a bare number (bytes)."""
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip().lower()
    for suffix in sorted(_MEMORY_SUFFIXES, key=len, reverse=True):
        if s.endswith(suffix):
            num = s[: -len(suffix)].strip()
            if num:
                return int(float(num) * _MEMORY_SUFFIXES[suffix])
    return int(float(s))


_PARSERS: Dict[type, Callable[[Any], Any]] = {
    bool: _parse_bool,
    int: lambda v: int(v),
    float: lambda v: float(v),
    str: lambda v: str(v),
    list: lambda v: list(v) if not isinstance(v, str) else [x.strip() for x in v.split(";") if x.strip()],
}


@dataclass(frozen=True)
class ConfigOption(Generic[T]):
    """A typed config key (analog of ``ConfigOption.java``)."""

    key: str
    type: type
    default: Any = None
    description: str = ""
    deprecated_keys: tuple = ()
    fallback_keys: tuple = ()
    parser: Optional[Callable[[Any], Any]] = None

    def with_deprecated_keys(self, *keys: str) -> "ConfigOption[T]":
        return ConfigOption(self.key, self.type, self.default, self.description,
                            self.deprecated_keys + tuple(keys), self.fallback_keys, self.parser)

    def with_fallback_keys(self, *keys: str) -> "ConfigOption[T]":
        return ConfigOption(self.key, self.type, self.default, self.description,
                            self.deprecated_keys, self.fallback_keys + tuple(keys), self.parser)

    def parse(self, raw: Any) -> T:
        if raw is None:
            return raw
        if self.parser is not None:
            return self.parser(raw)
        if self.type in _PARSERS:
            return _PARSERS[self.type](raw)
        if isinstance(raw, self.type):
            return raw
        return self.type(raw)

    def all_keys(self) -> Iterator[str]:
        yield self.key
        yield from self.fallback_keys
        yield from self.deprecated_keys


class _OptionBuilder:
    def __init__(self, key: str):
        self._key = key

    def bool_type(self):
        return _TypedBuilder(self._key, bool)

    def int_type(self):
        return _TypedBuilder(self._key, int)

    def float_type(self):
        return _TypedBuilder(self._key, float)

    def string_type(self):
        return _TypedBuilder(self._key, str)

    def list_type(self):
        return _TypedBuilder(self._key, list)

    def duration_type(self):
        # stored as int milliseconds
        return _TypedBuilder(self._key, int, parser=parse_duration_ms)

    def memory_type(self):
        return _TypedBuilder(self._key, int, parser=parse_memory_bytes)


class _TypedBuilder(Generic[T]):
    def __init__(self, key: str, typ: type, parser: Optional[Callable[[Any], Any]] = None):
        self._key = key
        self._type = typ
        self._parser = parser

    def default_value(self, default: T, description: str = "") -> ConfigOption[T]:
        return ConfigOption(self._key, self._type, default, description, parser=self._parser)

    def no_default_value(self, description: str = "") -> ConfigOption[T]:
        return self.default_value(None, description)


def key(name: str) -> _OptionBuilder:
    """Entry point mirroring ``ConfigOptions.key(...)``."""
    return _OptionBuilder(name)


class Configuration:
    """String-keyed config map with typed access through ``ConfigOption``.

    Analog of ``Configuration.java``.  Values are stored raw (as given) and
    parsed on read, so YAML/env/CLI sources can all feed it.
    """

    def __init__(self, data: Optional[Mapping[str, Any]] = None):
        self._data: Dict[str, Any] = dict(data) if data else {}

    # -- raw access ---------------------------------------------------------
    def set(self, option, value: Any) -> "Configuration":
        k = option.key if isinstance(option, ConfigOption) else str(option)
        self._data[k] = value
        return self

    def get(self, option, default: Any = _SENTINEL) -> Any:
        if isinstance(option, ConfigOption):
            for k in option.all_keys():
                if k in self._data:
                    return option.parse(self._data[k])
            if default is not _SENTINEL:
                return default
            # Copy mutable defaults so callers can't corrupt the shared
            # class-level ConfigOption object across Configuration instances.
            if isinstance(option.default, (list, dict, set)):
                return copy.copy(option.default)
            return option.default
        if option in self._data:
            return self._data[option]
        return None if default is _SENTINEL else default

    def contains(self, option) -> bool:
        if isinstance(option, ConfigOption):
            return any(k in self._data for k in option.all_keys())
        return option in self._data

    def remove(self, option) -> None:
        if isinstance(option, ConfigOption):
            for k in option.all_keys():
                self._data.pop(k, None)
        else:
            self._data.pop(str(option), None)

    # -- merging / views ----------------------------------------------------
    def add_all(self, other: "Configuration", prefix: str = "") -> "Configuration":
        for k, v in other._data.items():
            self._data[prefix + k] = v
        return self

    def clone(self) -> "Configuration":
        return Configuration(copy.deepcopy(self._data))

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._data)

    def keys(self):
        return self._data.keys()

    def __contains__(self, k) -> bool:
        return self.contains(k)

    def __eq__(self, other) -> bool:
        return isinstance(other, Configuration) and self._data == other._data

    def __repr__(self) -> str:
        return f"Configuration({self._data!r})"

    # -- loading ------------------------------------------------------------
    @staticmethod
    def from_yaml_file(path: str) -> "Configuration":
        """Load a flat ``key: value`` YAML-ish file (flink-conf.yaml analog,
        ``GlobalConfiguration.java``). Only the flat subset is supported —
        which is all the reference's loader supports too."""
        conf = Configuration()
        if not os.path.exists(path):
            return conf
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if ":" not in line:
                    continue
                k, _, v = line.partition(":")
                conf._data[k.strip()] = v.strip().strip("'\"")
        return conf

    @staticmethod
    def from_env(prefix: str = "FLINK_TPU_") -> "Configuration":
        """Env var naming: single ``_`` -> ``.``, double ``__`` -> ``-``
        (option keys use both separators, e.g. FLINK_TPU_PIPELINE_MAX__PARALLELISM
        -> pipeline.max-parallelism)."""
        conf = Configuration()
        for k, v in os.environ.items():
            if k.startswith(prefix):
                name = k[len(prefix):].lower()
                name = name.replace("__", "\x00").replace("_", ".").replace("\x00", "-")
                conf._data[name] = v
        return conf
