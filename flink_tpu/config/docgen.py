"""Config-option documentation generator.

Analog of the reference's generated configuration reference
(``flink-annotations/.../docs/Documentation.java`` + the ``flink-docs``
module, which renders every ``ConfigOption`` into the docs): walks the
registered option classes in :mod:`flink_tpu.config.options` and emits a
markdown table per group.

    python -m flink_tpu.config.docgen > docs/configuration.md
"""

from __future__ import annotations

import inspect
from typing import List

from flink_tpu.config import options as options_module
from flink_tpu.config.config_option import ConfigOption


def collect_option_groups():
    groups = []
    for name, obj in vars(options_module).items():
        if not inspect.isclass(obj) or name.startswith("_"):
            continue
        opts = [(k, v) for k, v in vars(obj).items()
                if isinstance(v, ConfigOption)]
        if opts:
            groups.append((name, sorted(opts)))
    return sorted(groups)


def render_markdown() -> str:
    lines: List[str] = ["# Configuration reference", "",
                        "Generated from the option classes in "
                        "`flink_tpu/config/options.py` — do not edit by hand.",
                        ""]
    for group, opts in collect_option_groups():
        lines.append(f"## {group}")
        lines.append("")
        lines.append("| key | default | type | description |")
        lines.append("|---|---|---|---|")
        for _attr, opt in opts:
            desc = (opt.description or "").replace("|", "\\|")
            typ = getattr(opt.type, "__name__", opt.type)
            lines.append(f"| `{opt.key}` | `{opt.default!r}` | "
                         f"{typ} | {desc} |")
        lines.append("")
    return "\n".join(lines)


if __name__ == "__main__":
    print(render_markdown())
