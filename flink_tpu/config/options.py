"""Built-in option groups.

Analog of the reference's ``XxxOptions`` classes in
``flink-core/src/main/java/org/apache/flink/configuration/`` (e.g.
``CoreOptions``, ``CheckpointingOptions``, ``StateBackendOptions``,
``TaskManagerOptions``, ``NettyShuffleEnvironmentOptions``).
"""

from flink_tpu.config.config_option import key


class CoreOptions:
    DEFAULT_PARALLELISM = key("parallelism.default").int_type().default_value(
        1, "Default operator parallelism (number of key-group shards driven concurrently).")
    MAX_PARALLELISM = key("pipeline.max-parallelism").int_type().default_value(
        128, "Number of key groups (state sharding unit; rescaling upper bound).")
    AUTO_WATERMARK_INTERVAL = key("pipeline.auto-watermark-interval").duration_type().default_value(
        200, "Periodic watermark emission interval in ms.")
    OBJECT_REUSE = key("pipeline.object-reuse").bool_type().default_value(
        True, "Batches are passed by reference between chained operators.")


class ExecutionOptions:
    MICRO_BATCH_SIZE = key("execution.micro-batch-size").int_type().default_value(
        65536, "Records per device micro-batch (the batched mailbox default action).")
    MICRO_BATCH_TIMEOUT_MS = key("execution.micro-batch-timeout").duration_type().default_value(
        5, "Max ms to wait filling a micro-batch before flushing a partial one.")
    RUNTIME_MODE = key("execution.runtime-mode").string_type().default_value(
        "STREAMING", "STREAMING | BATCH.")
    BUFFER_TIMEOUT_MS = key("execution.buffer-timeout").duration_type().default_value(
        100, "Output flush interval in ms.")


class StateOptions:
    BACKEND = key("state.backend").string_type().default_value(
        "hbm", "Keyed state backend: 'hbm' (device-resident dense arrays) or 'host' (numpy).")
    KEY_CAPACITY = key("state.backend.hbm.key-capacity").int_type().default_value(
        1 << 20, "Initial dense key-slot capacity per key-group shard (grows by doubling).")
    PANE_RING_SLOTS = key("state.backend.hbm.pane-ring-slots").int_type().default_value(
        0, "Pane ring slots (0 = derive from window size / lateness).")
    CHECKPOINT_DIR = key("state.checkpoints.dir").string_type().default_value(
        None, "Directory for checkpoint snapshots.")
    SAVEPOINT_DIR = key("state.savepoints.dir").string_type().default_value(
        None, "Directory for user-triggered savepoints.")
    INCREMENTAL = key("state.backend.incremental").bool_type().default_value(
        False, "Incremental checkpoints: delta-tracking operators ship "
        "pane-granular / changelog-suffix increments against the last "
        "confirmed base instead of full snapshots — checkpoint bytes scale "
        "with the change rate.  Savepoints and final (drain) snapshots "
        "stay full/self-contained.")
    CHANGELOG_MATERIALIZATION_THRESHOLD = key(
        "state.changelog.materialization-threshold").int_type().default_value(
        256, "Changelog backend: auto-materialize (full inner snapshot + "
        "log truncation) once the mutation log reaches this many entries; "
        "0 keeps materialization manual.")


class CheckpointingOptions:
    INTERVAL = key("execution.checkpointing.interval").duration_type().default_value(
        0, "Checkpoint interval in ms (0 disables periodic checkpoints).")
    TIMEOUT = key("execution.checkpointing.timeout").duration_type().default_value(
        600_000, "Checkpoint timeout in ms.")
    MODE = key("execution.checkpointing.mode").string_type().default_value(
        "EXACTLY_ONCE", "EXACTLY_ONCE | AT_LEAST_ONCE.")
    MAX_CONCURRENT = key("execution.checkpointing.max-concurrent-checkpoints").int_type().default_value(
        1, "Max concurrent in-flight checkpoints.")
    MIN_PAUSE = key("execution.checkpointing.min-pause").duration_type().default_value(
        0, "Minimum pause between checkpoints in ms.")
    RETAINED = key("state.checkpoints.num-retained").int_type().default_value(
        1, "How many completed checkpoints to retain.")
    UNALIGNED = key("execution.checkpointing.unaligned").bool_type().default_value(
        False, "Unaligned checkpoints: the barrier overtakes in-flight "
        "channel data, which is persisted as channel state — checkpoint "
        "duration becomes independent of backpressure.")
    ALIGNMENT_TIMEOUT = key("execution.checkpointing.alignment-timeout").duration_type().default_value(
        None, "Aligned-checkpoint timeout in ms: a checkpoint starts "
        "aligned and ESCALATES to unaligned once alignment exceeds this "
        "(0 = unaligned from the first barrier; None/unset = stay aligned).")
    ALIGNMENT_QUEUE_MAX = key("execution.checkpointing.alignment-queue-max-elements").int_type().default_value(
        8192, "Cap on elements buffered per subtask from barrier-blocked "
        "channels during alignment.  Hitting it escalates to unaligned "
        "when an alignment timeout is configured, and raises a classified "
        "AlignmentBufferOverflowError otherwise — bounded memory either way.")
    INCREMENTAL_MAX_INCREMENTS = key(
        "execution.checkpointing.incremental.max-increments-per-base").int_type().default_value(
        8, "Incremental storage: background-compact a checkpoint into a "
        "self-contained base once its increment chain exceeds this many "
        "links (bounds restore replay depth and retention pinning).")
    INCREMENTAL_REBASE_RATIO = key(
        "execution.checkpointing.incremental.rebase-ratio").float_type().default_value(
        0.5, "Delta-tracking operators take a full re-base cut when dirty "
        "cells exceed this fraction of the dense state grid (an increment "
        "bigger than that stops paying for itself).")


class DeviceOptions:
    PLATFORM = key("device.platform").string_type().default_value(
        None, "Force jax platform ('tpu'|'cpu'); None = jax default.")
    MESH_SHAPE = key("device.mesh.shape").string_type().default_value(
        None, "Mesh shape as 'kg=8' style spec; None = all devices on one 'kg' axis.")
    DONATE_STATE = key("device.donate-state").bool_type().default_value(
        True, "Donate state buffers into the jitted step (in-place HBM update).")
    SCATTER_MODE = key("device.scatter-mode").string_type().default_value(
        "sorted", "Segment aggregation strategy: 'direct' scatter-add | 'sorted' dedupe+unique-scatter.")


class NetworkOptions:
    """Analog of NettyShuffleEnvironmentOptions — host data-plane knobs."""
    BUFFERS_PER_CHANNEL = key("taskmanager.network.memory.buffers-per-channel").int_type().default_value(
        2, "Exclusive credit buffers per channel in the host exchange layer.")
    FLOATING_BUFFERS_PER_GATE = key("taskmanager.network.memory.floating-buffers-per-gate").int_type().default_value(
        8, "Floating credit buffers shared per input gate.")
    BUFFER_SIZE = key("taskmanager.memory.segment-size").memory_type().default_value(
        32 * 1024, "Host exchange buffer (segment) size in bytes.")
    COMPRESSION = key("taskmanager.network.compression.enabled").bool_type().default_value(
        False, "zstd-compress exchange buffers between hosts.")


class TaskManagerOptions:
    """Analog of TaskManagerOptions' managed-memory knobs (FLIP-49)."""
    MANAGED_MEMORY_SIZE = key("taskmanager.memory.managed.size").memory_type().default_value(
        256 << 20, "Managed memory per task executor, split evenly over "
        "its slots; budgeted operators (spill tier, sort/hash buffers) "
        "reserve from the slot's share and fail fast when over-committed.")
    NUM_TASK_SLOTS = key("taskmanager.numberOfTaskSlots").int_type().default_value(
        1, "Task slots offered by one task executor.")


class ShuffleOptions:
    """Analog of the shuffle SPI knobs (ShuffleServiceOptions +
    NettyShuffleEnvironmentOptions' sort-shuffle settings)."""
    SERVICE = key("shuffle.service").string_type().default_value(
        "sort-merge", "Result-partition service for batch exchanges: "
        "'sort-merge' (spilled blocking partitions) | 'pipelined' "
        "(in-memory concurrent) | any name registered via "
        "register_shuffle_service.")
    DIRECTORY = key("shuffle.directory").string_type().default_value(
        None, "Directory for spilled sort-merge partitions (default: a "
        "per-process tmp dir).")
    MEMORY_BUDGET_BYTES = key("shuffle.sort-merge.memory").memory_type().default_value(
        32 << 20, "Clustering buffer bytes before a sort-merge writer "
        "spills one region.")


class RestOptions:
    PORT = key("rest.port").int_type().default_value(8081, "REST/web endpoint port.")
    ADDRESS = key("rest.address").string_type().default_value("127.0.0.1", "REST bind address.")


class HeartbeatOptions:
    INTERVAL = key("heartbeat.interval").duration_type().default_value(
        1000, "Heartbeat interval in ms between coordinator and workers.")
    TIMEOUT = key("heartbeat.timeout").duration_type().default_value(
        5000, "Heartbeat timeout in ms before a worker is declared dead.")


class RestartOptions:
    STRATEGY = key("restart-strategy").string_type().default_value(
        "exponential-delay", "none | fixed-delay | exponential-delay | failure-rate.")
    FIXED_DELAY_ATTEMPTS = key("restart-strategy.fixed-delay.attempts").int_type().default_value(3)
    FIXED_DELAY_DELAY = key("restart-strategy.fixed-delay.delay").duration_type().default_value(1000)
    EXP_INITIAL_BACKOFF = key("restart-strategy.exponential-delay.initial-backoff").duration_type().default_value(100)
    EXP_MAX_BACKOFF = key("restart-strategy.exponential-delay.max-backoff").duration_type().default_value(60_000)
    EXP_MULTIPLIER = key("restart-strategy.exponential-delay.backoff-multiplier").float_type().default_value(2.0)


class HighAvailabilityOptions:
    """Analog of ``HighAvailabilityOptions.java``: coordinator leader
    lease + epoch fencing + job recovery from the HA store
    (``runtime/ha.py``)."""

    MODE = key("high-availability.type").string_type().default_value(
        "none", "'none' (single coordinator) | 'filesystem' (FileHaStore: "
        "leader lease with a monotone fencing epoch, registered job "
        "plans, and the completed-checkpoint pointer recovery consults "
        "before any directory scan).")
    STORAGE_DIR = key("high-availability.storageDir").string_type().default_value(
        None, "Directory backing the FileHaStore (lease, epoch counter, "
        "job registry, checkpoint pointers).  Required when the type is "
        "'filesystem'.")
    LEASE_TTL = key("high-availability.lease.ttl").duration_type().default_value(
        2000, "Leader lease time-to-live in ms.  The holder renews every "
        "ttl/3; a standby acquires the lease (at epoch + 1) once the "
        "deadline passes un-renewed.")
    ORPHAN_TIMEOUT = key("high-availability.worker.orphan-timeout").duration_type().default_value(
        45_000, "Workers self-terminate (committing nothing) when the "
        "coordinator has been silent this long — no control traffic, no "
        "pings — so an orphaned worker pool cannot outlive its leader. "
        "0 disables the reaper.")
    PING_INTERVAL = key("high-availability.coordinator.ping-interval").duration_type().default_value(
        5000, "Coordinator ping cadence in ms: keeps quiet-but-alive "
        "leaders' workers from self-terminating (must be well under the "
        "orphan timeout).")


class MetricOptions:
    REPORTERS = key("metrics.reporters").list_type().default_value(
        [], "Active metric reporter names.")
    LATENCY_INTERVAL = key("metrics.latency.interval").duration_type().default_value(
        0, "Latency-marker emission interval in ms (0 = disabled): sources "
        "emit LatencyMarker probes on this cadence (through the injectable "
        "clock seam); every operator hop records them into per-(source, "
        "hop) latency histograms exported by the reporters and the REST "
        "latency panel.")
    TRACING_ENABLED = key("metrics.tracing.enabled").bool_type().default_value(
        False, "Install the per-process span journal at deploy: hot-stage "
        "phases, checkpoint lifecycle, device-health/paging/exchange/CEP "
        "events record structured spans, exported as Chrome trace-event "
        "JSON (REST /jobs/<id>/trace, Perfetto-viewable).")
    TRACING_BUFFER = key("metrics.tracing.buffer-size").int_type().default_value(
        65536, "Span-journal ring capacity; once full new spans are "
        "dropped and counted (bounded memory, loud truncation).")
    SCOPE_DELIMITER = key("metrics.scope.delimiter").string_type().default_value(".")


class SecurityOptions:
    """Transport security (``SecurityOptions.java`` analog: the
    ``security.ssl.internal.*`` / ``security.ssl.rest.*`` key families)."""

    SSL_INTERNAL_ENABLED = key("security.ssl.internal.enabled").bool_type().default_value(
        False, "Mutual TLS on internal connections (data plane channels, "
               "coordinator control plane).")
    SSL_REST_ENABLED = key("security.ssl.rest.enabled").bool_type().default_value(
        False, "TLS on the REST endpoint (server-auth only).")
    SSL_CERT = key("security.ssl.certificate").string_type().default_value(
        "", "PEM certificate presented by this process.")
    SSL_KEY = key("security.ssl.key").string_type().default_value(
        "", "PEM private key for the certificate.")
    SSL_CA = key("security.ssl.ca").string_type().default_value(
        "", "PEM CA bundle that signs every cluster certificate "
            "(the truststore).")
    AUTH_TOKEN = key("security.auth.token").string_type().default_value(
        "", "Shared cluster secret: HMAC-authenticates control-plane "
            "connections (usable with or without TLS).")
