"""Pluggable filesystem protocols (``flink-filesystems/`` analog).

``s3``: a real AWS-Signature-V4 S3 client + an S3-compatible server facade
over the object store — the framework speaks the ECOSYSTEM's protocol, not
only its own wire formats (VERDICT r2 #4).
"""

from flink_tpu.filesystems.s3 import (S3Client, S3CompatibleServer,
                                      S3SignatureError, sign_v4)

__all__ = ["S3Client", "S3CompatibleServer", "S3SignatureError", "sign_v4"]
