"""S3 protocol: AWS Signature Version 4 client + S3-compatible server.

The reference's S3 filesystems (``flink-filesystems/flink-s3-fs-base/``)
speak the real S3 REST dialect so a job can point at any existing bucket.
This module does the same from first principles — no SDK:

- :func:`sign_v4` implements the documented SigV4 signing process
  (canonical request → string to sign → derived signing key → signature),
  verified against the AWS-published example vector in the tests.
- :class:`S3Client` — path-style PUT/GET/DELETE object + ListObjectsV2
  (XML) against ANY S3-compatible endpoint (AWS, MinIO, this module's
  server), signing every request and sending
  ``x-amz-content-sha256``.
- :class:`S3CompatibleServer` — serves the same dialect over a local
  directory: third-party S3 clients can read/write the framework's
  buckets; incoming signatures are verified by reconstructing the
  canonical request server-side (shared-credential model) and the payload
  hash is checked against the body.
- :class:`S3CheckpointStorage` — the checkpoint-storage seam
  (``runtime/checkpoint``) over the S3 dialect.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from flink_tpu.runtime.checkpoint.objectstore import (
    ObjectStoreCheckpointStorage)

_ALGO = "AWS4-HMAC-SHA256"
_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


class S3SignatureError(Exception):
    pass


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    """AWS URI encoding: unreserved chars pass; space -> %20 (never +)."""
    safe = "-_.~" if encode_slash else "-_.~/"
    return urllib.parse.quote(s, safe=safe)


def _canonical_query(query: str) -> str:
    if not query:
        return ""
    pairs = []
    for part in query.split("&"):
        if not part:
            continue
        k, _, v = part.partition("=")
        pairs.append((_uri_encode(urllib.parse.unquote(k)),
                      _uri_encode(urllib.parse.unquote(v))))
    return "&".join(f"{k}={v}" for k, v in sorted(pairs))


def sign_v4(method: str, url: str, headers: Dict[str, str],
            payload_hash: str, access_key: str, secret_key: str,
            region: str, service: str = "s3",
            amz_date: Optional[str] = None) -> Dict[str, str]:
    """Compute the SigV4 ``Authorization`` header for a request.

    ``headers`` must already include ``host`` (and any ``x-amz-*``
    headers to sign); ``amz_date`` is ``YYYYMMDD'T'HHMMSS'Z'`` (defaults
    to now, and is added to the returned headers as ``x-amz-date``).
    Returns the headers dict extended with ``x-amz-date`` +
    ``Authorization``."""
    split = urllib.parse.urlsplit(url)
    if amz_date is None:
        amz_date = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    out = dict(headers)
    out.setdefault("x-amz-date", amz_date)

    canon_headers = {k.lower().strip(): " ".join(str(v).split())
                     for k, v in out.items()}
    signed = ";".join(sorted(canon_headers))
    # canonical URI: S3 signs the path AS SENT (already once-encoded);
    # every other service URI-encodes each segment AGAIN (the documented
    # double-encoding rule) — getting this wrong is an interop-breaking
    # SignatureDoesNotMatch for any key with reserved characters
    path = split.path or "/"
    canon_uri = path if service == "s3" \
        else _uri_encode(path, encode_slash=False)
    canonical = "\n".join([
        method.upper(),
        canon_uri,
        _canonical_query(split.query),
        "".join(f"{k}:{canon_headers[k]}\n" for k in sorted(canon_headers)),
        signed,
        payload_hash,
    ])
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        _ALGO, amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k_date = _hmac(("AWS4" + secret_key).encode(), date)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    k_signing = _hmac(k_service, "aws4_request")
    signature = hmac.new(k_signing, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    out["Authorization"] = (
        f"{_ALGO} Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={signature}")
    return out


class S3Client:
    """Minimal real-protocol S3 client (path-style addressing)."""

    def __init__(self, endpoint: str, bucket: str, access_key: str,
                 secret_key: str, region: str = "us-east-1",
                 timeout_s: float = 30.0):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout_s = timeout_s
        self._host = urllib.parse.urlsplit(self.endpoint).netloc

    def _request(self, method: str, key: str = "", query: str = "",
                 body: bytes = b""):
        path = "/" + self.bucket + (("/" + _uri_encode(key, False))
                                    if key else "")
        url = self.endpoint + path + (f"?{query}" if query else "")
        payload_hash = hashlib.sha256(body).hexdigest()
        headers = sign_v4(method, url,
                          {"host": self._host,
                           "x-amz-content-sha256": payload_hash},
                          payload_hash, self.access_key, self.secret_key,
                          self.region)
        req = urllib.request.Request(url, data=body if body else None,
                                     method=method, headers=headers)
        return urllib.request.urlopen(req, timeout=self.timeout_s)

    def put_object(self, key: str, data: bytes) -> None:
        self._request("PUT", key, body=data).read()

    def get_object(self, key: str) -> bytes:
        with self._request("GET", key) as r:
            return r.read()

    def delete_object(self, key: str) -> None:
        self._request("DELETE", key).read()

    def list_objects(self, prefix: str = "") -> List[Dict[str, object]]:
        """ListObjectsV2 (single page up to 1000 keys; the dialect's
        continuation-token pagination)."""
        import xml.etree.ElementTree as ET

        out: List[Dict[str, object]] = []
        token = None
        while True:
            q = "list-type=2&prefix=" + _uri_encode(prefix)
            if token:
                q += "&continuation-token=" + _uri_encode(token)
            with self._request("GET", "", query=q) as r:
                root = ET.fromstring(r.read())
            ns = root.tag.split("}")[0] + "}" if root.tag.startswith("{") \
                else ""
            for c in root.findall(f"{ns}Contents"):
                out.append({"key": c.findtext(f"{ns}Key"),
                            "size": int(c.findtext(f"{ns}Size") or 0),
                            "etag": (c.findtext(f"{ns}ETag") or "").strip('"')})
            if (root.findtext(f"{ns}IsTruncated") or "false") != "true":
                return out
            token = root.findtext(f"{ns}NextContinuationToken")

    def list_keys(self, prefix: str = "") -> List[str]:
        return [str(o["key"]) for o in self.list_objects(prefix)]

    # object-store client protocol (put/get/list/delete): lets the generic
    # checkpoint storage run unchanged over the S3 dialect
    def put(self, key: str, data: bytes) -> None:
        self.put_object(key, data)

    def get(self, key: str) -> bytes:
        return self.get_object(key)

    def list(self, prefix: str = "") -> List[str]:
        return self.list_keys(prefix)

    def delete(self, key: str) -> None:
        self.delete_object(key)


class S3CompatibleServer:
    """S3 REST dialect over a local directory (path-style, SigV4-verified).

    Anything speaking real S3 (the AWS CLI with a custom endpoint, MinIO
    clients, boto3, this module's client) can point at it — the
    capability-parity claim of ``flink-s3-fs-base`` in reverse."""

    MAX_KEYS = 1000
    #: accepted request age (SigV4's 15-minute window)
    SKEW_S = 900

    def __init__(self, directory: str, access_key: str, secret_key: str,
                 region: str = "us-east-1", host: str = "127.0.0.1",
                 port: int = 0, require_auth: bool = True):
        self.directory = directory
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.require_auth = require_auth
        os.makedirs(directory, exist_ok=True)
        self._put_lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            # -- plumbing --------------------------------------------------
            def _bucket_key(self) -> Optional[Tuple[str, str]]:
                """(bucket, key), or None after rejecting traversal names —
                ``quote(..., safe="")`` collapses keys to one path segment,
                so only literal "."/".." could escape the served dir."""
                path = urllib.parse.urlsplit(self.path).path
                parts = path.lstrip("/").split("/", 1)
                bucket = urllib.parse.unquote(parts[0])
                key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
                if bucket in ("", ".", "..") or key in (".", ".."):
                    self._error(400, "InvalidBucketName",
                                "bucket/key must not be a dot segment")
                    return None
                return bucket, key

            def _obj_path(self, bucket: str, key: str) -> str:
                safe = urllib.parse.quote(key, safe="")
                return os.path.join(server.directory,
                                    urllib.parse.quote(bucket, safe=""),
                                    safe)

            def _error(self, code: int, s3_code: str, msg: str) -> None:
                body = (f'<?xml version="1.0" encoding="UTF-8"?>'
                        f"<Error><Code>{_xml_escape(s3_code)}</Code>"
                        f"<Message>{_xml_escape(msg)}</Message>"
                        f"</Error>").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/xml")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _ok(self, body: bytes = b"",
                    ctype: str = "application/xml") -> None:
                self.send_response(200)
                if body:
                    self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _verify(self, body: bytes) -> bool:
                if not server.require_auth:
                    return True
                auth = self.headers.get("Authorization", "")
                amz_date = self.headers.get("x-amz-date", "")
                content_sha = self.headers.get("x-amz-content-sha256", "")
                if not auth.startswith(_ALGO) or not amz_date:
                    self._error(403, "AccessDenied", "missing SigV4 auth")
                    return False
                try:
                    fields = dict(
                        f.strip().split("=", 1)
                        for f in auth[len(_ALGO):].strip().split(","))
                    cred = fields["Credential"].split("/")
                    signed_headers = fields["SignedHeaders"].split(";")
                    their_sig = fields["Signature"].strip()
                except (KeyError, ValueError):
                    self._error(403, "AuthorizationHeaderMalformed",
                                "cannot parse Authorization")
                    return False
                if len(cred) != 5 or cred[4] != "aws4_request":
                    self._error(403, "AuthorizationHeaderMalformed",
                                "credential scope must be key/date/region/"
                                "service/aws4_request")
                    return False
                if cred[0] != server.access_key:
                    self._error(403, "InvalidAccessKeyId", cred[0])
                    return False
                # clock-skew window (replay resistance)
                try:
                    then = datetime.datetime.strptime(
                        amz_date, "%Y%m%dT%H%M%SZ").replace(
                            tzinfo=datetime.timezone.utc)
                except ValueError:
                    self._error(403, "AccessDenied", "bad x-amz-date")
                    return False
                now = datetime.datetime.now(datetime.timezone.utc)
                if abs((now - then).total_seconds()) > server.SKEW_S:
                    self._error(403, "RequestTimeTooSkewed", amz_date)
                    return False
                # the payload hash is SIGNED; verify it matches the body
                if content_sha and content_sha != "UNSIGNED-PAYLOAD":
                    if hashlib.sha256(body).hexdigest() != content_sha:
                        self._error(400, "XAmzContentSHA256Mismatch",
                                    "payload hash mismatch")
                        return False
                # reconstruct the canonical request from the SIGNED headers
                hdrs = {h: self.headers.get(h, "") for h in signed_headers}
                url = f"http://{self.headers.get('host', '')}{self.path}"
                expect = sign_v4(
                    self.command, url, hdrs,
                    content_sha or _EMPTY_SHA256,
                    server.access_key, server.secret_key,
                    cred[2], cred[3], amz_date=amz_date)
                ours = expect["Authorization"].rsplit("Signature=", 1)[1]
                if not hmac.compare_digest(ours, their_sig):
                    self._error(403, "SignatureDoesNotMatch",
                                "signature mismatch")
                    return False
                return True

            # -- verbs -----------------------------------------------------
            def do_PUT(self):
                ln = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(ln)
                if not self._verify(body):
                    return
                bk = self._bucket_key()
                if bk is None:
                    return
                bucket, key = bk
                if not key:
                    # CreateBucket
                    os.makedirs(os.path.join(
                        server.directory,
                        urllib.parse.quote(bucket, safe="")), exist_ok=True)
                    return self._ok()
                path = self._obj_path(bucket, key)
                os.makedirs(os.path.dirname(path), exist_ok=True)
                # '#' never occurs in quote(safe="") output, so these
                # sidecar names can never collide with a stored object; the
                # temp suffix is per-request unique (concurrent PUTs of one
                # key must not interleave through a shared temp file)
                import uuid as _uuid
                tmp = f"{path}#tmp{_uuid.uuid4().hex[:8]}"
                with open(tmp, "wb") as f:
                    f.write(body)
                    f.flush()
                    os.fsync(f.fileno())
                etag = hashlib.md5(body).hexdigest()
                with open(tmp + "e", "w") as f:
                    f.write(etag)
                # finalize object+sidecar as one step under the server
                # lock: racing same-key PUTs must not install one writer's
                # object with the other's ETag
                with server._put_lock:
                    os.replace(tmp, path)
                    os.replace(tmp + "e", path + "#etag")
                self.send_response(200)
                self.send_header("ETag", f'"{etag}"')
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                if not self._verify(b""):
                    return
                bk = self._bucket_key()
                if bk is None:
                    return
                bucket, key = bk
                query = urllib.parse.parse_qs(
                    urllib.parse.urlsplit(self.path).query)
                if not key:
                    return self._list(bucket, query)
                path = self._obj_path(bucket, key)
                if not os.path.exists(path):
                    return self._error(404, "NoSuchKey", key)
                with open(path, "rb") as f:
                    data = f.read()
                self._ok(data, ctype="application/octet-stream")

            def do_DELETE(self):
                if not self._verify(b""):
                    return
                bk = self._bucket_key()
                if bk is None:
                    return
                bucket, key = bk
                if not key:
                    # DeleteBucket: only when empty (the S3 contract)
                    bdir = os.path.join(server.directory,
                                        urllib.parse.quote(bucket, safe=""))
                    try:
                        os.rmdir(bdir)
                    except FileNotFoundError:
                        pass
                    except OSError:
                        return self._error(409, "BucketNotEmpty", bucket)
                else:
                    path = self._obj_path(bucket, key)
                    try:
                        os.remove(path)
                    except FileNotFoundError:
                        pass            # idempotent delete (S3 semantics)
                    except OSError as e:
                        return self._error(500, "InternalError", str(e))
                    try:
                        os.remove(path + "#etag")
                    except OSError:
                        pass
                self.send_response(204)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_HEAD(self):
                if not self._verify(b""):
                    return
                bk = self._bucket_key()
                if bk is None:
                    return
                bucket, key = bk
                path = self._obj_path(bucket, key)
                if not os.path.exists(path):
                    self.send_response(404)
                else:
                    self.send_response(200)
                    self.send_header("Content-Length",
                                     str(os.path.getsize(path)))
                self.end_headers()

            def _list(self, bucket: str, query) -> None:
                if query.get("list-type", [""])[0] != "2":
                    return self._error(400, "InvalidArgument",
                                       "only list-type=2 supported")
                prefix = query.get("prefix", [""])[0]
                start = query.get("continuation-token", [""])[0]
                bdir = os.path.join(server.directory,
                                    urllib.parse.quote(bucket, safe=""))
                keys: List[str] = []
                if os.path.isdir(bdir):
                    keys = sorted(
                        urllib.parse.unquote(n) for n in os.listdir(bdir)
                        if "#" not in n)     # sidecars/temps never list
                keys = [k for k in keys if k.startswith(prefix)
                        and (not start or k > start)]
                page = keys[:server.MAX_KEYS]
                truncated = len(keys) > len(page)
                items = []
                for k in page:
                    p = os.path.join(bdir, urllib.parse.quote(k, safe=""))
                    try:
                        size = os.path.getsize(p)
                        try:  # ETag stored at PUT time (no O(data) reads)
                            if os.path.getmtime(p + "#etag") \
                                    < os.path.getmtime(p):
                                raise OSError("stale sidecar")  # crash gap
                            with open(p + "#etag") as f:
                                etag = f.read().strip()
                        except OSError:
                            with open(p, "rb") as f:
                                etag = hashlib.md5(f.read()).hexdigest()
                    except OSError:
                        continue        # deleted concurrently: skip entry
                    items.append(
                        f"<Contents><Key>{_xml_escape(k)}</Key>"
                        f"<Size>{size}</Size>"
                        f"<ETag>&quot;{etag}&quot;</ETag>"
                        f"<StorageClass>STANDARD</StorageClass></Contents>")
                nxt = (f"<NextContinuationToken>{_xml_escape(page[-1])}"
                       f"</NextContinuationToken>") if truncated else ""
                body = (
                    '<?xml version="1.0" encoding="UTF-8"?>'
                    '<ListBucketResult '
                    'xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                    f"<Name>{_xml_escape(bucket)}</Name>"
                    f"<Prefix>{_xml_escape(prefix)}</Prefix>"
                    f"<KeyCount>{len(page)}</KeyCount>"
                    f"<MaxKeys>{server.MAX_KEYS}</MaxKeys>"
                    f"<IsTruncated>{'true' if truncated else 'false'}"
                    f"</IsTruncated>{nxt}{''.join(items)}"
                    "</ListBucketResult>").encode()
                self._ok(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="s3-server", daemon=True)

    def start(self) -> "S3CompatibleServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def serve_forever(self) -> None:
        """Foreground serving (CLI) — do NOT combine with start()."""
        self._httpd.serve_forever()

    def client(self, bucket: str) -> S3Client:
        return S3Client(self.url, bucket, self.access_key, self.secret_key,
                        self.region)


def _xml_escape(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


class S3CheckpointStorage(ObjectStoreCheckpointStorage):
    """Checkpoint storage over the S3 dialect — the SAME key layout,
    versioned metadata-last protocol and device->host conversion as
    ``ObjectStoreCheckpointStorage`` (it IS that class, parameterized by
    an S3 client), so a job can checkpoint straight into any
    S3-compatible bucket and savepoint tooling reads it unchanged."""

    def __init__(self, endpoint: str, bucket: str, access_key: str,
                 secret_key: str, region: str = "us-east-1",
                 prefix: str = "", retain: int = 3):
        super().__init__(url="", prefix=prefix, retain=retain,
                         client=S3Client(endpoint, bucket, access_key,
                                         secret_key, region))
