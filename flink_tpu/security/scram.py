"""SCRAM-SHA-256 (RFC 5802/7677) message-level state machines.

One implementation of the salted-challenge math shared by every
SCRAM-speaking protocol in the repo: the PostgreSQL wire handshake
(SASL authentication codes 10/11/12) and the Kafka SASL/SCRAM mechanism
(SaslAuthenticate token exchange).  Transport-agnostic: callers move the
RFC's client-first / server-first / client-final / server-final strings
over their own framing.

Mutual authentication: the client proves the password via ClientProof
(the server checks it against the STORED key without learning the
password from the exchange), and the server proves it knows the password
via ServerSignature, which the client verifies."""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
from typing import Dict, Optional, Tuple


def _h(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _attrs(msg: str) -> Dict[str, str]:
    return dict(p.split("=", 1) for p in msg.split(","))


def _saslname_escape(name: str) -> str:
    """RFC 5802 saslname: '=' and ',' must transit as =3D / =2C."""
    return name.replace("=", "=3D").replace(",", "=2C")


def _saslname_unescape(name: str) -> str:
    return name.replace("=2C", ",").replace("=3D", "=")


class ScramClient:
    """Client half: ``first()`` → send; feed the server-first message to
    ``final()`` → send; feed the server-final message to ``verify()``."""

    def __init__(self, username: str, password: str):
        self.username = username
        self.password = password
        self._cnonce = _b64(os.urandom(18))
        self._bare = f"n={_saslname_escape(username)},r={self._cnonce}"
        self._server_sig: Optional[bytes] = None

    def first(self) -> str:
        return "n,," + self._bare

    def final(self, server_first: str) -> str:
        a = _attrs(server_first)
        nonce, salt, iters = a["r"], base64.b64decode(a["s"]), int(a["i"])
        if not nonce.startswith(self._cnonce):
            raise ValueError("SCRAM nonce mismatch (not our challenge)")
        salted = hashlib.pbkdf2_hmac("sha256", self.password.encode(),
                                     salt, iters)
        client_key = _hmac(salted, b"Client Key")
        stored_key = _h(client_key)
        without_proof = f"c=biws,r={nonce}"
        auth_msg = f"{self._bare},{server_first},{without_proof}".encode()
        proof = bytes(a ^ b for a, b in
                      zip(client_key, _hmac(stored_key, auth_msg)))
        self._server_sig = _hmac(_hmac(salted, b"Server Key"), auth_msg)
        return f"{without_proof},p={_b64(proof)}"

    def verify(self, server_final: str) -> None:
        got = base64.b64decode(_attrs(server_final).get("v", ""))
        if self._server_sig is None \
                or not hmac.compare_digest(got, self._server_sig):
            raise ValueError("SCRAM server signature verification failed "
                             "(peer does not know the password)")


class ScramServer:
    """Server half: feed the client-first message + the user's password to
    ``first_response()`` → send; feed the client-final message to
    ``verify_final()`` → (ok, server-final to send)."""

    def __init__(self, iterations: int = 4096):
        self.iterations = iterations
        self._server_first: Optional[str] = None
        self._bare: Optional[str] = None
        self._snonce: Optional[str] = None
        self._salted: Optional[bytes] = None

    @staticmethod
    def username_of(client_first: str) -> str:
        bare = client_first.split(",", 2)[2]
        # n=<saslname> ends at the ,r= attribute (escaped commas inside
        # the name transit as =2C, so this split is unambiguous)
        name = bare.split(",r=", 1)[0]
        if not name.startswith("n="):
            raise ValueError("malformed client-first message")
        return _saslname_unescape(name[2:])

    def first_response(self, client_first: str,
                       password: Optional[str] = None, *,
                       salt: Optional[bytes] = None,
                       salted: Optional[bytes] = None) -> str:
        """Build the server-first message.  Either pass the ``password``
        (the salted key is derived here — one PBKDF2 per handshake, fresh
        random salt), or pass ``salt`` + ``salted`` directly: credential
        stores keep a STABLE per-user salt and cache the salted password,
        so repeated (including unauthenticated) handshakes stop costing a
        fresh 4096-iteration PBKDF2 — and unknown-user handshakes can be
        served with a deterministic decoy salt that never touches a real
        credential (no username enumeration)."""
        self._bare = client_first.split(",", 2)[2]
        cnonce = _attrs(self._bare)["r"]
        if salted is not None:
            if salt is None:
                raise ValueError("salted requires its salt")
            self._salted = salted
        else:
            if password is None:
                raise ValueError("need password or (salt, salted)")
            salt = os.urandom(16) if salt is None else salt
            self._salted = hashlib.pbkdf2_hmac(
                "sha256", password.encode(), salt, self.iterations)
        self._snonce = cnonce + _b64(os.urandom(18))
        self._server_first = (f"r={self._snonce},s={_b64(salt)},"
                              f"i={self.iterations}")
        return self._server_first

    def verify_final(self, client_final: str) -> Tuple[bool, str]:
        a = _attrs(client_final)
        proof = base64.b64decode(a["p"])
        without_proof = client_final.rsplit(",p=", 1)[0]
        if a.get("r") != self._snonce:
            return False, ""
        client_key = _hmac(self._salted, b"Client Key")
        stored_key = _h(client_key)
        auth_msg = (f"{self._bare},{self._server_first},"
                    f"{without_proof}").encode()
        sig = _hmac(stored_key, auth_msg)
        recovered = bytes(x ^ y for x, y in zip(proof, sig))
        if not hmac.compare_digest(_h(recovered), stored_key):
            return False, ""
        server_sig = _hmac(_hmac(self._salted, b"Server Key"), auth_msg)
        return True, f"v={_b64(server_sig)}"
