"""Transport security: mutual TLS + shared-secret auth for every listener.

Analog of the reference's SSL layer (``SecurityOptions.java`` in flink-core:
``security.ssl.internal.enabled`` for RPC/data/blob traffic and
``security.ssl.rest.enabled`` for the REST endpoint, with keystore/truststore
pairs; setup in ``flink-runtime/.../net/SSLUtils.java``).  Redesigned for the
Python runtime:

- **internal TLS** (data plane ``cluster/net.py``, control plane
  ``cluster/distributed.py``) is MUTUAL: both sides present a certificate
  signed by the cluster CA and verify the peer against it — the reference's
  identical-keystore/truststore internal SSL.
- **REST TLS** is server-only by default (browsers/CLIs connect with the CA
  as trust root), mirroring ``security.ssl.rest.*``.
- an optional **shared auth token** (HMAC over a per-connection nonce) guards
  the coordinator control plane even without TLS — the Kerberos/JAAS slot in
  the reference's security stack, reduced to the single-cluster secret that
  actually protects job submission here.

Certificates are plain PEM files (``ssl_cert`` / ``ssl_key`` / ``ssl_ca``);
:func:`generate_self_signed` mints a CA + node cert for tests and
single-host clusters (the reference ships the same convenience through its
``SSLUtils`` test helpers).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import ssl
from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass
class SecurityConfig:
    """Resolved security settings (``SecurityOptions`` analog)."""

    internal_ssl: bool = False
    rest_ssl: bool = False
    cert_path: Optional[str] = None
    key_path: Optional[str] = None
    ca_path: Optional[str] = None
    auth_token: Optional[str] = None

    # -- contexts ----------------------------------------------------------
    def server_context(self, mutual: bool = True) -> Optional[ssl.SSLContext]:
        if not (self.internal_ssl if mutual else self.rest_ssl):
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_path, self.key_path)
        if mutual:
            ctx.load_verify_locations(self.ca_path)
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def client_context(self, mutual: bool = True) -> Optional[ssl.SSLContext]:
        if not (self.internal_ssl if mutual else self.rest_ssl):
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(self.ca_path)
        # single-host clusters use IP peers; identity is the cluster CA
        ctx.check_hostname = False
        if mutual:
            ctx.load_cert_chain(self.cert_path, self.key_path)
        return ctx

    # -- token auth --------------------------------------------------------
    def sign(self, nonce: bytes) -> bytes:
        """HMAC-SHA256 over a nonce with the cluster secret."""
        assert self.auth_token is not None
        return hmac.new(self.auth_token.encode(), nonce,
                        hashlib.sha256).digest()

    def verify(self, nonce: bytes, mac: bytes) -> bool:
        return hmac.compare_digest(self.sign(nonce), mac)


def load_security_config(conf) -> SecurityConfig:
    """Build a :class:`SecurityConfig` from a ``Configuration``
    (``SecurityOptions`` keys, see ``config/options.py``)."""
    from flink_tpu.config.options import SecurityOptions as S

    return SecurityConfig(
        internal_ssl=conf.get(S.SSL_INTERNAL_ENABLED),
        rest_ssl=conf.get(S.SSL_REST_ENABLED),
        cert_path=conf.get(S.SSL_CERT) or None,
        key_path=conf.get(S.SSL_KEY) or None,
        ca_path=conf.get(S.SSL_CA) or None,
        auth_token=conf.get(S.AUTH_TOKEN) or None)


def generate_self_signed(out_dir: str,
                         common_name: str = "flink-tpu") -> Tuple[str, str, str]:
    """Mint a CA plus one node certificate signed by it; returns
    ``(cert_path, key_path, ca_path)``.  Every cluster process shares the
    pair — the reference's identical internal keystore/truststore model."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(out_dir, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)

    def _name(cn):
        return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])

    ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    ca_cert = (x509.CertificateBuilder()
               .subject_name(_name(f"{common_name}-ca"))
               .issuer_name(_name(f"{common_name}-ca"))
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - datetime.timedelta(minutes=5))
               .not_valid_after(now + datetime.timedelta(days=365))
               .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    cert = (x509.CertificateBuilder()
            .subject_name(_name(common_name))
            .issuer_name(ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName("localhost"),
                 x509.IPAddress(__import__("ipaddress").ip_address(
                     "127.0.0.1"))]), critical=False)
            .sign(ca_key, hashes.SHA256()))

    paths = (os.path.join(out_dir, "node.crt"),
             os.path.join(out_dir, "node.key"),
             os.path.join(out_dir, "ca.crt"))
    with open(paths[0], "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(paths[1], "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    with open(paths[2], "wb") as f:
        f.write(ca_cert.public_bytes(serialization.Encoding.PEM))
    return paths
