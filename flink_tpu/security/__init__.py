from flink_tpu.security.ssl_context import (SecurityConfig,
                                            generate_self_signed,
                                            load_security_config)

__all__ = ["SecurityConfig", "generate_self_signed", "load_security_config"]
