"""Test infrastructure: operator harness + chaos/fault injection.

Lazy exports (PEP 562): runtime modules import ``flink_tpu.testing.chaos``
for fault points, and that must not drag the operator harness (and its
operator/core imports) into every runtime import.
"""

from typing import TYPE_CHECKING

__all__ = ["KeyedOneInputOperatorHarness", "TestProcessingTimeService",
           "chaos"]

if TYPE_CHECKING:  # pragma: no cover
    from flink_tpu.testing.harness import (KeyedOneInputOperatorHarness,
                                           TestProcessingTimeService)


def __getattr__(name):
    import importlib
    if name in ("KeyedOneInputOperatorHarness", "TestProcessingTimeService"):
        harness = importlib.import_module("flink_tpu.testing.harness")
        return getattr(harness, name)
    if name == "chaos":
        return importlib.import_module("flink_tpu.testing.chaos")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
