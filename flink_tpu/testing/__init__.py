from flink_tpu.testing.harness import (
    KeyedOneInputOperatorHarness,
    TestProcessingTimeService,
)

__all__ = ["KeyedOneInputOperatorHarness", "TestProcessingTimeService"]
