"""Seeded, deterministic fault injection for the runtime's chaos tests.

Analog of the reference's jepsen harness (``flink-jepsen/src/jepsen/flink/
nemesis.clj``) folded into the library: the runtime exposes **named fault
points** — ``checkpoint.store`` / ``checkpoint.load`` (storage layer),
``channel.send`` / ``channel.recv`` (data plane), ``rpc.call`` (control
plane), ``heartbeat.deliver`` (liveness), ``subtask.run`` /
``subtask.snapshot`` (task threads), ``device.dispatch`` (accelerator
lane), ``queryable.replica_fetch`` (the serving tier's bulk checkpoint
fetch; fired with ``direction="storage->replica"`` so
``Partition(direction=)`` cuts exactly the replica's data plane),
``rescale.redistribute`` / ``rescale.redeploy`` (the rescale lifecycle's
channel-state redistribution and redeploy steps — the
:class:`KillDuringRescale` prey), ``ha.lease`` (the HA store's lease
renewal write: :class:`TruncatedWrite` tears the renewal so the
verify-back demotes the holder loudly; :class:`KillCoordinator` fails
the n-th renewal outright — the leader "dies" and a standby takes over
at epoch + 1) — each
a near-zero-cost :func:`fire` call that consults the
installed :class:`FaultInjector`.  Tests attach *schedules*
(fail-K-times-then-succeed, crash-once-at-N, delay-by-D,
partition-until-healed, seeded probabilistic failure) to points and get a
reproducible failure sequence: schedules keyed by per-point counters (and
per-point RNGs derived from the injector seed) produce identical action
histories on every run regardless of thread interleaving elsewhere.

:class:`FreezableProxy` (promoted out of ``tests/test_nemesis.py``) is the
TCP-level injector for real-socket paths — a one-link network partition
where bytes neither flow nor error while both endpoints stay up.

Usage::

    inj = FaultInjector(seed=7)
    inj.inject("checkpoint.store", FailTimes(2))
    with installed(inj):
        cluster.execute(plan)
    assert inj.history("checkpoint.store")[:2] == ["fail", "fail"]

This module imports only the standard library so every runtime layer can
call :func:`fire` without import cycles or overhead when no injector is
installed.
"""

from __future__ import annotations

import random
import socket
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple, Union

__all__ = [
    "InjectedFault", "FaultSchedule", "FailTimes", "CrashOnceAt", "DelayBy",
    "SlowDisk", "SlowConsumer", "ActionSequence", "Partition",
    "FailWithProbability", "WedgedDevice", "ClockSkew", "KillDuringRescale",
    "KillCoordinator", "TruncatedWrite",
    "FaultInjector", "FreezableProxy", "install", "uninstall", "installed",
    "fire", "active", "blocked", "skew", "truncated",
]

#: actions a schedule may return for one firing
OK = "ok"          # proceed normally
FAIL = "fail"      # raise InjectedFault at the fault point
DROP = "drop"      # suppress delivery (heartbeats) / stall the link (channels)
HANG = "hang"      # block the firing thread until the schedule heals — the
#                    wedged-accelerator model (device_health watchdog prey)
# ("delay", seconds), ("fail", message) and ("skew", offset_ms) are the
# parameterized kinds
Action = Union[str, Tuple[str, float], Tuple[str, str]]


class InjectedFault(RuntimeError):
    """The error raised at a firing fault point (schedule said ``fail``)."""


class FaultSchedule:
    """Maps the 1-based firing count of a point to an action.

    Subclasses implement :meth:`action`; they must be pure functions of
    ``(n, rng)`` (plus their own construction parameters and explicit
    state transitions like :meth:`Partition.heal`) so the same seed yields
    the same failure sequence on every run."""

    def action(self, n: int, rng: random.Random) -> Action:
        raise NotImplementedError

    def dropping(self) -> bool:
        """Is the schedule in a PERSISTENT drop state right now?  Polled by
        stalled senders (via :func:`blocked`) without advancing the firing
        counter.  Default False: a one-shot ``drop`` from a sequence is a
        momentary loss, not a stall — only :class:`Partition` (and
        :class:`WedgedDevice`) keeps a link down until explicitly healed."""
        return False

    def matches(self, ctx: Dict) -> bool:
        """Does this schedule apply to a firing with context ``ctx``?
        Unmatched firings proceed normally WITHOUT advancing the counter,
        RNG or history (so directional schedules stay deterministic
        regardless of how much opposite-direction traffic flows).  Default:
        applies to every firing."""
        return True


class FailTimes(FaultSchedule):
    """Fail the first ``k`` firings, then succeed forever — the transient
    storage-flake model (retry/backoff must absorb exactly ``k`` errors).
    ``message`` customizes the raised error text, letting tests steer
    error CLASSIFIERS (e.g. the device-health monitor reads
    RESOURCE_EXHAUSTED as an OOM)."""

    def __init__(self, k: int, message: Optional[str] = None):
        self.k = k
        self.message = message

    def action(self, n: int, rng: random.Random) -> Action:
        if n > self.k:
            return OK
        return FAIL if self.message is None else (FAIL, self.message)


class CrashOnceAt(FaultSchedule):
    """Fail exactly the ``n``-th firing (1-based), once — crash-at-
    checkpoint-N / crash-mid-window."""

    def __init__(self, n: int):
        self.n = n

    def action(self, n: int, rng: random.Random) -> Action:
        return FAIL if n == self.n else OK


class DelayBy(FaultSchedule):
    """Delay each firing by ``seconds`` (the first ``times`` firings when
    given) — slow-disk / slow-network injection."""

    def __init__(self, seconds: float, times: Optional[int] = None):
        self.seconds = seconds
        self.times = times

    def action(self, n: int, rng: random.Random) -> Action:
        if self.times is not None and n > self.times:
            return OK
        return ("delay", self.seconds)


class SlowDisk(FaultSchedule):
    """Seeded, jittered write stalls — the degrading-disk model (writes
    intermittently take ~seconds instead of ~ms, without erroring).

    Unlike :class:`DelayBy`'s constant delay, each firing stalls with
    probability ``p`` for a duration drawn uniformly from
    ``[min_s, max_s]`` out of the point's own seeded RNG — a realistic
    bursty-latency profile that is still a pure function of
    ``(seed, point, firing count)``, so two runs with one seed stall at
    identical firings for identical durations.  ``times`` bounds the flaky
    period (the disk "recovers" afterwards)."""

    def __init__(self, max_s: float, min_s: float = 0.0, p: float = 1.0,
                 times: Optional[int] = None):
        if max_s < min_s:
            raise ValueError("SlowDisk: max_s must be >= min_s")
        self.max_s = max_s
        self.min_s = min_s
        self.p = p
        self.times = times

    def action(self, n: int, rng: random.Random) -> Action:
        # ALWAYS draw both samples: the RNG stream must advance identically
        # per firing regardless of which branch a firing takes, or later
        # firings' actions would depend on earlier probabilities
        gate = rng.random()
        span = self.min_s + (self.max_s - self.min_s) * rng.random()
        if self.times is not None and n > self.times:
            return OK
        if gate >= self.p:
            return OK
        return ("delay", span)


class SlowConsumer(FaultSchedule):
    """Seeded, BURSTY per-channel drain stalls — the slow-consumer model
    (a sink or operator that intermittently falls behind, so its input
    queues deepen and barriers crawl behind the backlog).

    Fired at the ``channel.recv`` point (one firing per element actually
    dequeued): with probability ``p`` a firing STARTS a burst of ``burst``
    consecutive stalled dequeues, each stalling for a duration drawn
    uniformly from ``[min_s, max_s]`` out of the point's seeded RNG.
    Bursts — not independent per-element stalls — are what make input
    queues deepen faster than they drain, the condition unaligned
    checkpoints exist for.  Still a pure function of (seed, point, firing
    count): both RNG samples are drawn on EVERY firing (the SlowDisk
    invariant), and the burst countdown advances only with the strictly
    ordered firing counter.  ``times`` bounds the flaky period; ``channel``
    (a substring of the channel name) scopes the schedule to matching
    channels — unmatched firings advance nothing."""

    def __init__(self, max_s: float, min_s: float = 0.0, p: float = 0.05,
                 burst: int = 8, times: Optional[int] = None,
                 channel: Optional[str] = None):
        if max_s < min_s:
            raise ValueError("SlowConsumer: max_s must be >= min_s")
        if burst < 1:
            raise ValueError("SlowConsumer: burst must be >= 1")
        self.max_s = max_s
        self.min_s = min_s
        self.p = p
        self.burst = burst
        self.times = times
        self.channel = channel
        self._burst_left = 0

    def matches(self, ctx: Dict) -> bool:
        return self.channel is None or self.channel in str(
            ctx.get("channel", ""))

    def action(self, n: int, rng: random.Random) -> Action:
        # ALWAYS draw both samples (SlowDisk invariant): the RNG stream
        # must advance identically per firing regardless of branch
        gate = rng.random()
        span = self.min_s + (self.max_s - self.min_s) * rng.random()
        if self.times is not None and n > self.times:
            self._burst_left = 0
            return OK
        if self._burst_left > 0:
            self._burst_left -= 1
            return ("delay", span)
        if gate < self.p:
            self._burst_left = self.burst - 1
            return ("delay", span)
        return OK


class TruncatedWrite(FaultSchedule):
    """Tear durable writes short: firings ``at .. at+times-1`` return a
    ``("truncate", frac)`` action — the fault point (storage consults it
    via :meth:`FaultInjector.truncated`) persists only the first
    ``frac`` of the payload's bytes, models a crash/power-cut after the
    file was published (torn page past the rename).  The CRC/size gate on
    load is expected to classify the survivor as corrupt and fall back to
    an older base."""

    def __init__(self, at: int = 1, frac: float = 0.5, times: int = 1):
        if not 0.0 <= frac < 1.0:
            raise ValueError("TruncatedWrite: frac must be in [0, 1)")
        self.at = at
        self.frac = frac
        self.times = times

    def action(self, n: int, rng: random.Random) -> Action:
        if self.at <= n < self.at + self.times:
            return ("truncate", self.frac)
        return OK


class ActionSequence(FaultSchedule):
    """Explicit per-firing script (``["ok", "fail", "fail"]``), then
    ``then`` forever — arbitrary deterministic scenarios."""

    def __init__(self, actions: Sequence[Action], then: Action = OK):
        self.actions = list(actions)
        self.then = then

    def action(self, n: int, rng: random.Random) -> Action:
        return self.actions[n - 1] if n <= len(self.actions) else self.then


class Partition(FaultSchedule):
    """Suppress delivery until healed (``drop`` while active) — the
    logical-link partition; :class:`FreezableProxy` is its TCP twin.

    ``direction`` makes the partition ASYMMETRIC: only firings whose
    context carries a matching ``direction=...`` are dropped; everything
    else (the opposite direction, or callers that pass no direction)
    proceeds without even advancing the schedule's counter.  The classic
    one-way-partition false suspect: A's messages to B blackhole while
    B→A flows.

    ``replica`` scopes the partition to ONE queryable read replica (the
    fan-out siblings fire the same point with ``replica=<name>`` context):
    only the named replica's fetches blackhole — the failover nemesis that
    proves reads continue via the siblings."""

    def __init__(self, active: bool = True,
                 direction: Optional[str] = None,
                 replica: Optional[str] = None):
        self.direction = direction
        self.replica = replica
        self._active = threading.Event()
        if active:
            self._active.set()

    def matches(self, ctx: Dict) -> bool:
        return (self.direction is None
                or ctx.get("direction") == self.direction) \
            and (self.replica is None
                 or ctx.get("replica") == self.replica)

    def partition(self) -> None:
        self._active.set()

    def heal(self) -> None:
        self._active.clear()

    @property
    def healed(self) -> bool:
        return not self._active.is_set()

    def action(self, n: int, rng: random.Random) -> Action:
        return DROP if self._active.is_set() else OK

    def dropping(self) -> bool:
        return self._active.is_set()


class WedgedDevice(FaultSchedule):
    """Hang the firing thread from the ``at``-th firing until healed — the
    wedged-accelerator model (VERDICT r5 weak #1: a SIGKILLed tunnel
    client's device grant is never released; ``block_until_ready`` then
    blocks forever in every process).  Deterministic: firing ``at`` (and
    every later one while active) parks inside :meth:`FaultInjector.fire`
    in a ``dropping()`` poll loop; :meth:`heal` releases it.  The
    device-health watchdog is expected to abandon the hung dispatch from
    outside long before then — the parked thread is the sacrifice."""

    def __init__(self, at: int = 1):
        self.at = at
        self._active = threading.Event()
        self._active.set()
        self._reached = threading.Event()   # a firing actually wedged

    def heal(self) -> None:
        self._active.clear()

    @property
    def healed(self) -> bool:
        return not self._active.is_set()

    @property
    def wedged_once(self) -> bool:
        """Did any firing actually park?  (Test synchronization hook.)"""
        return self._reached.is_set()

    def action(self, n: int, rng: random.Random) -> Action:
        if self._active.is_set() and n >= self.at:
            self._reached.set()
            return HANG
        return OK

    def dropping(self) -> bool:
        return self._active.is_set()


class ClockSkew(FaultSchedule):
    """Seeded clock skew applied per clock READING (``clock.wall`` /
    ``clock.monotonic`` points, consumed via :func:`skew`): offset =
    cumulative step ``jumps`` + linear ``drift_ms_per_read`` + seeded
    jitter in ``[-jitter_ms, +jitter_ms]``.

    ``jumps`` is a sequence of ``(reading_n, delta_ms)``: from the n-th
    reading onward the clock is additionally offset by ``delta_ms``
    (negative = backward step, positive = forward jump).  Pure function of
    (seed, point, reading count) — two runs with one seed see identical
    skewed clocks.  ``times`` bounds the skewed period (NTP "recovers"
    afterwards)."""

    def __init__(self, jumps: Sequence[Tuple[int, float]] = (),
                 drift_ms_per_read: float = 0.0, jitter_ms: float = 0.0,
                 times: Optional[int] = None):
        self.jumps = list(jumps)
        self.drift = float(drift_ms_per_read)
        self.jitter = float(jitter_ms)
        self.times = times

    def action(self, n: int, rng: random.Random) -> Action:
        # ALWAYS draw: the RNG stream must advance identically per reading
        # regardless of the recovered/skewed branch (SlowDisk invariant)
        j = (2.0 * rng.random() - 1.0) * self.jitter
        if self.times is not None and n > self.times:
            return OK
        off = sum(d for at, d in self.jumps if n >= at)
        return ("skew", off + self.drift * n + j)


class KillDuringRescale(FaultSchedule):
    """Kill (or stall, then kill) INSIDE the rescale window — fired at the
    ``rescale.redistribute`` point, which the rescale lifecycle hits after
    the pre-rescale cut is taken and before the job redeploys at the new
    parallelism.  Deterministic: the ``at``-th rescale through the point
    dies (``times`` consecutive rescales when given), everything else
    proceeds.  ``stall_s`` sleeps before the kill so partition/stall
    composites can hold the window open.  The rescale lifecycle is
    expected to absorb the kill: re-trigger the redistribution from the
    same pre-rescale checkpoint (idempotent — the cut is immutable), or
    roll back to the old parallelism past its retry budget; either way
    zero records may be lost or duplicated."""

    def __init__(self, at: int = 1, times: int = 1, stall_s: float = 0.0):
        if times < 1:
            raise ValueError("KillDuringRescale: times must be >= 1")
        self.at = at
        self.times = times
        self.stall_s = stall_s

    def action(self, n: int, rng: random.Random) -> Action:
        if self.at <= n < self.at + self.times:
            if self.stall_s > 0:
                # one composite firing: stall first (holds the rescale
                # window open), then die — FaultInjector sleeps on the
                # delay branch, so model it as a slow kill message
                time.sleep(self.stall_s)
            return (FAIL, f"killed during rescale (firing {n})")
        return OK


class KillCoordinator(FaultSchedule):
    """Kill the LEADER coordinator — fired at the ``ha.lease`` point,
    which the HA store hits on every lease renewal write.  Deterministic:
    the ``at``-th renewal (``times`` consecutive renewals when given)
    fails outright, so the :class:`~flink_tpu.runtime.ha.LeaseRenewer`
    invokes its ``on_lost`` demotion and the leader stands down exactly
    as if the process died mid-flight: the lease ages out, a standby
    acquires it at epoch + 1, recovers the job from the HA store's
    completed-checkpoint pointer and resumes triggering.  ``stall_s``
    sleeps before the kill (a wedged-then-dead leader whose lease file
    goes stale while it still holds sockets open).  The cluster is
    expected to absorb the kill with zero lost and zero duplicated
    records: every stale-epoch completion, deploy and 2PC commit the
    zombie attempts afterwards is fenced."""

    def __init__(self, at: int = 1, times: int = 1, stall_s: float = 0.0):
        if times < 1:
            raise ValueError("KillCoordinator: times must be >= 1")
        self.at = at
        self.times = times
        self.stall_s = stall_s

    def action(self, n: int, rng: random.Random) -> Action:
        if self.at <= n < self.at + self.times:
            if self.stall_s > 0:
                # composite firing: hold the lease stale first, then die —
                # same slow-kill modeling as KillDuringRescale
                time.sleep(self.stall_s)
            return (FAIL, f"coordinator killed at lease renewal {n}")
        return OK


class FailWithProbability(FaultSchedule):
    """Fail each firing with probability ``p`` — drawn from the point's own
    seeded RNG, so the sequence is a pure function of (seed, point)."""

    def __init__(self, p: float):
        self.p = p

    def action(self, n: int, rng: random.Random) -> Action:
        return FAIL if rng.random() < self.p else OK


class FaultInjector:
    """Registry of fault points -> schedules with a deterministic seed.

    Each point gets its own firing counter, its own ``random.Random``
    seeded from ``f"{seed}:{point}"``, and its own action history — two
    runs with the same seed and schedules produce identical per-point
    histories no matter how unrelated threads interleave."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._lock = threading.Lock()
        self._schedules: Dict[str, FaultSchedule] = {}
        self._counts: Dict[str, int] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._history: Dict[str, List[Action]] = {}

    def inject(self, point: str, schedule: FaultSchedule) -> FaultSchedule:
        """Attach ``schedule`` to ``point`` (replacing any previous one);
        returns the schedule for later control (e.g. ``Partition.heal``)."""
        with self._lock:
            self._schedules[point] = schedule
            self._counts.setdefault(point, 0)
            self._history.setdefault(point, [])
        return schedule

    def clear(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._schedules.clear()
            else:
                self._schedules.pop(point, None)

    def _consult(self, point: str, ctx) -> Tuple[Optional[FaultSchedule],
                                                 Action, int]:
        """One firing: match, count, draw the action, record history."""
        with self._lock:
            sched = self._schedules.get(point)
            if sched is None or not sched.matches(ctx):
                return None, OK, 0
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            rng = self._rngs.get(point)
            if rng is None:
                rng = self._rngs[point] = random.Random(
                    f"{self.seed}:{point}")
            act = sched.action(n, rng)
            self._history.setdefault(point, []).append(act)
        return sched, act, n

    def fire(self, point: str, **ctx) -> bool:
        """Consult the point's schedule: returns True to proceed, False to
        suppress delivery (``drop``), sleeps on ``delay``, parks on
        ``hang`` until the schedule heals, raises :class:`InjectedFault`
        on ``fail``."""
        sched, act, n = self._consult(point, ctx)
        if act == OK:
            return True
        if act == DROP:
            return False
        if act == HANG:
            # wedged: park until healed — the hang itself fired exactly
            # once, so determinism survives any wedge duration
            while sched.dropping():
                time.sleep(0.005)
            return True
        if isinstance(act, tuple) and act[0] == "delay":
            time.sleep(act[1])
            return True
        if isinstance(act, tuple) and act[0] == FAIL:
            raise InjectedFault(act[1])
        raise InjectedFault(f"injected fault at {point} (firing {n}, "
                            f"ctx={ctx or {}})")

    def skew(self, point: str, **ctx) -> float:
        """Clock-reading twin of :meth:`fire`: returns the schedule's skew
        offset in ms (``("skew", off)`` actions), 0.0 otherwise.  Each
        reading advances the point's counter/RNG/history like a firing."""
        _sched, act, _n = self._consult(point, ctx)
        if isinstance(act, tuple) and act[0] == "skew":
            return float(act[1])
        return 0.0

    def truncated(self, point: str, nbytes: int, **ctx) -> int:
        """Durable-write twin of :meth:`fire`: returns how many of the
        payload's ``nbytes`` actually persist.  One consult per call (the
        counter/RNG/history advance exactly once — never combine with a
        separate ``fire`` on the same point): ``("truncate", frac)``
        actions keep the first ``int(nbytes * frac)`` bytes, ``drop``
        persists nothing, ``delay``/``hang``/``fail`` behave exactly like
        :meth:`fire`, ``ok`` persists everything."""
        sched, act, n = self._consult(point, ctx)
        if act == OK:
            return nbytes
        if isinstance(act, tuple) and act[0] == "truncate":
            return int(nbytes * float(act[1]))
        if act == DROP:
            return 0
        if act == HANG:
            while sched.dropping():
                time.sleep(0.005)
            return nbytes
        if isinstance(act, tuple) and act[0] == "delay":
            time.sleep(act[1])
            return nbytes
        if isinstance(act, tuple) and act[0] == FAIL:
            raise InjectedFault(act[1])
        raise InjectedFault(f"injected fault at {point} (firing {n}, "
                            f"ctx={ctx or {}})")

    def blocked(self, point: str, **ctx) -> bool:
        """Is the point's schedule in a persistent drop state?  The poll
        primitive for partition-style stalls: a blocked sender re-checks
        until :meth:`Partition.heal` without advancing the firing counter,
        RNG or history — stall duration never corrupts determinism.  A
        one-shot ``drop`` (e.g. from an :class:`ActionSequence`) reads as
        not-blocked, so it delays a sender momentarily instead of hanging
        it forever.  Directional schedules only read blocked for matching
        ``ctx`` (same contract as :meth:`fire`)."""
        with self._lock:
            sched = self._schedules.get(point)
        return sched is not None and sched.dropping() and sched.matches(ctx)

    def history(self, point: Optional[str] = None):
        """Recorded action sequence of one point (or all points) — the
        determinism contract: compare across runs with the same seed."""
        with self._lock:
            if point is not None:
                return list(self._history.get(point, []))
            return {p: list(h) for p, h in self._history.items()}

    def fired(self, point: str) -> int:
        with self._lock:
            return self._counts.get(point, 0)

    def has_schedule(self, point: str) -> bool:
        with self._lock:
            return point in self._schedules


# ---------------------------------------------------------------------------
# global hook — the runtime's fault points call fire(); no injector = no-op
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> FaultInjector:
    global _ACTIVE
    _ACTIVE = injector
    return injector


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[FaultInjector]:
    return _ACTIVE


@contextmanager
def installed(injector: FaultInjector):
    """``with chaos.installed(inj): ...`` — scoped installation; always
    uninstalls, so one test's faults never leak into the next."""
    install(injector)
    try:
        yield injector
    finally:
        uninstall()


def fire(point: str, **ctx) -> bool:
    """The runtime-side hook: near-zero cost when no injector is installed."""
    inj = _ACTIVE
    if inj is None:
        return True
    return inj.fire(point, **ctx)


def blocked(point: str, **ctx) -> bool:
    """Poll a dropped point without re-firing it (counter/RNG/history stay
    untouched): a stalled sender loops on this until the partition heals."""
    inj = _ACTIVE
    return inj is not None and inj.blocked(point, **ctx)


def skew(point: str, **ctx) -> float:
    """Clock-reading hook (``utils/clock.py``): current skew offset in ms
    from an installed :class:`ClockSkew` schedule; 0.0 when no injector or
    no schedule — near-zero cost on the unskewed path."""
    inj = _ACTIVE
    if inj is None:
        return 0.0
    return inj.skew(point, **ctx)


def truncated(point: str, nbytes: int, **ctx) -> int:
    """Durable-write hook (checkpoint storage): how many of ``nbytes``
    persist at this fault point — ``nbytes`` when no injector/schedule."""
    inj = _ACTIVE
    if inj is None:
        return nbytes
    return inj.truncated(point, nbytes, **ctx)


# ---------------------------------------------------------------------------
# TCP-level injector (promoted from tests/test_nemesis.py)
# ---------------------------------------------------------------------------

class FreezableProxy:
    """TCP proxy that can stop forwarding bytes (packets 'drop' while both
    endpoints' sockets stay open) — a one-link network partition.

    Interpose it on a component's path to a real-socket service (object
    store, Kafka broker, worker control plane) and call :meth:`freeze` /
    :meth:`heal`; iptables-free, in-process, deterministic.

    :meth:`freeze` takes an optional ``direction`` for ASYMMETRIC
    partitions: ``"a->b"`` blackholes only client→server bytes (requests
    vanish, responses would flow), ``"b->a"`` only server→client
    (requests arrive, responses vanish), ``"both"`` (default) the classic
    full blackhole."""

    DIRECTIONS = ("both", "a->b", "b->a")

    def __init__(self, target_host: str, target_port: int):
        self.target = (target_host, target_port)
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._frozen = {"a->b": threading.Event(),
                        "b->a": threading.Event()}
        self._stop = threading.Event()
        self._threads = []
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def freeze(self, direction: str = "both") -> None:
        if direction not in self.DIRECTIONS:
            raise ValueError(f"direction must be one of {self.DIRECTIONS}")
        for d, ev in self._frozen.items():
            if direction in ("both", d):
                ev.set()

    def heal(self, direction: str = "both") -> None:
        if direction not in self.DIRECTIONS:
            raise ValueError(f"direction must be one of {self.DIRECTIONS}")
        for d, ev in self._frozen.items():
            if direction in ("both", d):
                ev.clear()

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                up = socket.create_connection(self.target, timeout=5)
            except OSError:
                conn.close()
                continue
            for a, b, d in ((conn, up, "a->b"), (up, conn, "b->a")):
                t = threading.Thread(target=self._pump, args=(a, b, d),
                                     daemon=True)
                t.start()
                self._threads.append(t)

    def _pump(self, src: socket.socket, dst: socket.socket,
              direction: str) -> None:
        frozen = self._frozen[direction]
        src.settimeout(0.2)
        while not self._stop.is_set():
            try:
                data = src.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            if not data:
                break
            if frozen.is_set():
                # blackhole: this direction's bytes are DROPPED on the
                # floor (never queued — a heal must not deliver stale
                # in-flight traffic the sender already gave up on); the
                # sender neither errors nor progresses, exactly the
                # packets-vanish partition, while the opposite pump may
                # still be forwarding
                continue
            try:
                dst.sendall(data)
            except OSError:
                break
        for s in (src, dst):
            try:
                s.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
