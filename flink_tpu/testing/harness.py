"""Operator test harness.

Analog of the reference's workhorse test infrastructure
(``KeyedOneInputStreamOperatorTestHarness.java`` +
``TestProcessingTimeService.java``, SURVEY §4.2): run one operator with manual
control of elements, watermarks and processing time, collecting everything it
emits — no cluster, no executor.  ``WindowOperatorTest.java`` (3,364 LoC) is
the usage model: push elements + watermarks, assert (value, timestamp) pairs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.core.batch import RecordBatch, StreamElement, Watermark
from flink_tpu.core.functions import RuntimeContext
from flink_tpu.operators.base import StreamOperator


class TestProcessingTimeService:
    """Manually-advanced processing-time clock (``TestProcessingTimeService.java``)."""

    def __init__(self, start_ms: int = 0):
        self.now = start_ms

    def advance_to(self, t_ms: int) -> int:
        self.now = max(self.now, t_ms)
        return self.now


class KeyedOneInputOperatorHarness:
    """Push batches / watermarks / time into one operator; collect its output."""

    def __init__(self, operator: StreamOperator, ctx: Optional[RuntimeContext] = None):
        self.op = operator
        self.time_service = TestProcessingTimeService()
        # operators read wall clock via _now_ms; patch to the test clock
        if hasattr(operator, "_now_ms"):
            operator._now_ms = lambda: self.time_service.now  # type: ignore
        operator.open(ctx or RuntimeContext())
        self.output: List[StreamElement] = []

    # ---- input ----
    def process_batch(self, batch: RecordBatch) -> None:
        self.output.extend(self.op.process_batch(batch))

    def process_elements(self, rows: Sequence[Dict[str, Any]],
                         timestamps: Optional[Sequence[int]] = None) -> None:
        self.process_batch(RecordBatch.from_rows(list(rows), list(timestamps) if timestamps is not None else None))

    def process_watermark(self, ts: int) -> None:
        self.output.extend(self.op.process_watermark(Watermark(ts)))
        self.output.append(Watermark(ts))

    def set_processing_time(self, t_ms: int) -> None:
        self.time_service.advance_to(t_ms)
        self.output.extend(self.op.on_processing_time(t_ms))

    def end_input(self) -> None:
        self.output.extend(self.op.end_input())

    # ---- output ----
    def extract_output_batches(self) -> List[RecordBatch]:
        return [e for e in self.output if isinstance(e, RecordBatch)]

    def extract_output_rows(self) -> List[Dict[str, Any]]:
        rows: List[Dict[str, Any]] = []
        for b in self.extract_output_batches():
            rws = b.to_rows()
            if b.timestamps is not None:
                for r, t in zip(rws, np.asarray(b.timestamps)):
                    r["__ts__"] = int(t)
            rows.extend(rws)
        return rows

    def extract_watermarks(self) -> List[int]:
        return [e.timestamp for e in self.output if isinstance(e, Watermark)]

    def clear_output(self) -> None:
        self.output = []

    # ---- checkpointing ----
    def snapshot(self) -> Dict[str, Any]:
        return self.op.snapshot_state()

    @staticmethod
    def restored(operator: StreamOperator, snapshot: Dict[str, Any],
                 ctx: Optional[RuntimeContext] = None) -> "KeyedOneInputOperatorHarness":
        h = KeyedOneInputOperatorHarness(operator, ctx)
        operator.restore_state(snapshot)
        return h


def sorted_rows(rows: List[Dict[str, Any]], by: Tuple[str, ...]) -> List[Dict[str, Any]]:
    return sorted(rows, key=lambda r: tuple(r[k] for k in by))
