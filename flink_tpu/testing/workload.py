"""Shared workload generators: the diurnal load curve (ISSUE-14/15).

ONE implementation of the millions-of-users day curve drives both
``bench.py --autoscale`` and the scenario suite (``flink_tpu/scenarios``)
— twin generators would drift, and the whole point of the curve is that
the autoscaler, the chaos schedules, and the budget gates all see the
same arrival process.

Pacing goes through the :mod:`flink_tpu.utils.clock` seam
(``clock.sleep``) so chaos clock schedules and tests see one time
surface; data is fully determined by ``seed`` (two instances with the
same arguments generate bit-identical streams — the scenario harness
runs its unfaulted control leg on a fresh instance and compares
committed digests).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.connectors.sources import Source, SourceSplit
from flink_tpu.core.batch import RecordBatch
from flink_tpu.utils import clock

__all__ = ["DiurnalSource"]


class DiurnalSource(Source):
    """Diurnal load-curve generator: a stable-split bounded source whose
    per-batch emission pace follows a day curve — slow at the edges (the
    overnight trough), fastest in the middle (the traffic peak) — so the
    arrival rate crosses the (injected, per-dequeue) consumer capacity
    mid-stream and recrosses it on the way down.  Splits are fixed
    (2 by default) regardless of job parallelism: the autoscaler's
    stable-split rescale contract.

    ``value_fn(rng, n) -> ndarray`` shapes the value column (default: all
    ones — count-like sums stay exact in float64, the digest-comparison
    convention); keys are uniform over ``[0, n_keys)`` and timestamps are
    sorted uniform over ``[0, span_ms)`` per split.

    Replay fast-forward: a rescale restore re-reads each split from batch
    0; batches already emitted once (tracked per split in ``_progress``)
    re-yield WITHOUT re-sleeping the pre-cut day curve — re-pacing would
    add seconds of dead time per restore and shift the remaining curve.

    ``paced=False`` drops the sleeps entirely (data identical): the
    scenario harness's unfaulted control leg runs at full speed.
    """

    bounded = True

    def __init__(self, n_records: int, n_keys: int, batch_size: int,
                 span_ms: int, peak_s: float, trough_s: float,
                 n_splits: int = 2, seed: int = 31,
                 key_column: str = "k", value_column: str = "v",
                 ts_column: str = "t",
                 value_fn: Optional[Callable[[np.random.Generator, int],
                                             np.ndarray]] = None,
                 paced: bool = True):
        rng = np.random.default_rng(seed)
        per = n_records // n_splits
        self.n_keys = n_keys
        self.batch_size = batch_size
        self.n_splits = n_splits
        self.key_column = key_column
        self.value_column = value_column
        self.ts_column = ts_column
        self.paced = paced
        self._data: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        for split in range(n_splits):
            ks = rng.integers(0, n_keys, per).astype(np.int64)
            vs = (np.ones(per, np.float64) if value_fn is None
                  else np.asarray(value_fn(rng, per), np.float64))
            ts = np.sort(rng.integers(0, span_ms, per)).astype(np.int64)
            # disjoint per-split timestamp residue classes (ts ≡ split
            # mod n_splits; the floor map is monotone, sortedness holds):
            # two splits can otherwise emit SAME-timestamp events for one
            # key, and which arrives first at a keyed consumer is thread
            # scheduling — order-sensitive consumers (CEP: which strike a
            # bait partial takes) would then differ run to run, making
            # the control-digest comparison flaky on a tie the framework
            # legitimately may resolve either way.  With total per-key
            # event-time order the committed output is deterministic.
            ts = (ts // n_splits) * np.int64(n_splits) + np.int64(split)
            self._data.append((ks, vs, ts))
        nb = max(1, per // batch_size)
        self.n_batches = nb
        #: pace per batch index: trough at the edges, peak (the smallest
        #: sleep = highest arrival rate) in the middle
        self.paces = [
            trough_s - (trough_s - peak_s)
            * math.sin(math.pi * i / max(1, nb - 1))
            for i in range(nb + 2)]
        #: per-split high-water batch index EVER emitted (the replay
        #: fast-forward state — see class docstring)
        self._progress = [0] * n_splits
        #: per-split (batch_index, monotonic_s) log of FIRST emissions —
        #: the scenario harness derives sustained-at-peak throughput from
        #: the middle third of the curve
        self._emit_log: List[List[Tuple[int, float]]] = \
            [[] for _ in range(n_splits)]
        self._lock = threading.Lock()

    # -- Source contract ---------------------------------------------------
    def create_splits(self, parallelism: int) -> List[SourceSplit]:
        return [SourceSplit(self, i, self.n_splits)
                for i in range(self.n_splits)]

    def read_split(self, index: int, of: int):
        ks, vs, ts = self._data[index]
        for bi, lo in enumerate(range(0, len(ks), self.batch_size)):
            hi = min(lo + self.batch_size, len(ks))
            if bi >= self._progress[index]:
                if self.paced:
                    clock.sleep(self.paces[min(bi, len(self.paces) - 1)])
                # split reader threads write, the harness watcher reads
                # progress_frac()/peak_stats() concurrently
                with self._lock:
                    self._progress[index] = bi + 1
                    self._emit_log[index].append((bi, time.monotonic()))
            yield RecordBatch({self.key_column: ks[lo:hi],
                               self.value_column: vs[lo:hi],
                               self.ts_column: ts[lo:hi]})

    # -- accounting helpers (bench + scenario harness share these) ---------
    @property
    def total_records(self) -> int:
        return sum(d[0].size for d in self._data)

    def progress_frac(self) -> float:
        """Fraction of first-time batch emissions done across splits —
        the harness's trigger for arming chaos at the peak."""
        with self._lock:
            return sum(self._progress) / float(
                self.n_splits * self.n_batches)

    def expected_per_key(self) -> Dict[int, Tuple[int, float]]:
        """Per-key ``(count, value_sum)`` over the WHOLE generated stream
        — the exactly-once ledger both bench and harness check against.
        Vectorized: a per-row Python loop costs seconds at the full
        tier's 500k records."""
        ks = np.concatenate([d[0] for d in self._data])
        vs = np.concatenate([d[1] for d in self._data])
        uniq, inv = np.unique(ks, return_inverse=True)
        counts = np.bincount(inv)
        sums = np.bincount(inv, weights=vs)
        return {int(k): (int(c), float(s))
                for k, c, s in zip(uniq.tolist(), counts.tolist(),
                                   sums.tolist())}

    def peak_stats(self) -> Dict[str, float]:
        """Sustained throughput over the curve's middle third (the peak):
        records first-emitted there divided by the emission span."""
        lo, hi = self.n_batches // 3, (2 * self.n_batches) // 3
        t0, t1, records = None, None, 0
        with self._lock:
            logs = [list(log) for log in self._emit_log]
        for log in logs:
            for bi, t in log:
                if lo <= bi < hi:
                    t0 = t if t0 is None else min(t0, t)
                    t1 = t if t1 is None else max(t1, t)
                    records += self.batch_size
        span = (t1 - t0) if (t0 is not None and t1 is not None) else 0.0
        return {"peak_records": float(records),
                "peak_span_s": round(span, 3),
                "peak_records_per_sec": round(records / span, 1)
                if span > 1e-6 else 0.0}
