"""Out-of-core batch runtime: external merge sort + grace hash join.

The reference's batch operators run out-of-core by design —
``ExternalSorter`` (normalized-key sort over MemorySegments with spill +
k-way merge, ``flink-runtime/.../operators/sort/``) and the spilling hybrid
hash join (``operators/hash/MutableHashTable.java``).  This module is the
columnar analog: runs/partitions are FTB files of RecordBatches (CRC-framed,
block-compressed — the same on-disk format as the connectors), and the
in-memory kernels stay the vectorized argsort / span-intersection joins of
``dataset/optimizer.py`` — spilling changes WHERE data lives, not how a run
is processed.

- :class:`ExternalSorter`: accumulate batches; when the in-memory rows
  exceed the budget, sort the run (argsort on the composite key) and spill
  it; ``merged()`` streams a k-way merge over all runs in bounded memory.
- :class:`GraceHashJoin`: partition both sides by key hash into B bucket
  files; join bucket-by-bucket in memory (each bucket pair must fit — the
  grace scheme; B is chosen from the budget).

Budget: ``FLINK_TPU_BATCH_MEMORY_ROWS`` rows (default 4M) — the managed-
memory knob of the batch runtime (``MemoryManager`` analog).  The dataset
drivers switch to these paths automatically above the budget.
"""

from __future__ import annotations

import heapq
import os
import tempfile
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.core.batch import RecordBatch


def memory_budget_rows() -> int:
    try:
        return int(os.environ.get("FLINK_TPU_BATCH_MEMORY_ROWS", 1 << 22))
    except ValueError:
        return 1 << 22


def _sort_key(batch: RecordBatch, columns: Sequence[str]):
    """np.lexsort keys (last = primary, lexsort convention)."""
    return [np.asarray(batch.column(c)) for c in reversed(columns)]


class _RunCursor:
    """Streaming cursor over one sorted spilled run (batch at a time)."""

    def __init__(self, path: str, columns: Sequence[str]):
        from flink_tpu.formats import read_ftb

        self._it = read_ftb(path)
        self.columns = columns
        self._batch: Optional[RecordBatch] = None
        self._keys = None
        self._pos = 0
        self._advance_batch()

    def _advance_batch(self) -> None:
        self._batch = next(self._it, None)
        self._pos = 0
        if self._batch is not None:
            self._keys = [np.asarray(self._batch.column(c))
                          for c in self.columns]

    @property
    def exhausted(self) -> bool:
        return self._batch is None

    def head_key(self) -> Tuple:
        return tuple(k[self._pos] for k in self._keys)

    def head_scalar(self):
        return self._keys[0][self._pos]

    def pop_row(self) -> Tuple[RecordBatch, int]:
        b, i = self._batch, self._pos
        self._pos += 1
        if self._pos >= len(self._batch):
            self._advance_batch()
        return b, i


class ExternalSorter:
    """Spilling sort: bounded memory regardless of input size
    (``ExternalSorter`` / ``UnilateralSortMerger`` analog)."""

    def __init__(self, columns: Sequence[str], ascending: bool = True,
                 budget_rows: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 emit_batch_rows: int = 1 << 16):
        self.columns = list(columns)
        self.ascending = ascending
        self.budget_rows = budget_rows or memory_budget_rows()
        self.emit_batch_rows = emit_batch_rows
        self._dir = spill_dir or tempfile.mkdtemp(prefix="flink-tpu-sort-")
        self._own_dir = spill_dir is None
        self._pending: List[RecordBatch] = []
        self._pending_rows = 0
        self._runs: List[str] = []

    def add(self, batch: RecordBatch) -> None:
        if len(batch) == 0:
            return
        self._pending.append(batch)
        self._pending_rows += len(batch)
        if self._pending_rows >= self.budget_rows:
            self._spill_run()

    def _sorted_pending(self) -> Optional[RecordBatch]:
        if not self._pending:
            return None
        b = (self._pending[0] if len(self._pending) == 1
             else RecordBatch.concat(self._pending))
        order = np.lexsort(_sort_key(b, self.columns))
        if not self.ascending:
            order = order[::-1]
        self._pending = []
        self._pending_rows = 0
        return b.take(order)

    def _spill_run(self) -> None:
        from flink_tpu.formats import write_ftb

        run = self._sorted_pending()
        if run is None:
            return
        path = os.path.join(self._dir, f"run-{len(self._runs):05d}.ftb")
        chunks = [run.take(np.arange(lo, min(lo + self.emit_batch_rows,
                                             len(run))))
                  for lo in range(0, len(run), self.emit_batch_rows)]
        write_ftb(chunks, path)
        self._runs.append(path)

    def merged(self) -> Iterator[RecordBatch]:
        """K-way merge over the spilled runs + the in-memory tail, streamed
        as bounded batches.  Single-column keys use a vectorized GALLOP
        merge (emit the leading run's whole prefix up to the runner-up's
        head via ``searchsorted`` — numpy slices, not per-row Python);
        composite keys fall back to a row heap."""
        tail = self._sorted_pending()
        if not self._runs:
            if tail is not None:
                yield tail
            self._cleanup()
            return
        if tail is not None:
            from flink_tpu.formats import write_ftb

            path = os.path.join(self._dir, f"run-{len(self._runs):05d}.ftb")
            write_ftb([tail], path)
            self._runs.append(path)
        cursors = [_RunCursor(p, self.columns) for p in self._runs]
        live = [c for c in cursors if not c.exhausted]
        numeric = (live and live[0]._keys[0].dtype.kind in "iuf")
        try:
            if len(self.columns) == 1 and numeric:
                yield from self._merge_gallop(cursors)
            else:
                yield from self._merge_rowheap(cursors)
        finally:
            # abandoned/failed iteration must not leak input-sized run files
            self._cleanup()

    def _merge_gallop(self, cursors: List[_RunCursor]
                      ) -> Iterator[RecordBatch]:
        asc = self.ascending
        out: List[RecordBatch] = []
        out_rows = 0
        live = [c for c in cursors if not c.exhausted]
        while live:
            heads = [c.head_scalar() for c in live]
            # lead cursor + runner-up WITHOUT negating keys (negation would
            # overflow uint64 and wrap INT64_MIN)
            j = int(np.argmin(heads)) if asc else int(np.argmax(heads))
            c = live[j]
            if len(live) == 1:
                hi = len(c._batch)
            else:
                others = [h for i, h in enumerate(heads) if i != j]
                runner_up = min(others) if asc else max(others)
                keys = c._keys[0]
                if asc:
                    # prefix of the (ascending) lead batch <= runner-up
                    hi = int(np.searchsorted(keys, runner_up, side="right"))
                else:
                    # prefix of the DESCENDING lead batch >= runner-up:
                    # count via the reversed (ascending) view
                    hi = len(keys) - int(np.searchsorted(
                        keys[::-1], runner_up, side="left"))
                hi = max(hi, c._pos + 1)
            chunk = c._batch.take(np.arange(c._pos, hi))
            c._pos = hi
            if c._pos >= len(c._batch):
                c._advance_batch()
            out.append(chunk)
            out_rows += len(chunk)
            if out_rows >= self.emit_batch_rows:
                yield (RecordBatch.concat(out) if len(out) > 1 else out[0])
                out, out_rows = [], 0
            live = [x for x in live if not x.exhausted]
        if out:
            yield RecordBatch.concat(out) if len(out) > 1 else out[0]

    def _merge_rowheap(self, cursors: List[_RunCursor]
                       ) -> Iterator[RecordBatch]:
        sign = 1 if self.ascending else -1

        def key_of(c: _RunCursor):
            k = c.head_key()
            return k if sign == 1 else tuple(_Neg(x) for x in k)

        heap = [(key_of(c), j) for j, c in enumerate(cursors)
                if not c.exhausted]
        heapq.heapify(heap)
        out_idx: List[Tuple[RecordBatch, int]] = []

        def flush():
            nonlocal out_idx
            if not out_idx:
                return None
            cols = {}
            first = out_idx[0][0]
            for cname in first.columns:
                cols[cname] = np.asarray(
                    [np.asarray(b.column(cname))[i] for b, i in out_idx])
            ts = (np.asarray([np.asarray(b.timestamps)[i]
                              for b, i in out_idx], np.int64)
                  if first.timestamps is not None else None)
            out_idx = []
            return RecordBatch(cols, timestamps=ts)

        while heap:
            _k, j = heapq.heappop(heap)
            c = cursors[j]
            out_idx.append(c.pop_row())
            if not c.exhausted:
                heapq.heappush(heap, (key_of(c), j))
            if len(out_idx) >= self.emit_batch_rows:
                yield flush()
        last = flush()
        if last is not None:
            yield last

    def sorted_batch(self) -> Optional[RecordBatch]:
        """Materialize the fully sorted result (drivers' convenience)."""
        parts = list(self.merged())
        if not parts:
            return None
        return parts[0] if len(parts) == 1 else RecordBatch.concat(parts)

    def _cleanup(self) -> None:
        for p in self._runs:
            try:
                os.remove(p)
            except OSError:
                pass
        self._runs = []
        if self._own_dir:
            try:
                os.rmdir(self._dir)
            except OSError:
                pass


class _Neg:
    """Ordering inverter for descending k-way merges over mixed types."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return other.v == self.v


class GraceHashJoin:
    """Spilling equi-join (``MutableHashTable`` hybrid hash analog): both
    sides hash-partition into bucket files; each bucket pair joins in
    memory with the span-intersection kernel.

    ``add`` spills INCREMENTALLY: once accumulated input crosses the row
    budget, buffered batches flush to depth-0 bucket files and every later
    batch streams straight to its buckets — so building the join holds at
    most ~budget rows in memory no matter how large the inputs (the
    streamed-plan dam breaker, VERDICT r3 next #6).  Skewed buckets
    recursively repartition with a re-salted hash; a single hot KEY cannot
    be split and joins in memory past ``_MAX_DEPTH``."""

    _MAX_DEPTH = 3

    def __init__(self, left_key: str, right_key: str,
                 budget_rows: Optional[int] = None,
                 num_buckets: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self.left_key = left_key
        self.right_key = right_key
        self.budget_rows = budget_rows or memory_budget_rows()
        self.num_buckets = num_buckets or 0
        self._dir = spill_dir or tempfile.mkdtemp(prefix="flink-tpu-join-")
        self._left: List[RecordBatch] = []
        self._right: List[RecordBatch] = []
        self._rows = [0, 0]
        self._spilled = False
        self._B = 0
        self._file_rows: Dict[str, int] = {}

    def add(self, side: int, batch: RecordBatch) -> None:
        if len(batch) == 0:
            return
        self._rows[side] += len(batch)
        if self._spilled:
            self._write_buckets(side, [batch], depth=0, tag="d0",
                                B=self._B)
            return
        (self._left if side == 0 else self._right).append(batch)
        if self._rows[0] + self._rows[1] > self.budget_rows:
            # switch to spill mode: flush the buffer, stream from now on
            self._spilled = True
            self._B = self.num_buckets or 32
            self._write_buckets(0, self._left, depth=0, tag="d0", B=self._B)
            self._write_buckets(1, self._right, depth=0, tag="d0",
                                B=self._B)
            self._left, self._right = [], []

    def _key_name(self, side: int) -> str:
        return self.left_key if side == 0 else self.right_key

    def _bucket_of(self, keys: np.ndarray, B: int) -> np.ndarray:
        from flink_tpu.core.keygroups import hash_keys

        return (np.abs(hash_keys(keys).astype(np.int64)) % B)

    def _path(self, tag: str, side: int, b: int) -> str:
        return os.path.join(self._dir, f"{tag}-s{side}-b{b:04d}.ftb")

    def _write_buckets(self, side: int, batches, depth: int, tag: str,
                       B: int) -> None:
        from flink_tpu.formats import write_ftb

        os.makedirs(self._dir, exist_ok=True)
        key_name = self._key_name(side)
        for batch in batches:
            keys = np.asarray(batch.column(key_name))
            if depth:  # re-salt: a skewed bucket must re-split differently
                keys = keys + np.int64(depth * 0x9E3779B9) \
                    if keys.dtype.kind in "iu" else keys
            buckets = self._bucket_of(keys, B)
            for b in np.unique(buckets).tolist():
                part = batch.select(buckets == b)
                p = self._path(tag, side, int(b))
                write_ftb([part], p, append=True)
                self._file_rows[p] = self._file_rows.get(p, 0) + len(part)

    def join_pairs(self) -> Iterator[Tuple[RecordBatch, np.ndarray,
                                           RecordBatch, np.ndarray]]:
        """Yields (left_batch, left_idx, right_batch, right_idx) per bucket
        pair; in-memory (single pair) when everything fit the budget."""
        from flink_tpu.operators.joins import _join_pairs

        try:
            if not self._spilled:
                l = RecordBatch.concat(self._left) if self._left else None
                r = RecordBatch.concat(self._right) if self._right else None
                if l is not None and r is not None and len(l) and len(r):
                    li, ri = _join_pairs(
                        np.asarray(l.column(self.left_key)),
                        np.asarray(r.column(self.right_key)))
                    if li.size:
                        yield l, li, r, ri
                return
            parent = self._rows[0] + self._rows[1]
            for b in range(self._B):
                yield from self._join_bucket("d0", b, depth=0,
                                             parent_rows=parent)
        finally:
            self._left, self._right = [], []
            self._rows = [0, 0]
            self._spilled = False
            for p in list(self._file_rows):
                try:
                    os.remove(p)
                except OSError:
                    pass
            self._file_rows = {}
            try:
                os.rmdir(self._dir)
            except OSError:
                pass

    def _join_bucket(self, tag: str, b: int, depth: int, parent_rows: int):
        """Join one bucket pair, recursively repartitioning (streamed —
        batches flow file->file, never fully resident) while it exceeds the
        budget AND re-splitting still shrinks it."""
        from flink_tpu.formats import read_ftb
        from flink_tpu.operators.joins import _join_pairs

        lp, rp = self._path(tag, 0, b), self._path(tag, 1, b)
        rows = self._file_rows.get(lp, 0) + self._file_rows.get(rp, 0)
        if not (os.path.exists(lp) and os.path.exists(rp)):
            return
        if rows > self.budget_rows and depth < self._MAX_DEPTH \
                and rows < parent_rows:
            sub = f"{tag}b{b}"
            B2 = max(2, int(np.ceil(rows / max(self.budget_rows // 2, 1))))
            for side, path in ((0, lp), (1, rp)):
                self._write_buckets(side, read_ftb(path), depth + 1, sub, B2)
            for b2 in range(B2):
                yield from self._join_bucket(sub, b2, depth + 1, rows)
            return
        l = RecordBatch.concat(list(read_ftb(lp)))
        r = RecordBatch.concat(list(read_ftb(rp)))
        if len(l) and len(r):
            li, ri = _join_pairs(np.asarray(l.column(self.left_key)),
                                 np.asarray(r.column(self.right_key)))
            if li.size:
                yield l, li, r, ri
