"""DataSet API: bounded batch processing, columnar + vectorized.

Analog of the reference's DataSet stack (``flink-java``
``ExecutionEnvironment``/``DataSet`` + ``flink-optimizer`` +
``runtime/operators/`` drivers — map/reduce/join/cogroup/cross, external
sort, hybrid hash join).  TPU-first redesign: a dataset IS a columnar
``RecordBatch``; every operator is a whole-array transform (argsort-based
sort, segment reductions for grouping, vectorized equi-join), so the "37
drivers + ManagedMemory sort/hash code" collapse into array programs that
XLA/numpy execute directly.

Plans are lazy: transformations build a small DAG; ``collect()``/``execute``
runs it through the optimizer (``flink_tpu/dataset/optimizer.py``) which
picks join strategies and can ``explain()`` the physical plan.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.core.batch import RecordBatch


@dataclass
class BatchOp:
    """One node of the batch plan DAG."""

    kind: str
    args: Dict[str, Any]
    inputs: List["BatchOp"] = field(default_factory=list)
    #: filled by the optimizer: chosen physical strategy + size estimate
    strategy: Optional[str] = None
    est_rows: Optional[int] = None


class ExecutionEnvironment:
    """``ExecutionEnvironment.getExecutionEnvironment`` analog."""

    def __init__(self, config=None):
        from flink_tpu.config.config_option import Configuration

        #: governs batch exchanges (ShuffleOptions) and future knobs
        self.config = config if config is not None else Configuration()

    @staticmethod
    def get_execution_environment(config=None) -> "ExecutionEnvironment":
        return ExecutionEnvironment(config)

    def from_columns(self, columns: Dict[str, Any]) -> "DataSet":
        cols = {k: np.asarray(v) for k, v in columns.items()}
        return DataSet(self, BatchOp("source", {"batch": RecordBatch(cols)}))

    def from_rows(self, rows: Sequence[Dict[str, Any]]) -> "DataSet":
        return DataSet(self, BatchOp(
            "source", {"batch": RecordBatch.from_rows(list(rows))}))

    def read_file(self, path: str, format: str = "csv", **kw) -> "DataSet":
        return DataSet(self, BatchOp("read", {"path": path, "format": format,
                                              "kw": kw}))

    def generate_sequence(self, start: int, end: int) -> "DataSet":
        # lazy: the streamed executor materializes only budget-sized chunks
        # (``env.generateSequence`` analog)
        return DataSet(self, BatchOp("sequence", {"start": int(start),
                                                  "end": int(end)}))


class DataSet:
    def __init__(self, env: ExecutionEnvironment, op: BatchOp):
        self.env = env
        self.op = op

    def _then(self, kind: str, **args) -> "DataSet":
        return DataSet(self.env, BatchOp(kind, args, [self.op]))

    # -- row-wise -----------------------------------------------------------
    def map(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]]) -> "DataSet":
        return self._then("map", fn=fn)

    def filter(self, fn: Callable[[Dict[str, Any]], np.ndarray]) -> "DataSet":
        return self._then("filter", fn=fn)

    def flat_map(self, fn) -> "DataSet":
        return self._then("flat_map", fn=fn)

    def project(self, *columns: str) -> "DataSet":
        return self._then("project", columns=list(columns))

    # -- grouping / aggregation --------------------------------------------
    def group_by(self, *key_columns: str) -> "GroupedDataSet":
        return GroupedDataSet(self, list(key_columns))

    def distinct(self, *columns: str) -> "DataSet":
        return self._then("distinct", columns=list(columns) or None)

    def sum(self, column: str) -> "DataSet":
        return self._then("global_agg", column=column, how="sum")

    def min(self, column: str) -> "DataSet":
        return self._then("global_agg", column=column, how="min")

    def max(self, column: str) -> "DataSet":
        return self._then("global_agg", column=column, how="max")

    def count(self) -> int:
        # streaming terminal: never holds the result set
        return sum(len(b) for b in self.stream_batches())

    def reduce(self, fn: Callable[[Dict, Dict], Dict]) -> "DataSet":
        return self._then("global_reduce", fn=fn)

    # -- binary -------------------------------------------------------------
    def join(self, other: "DataSet") -> "JoinOperatorBuilder":
        return JoinOperatorBuilder(self, other, how="inner")

    def left_outer_join(self, other: "DataSet") -> "JoinOperatorBuilder":
        return JoinOperatorBuilder(self, other, how="left")

    def right_outer_join(self, other: "DataSet") -> "JoinOperatorBuilder":
        return JoinOperatorBuilder(self, other, how="right")

    def full_outer_join(self, other: "DataSet") -> "JoinOperatorBuilder":
        return JoinOperatorBuilder(self, other, how="full")

    def co_group(self, other: "DataSet") -> "JoinOperatorBuilder":
        return JoinOperatorBuilder(self, other, how="cogroup")

    def cross(self, other: "DataSet") -> "DataSet":
        return DataSet(self.env, BatchOp("cross", {},
                                         [self.op, other.op]))

    def union(self, other: "DataSet") -> "DataSet":
        return DataSet(self.env, BatchOp("union", {}, [self.op, other.op]))

    # -- physical partitioning ----------------------------------------------
    def partition_by_hash(self, *columns: str, num_partitions: int = 0,
                          service: Optional[str] = None) -> "DataSet":
        """``DataSet.partitionByHash`` analog: route rows into hash
        partitions through the configured shuffle service
        (``shuffle.service`` — sort-merge spilled blocking partitions by
        default; ``service=`` overrides per-exchange).  ``num_partitions``
        0 derives the count from the size estimate and the row budget.
        Downstream :meth:`map_partition` sees one partition at a time."""
        return self._then("partition_hash", columns=list(columns),
                          n=int(num_partitions), service=service,
                          config=self.env.config)

    def map_partition(self, fn: Callable[[RecordBatch], RecordBatch]
                      ) -> "DataSet":
        """``DataSet.mapPartition`` analog, vectorized: ``fn`` receives one
        whole partition as a RecordBatch and returns a RecordBatch.  Over
        a :meth:`partition_by_hash` input each hash partition is one call
        (peak memory = one partition); otherwise the full dataset is a
        single partition."""
        return self._then("map_partition", fn=fn)

    # -- ordering -----------------------------------------------------------
    def sort_partition(self, column: str, ascending: bool = True) -> "DataSet":
        return self._then("sort", column=column, ascending=ascending)

    def first_n(self, n: int) -> "DataSet":
        return self._then("first_n", n=n)

    # -- iterations (BSP) ----------------------------------------------------
    def iterate(self, max_iterations: int,
                step: Callable[["DataSet"], "DataSet"],
                termination: Optional[Callable[[RecordBatch, RecordBatch], bool]] = None
                ) -> "DataSet":
        """Bulk iteration (``DataSet.iterate`` analog): ``step`` maps the
        loop dataset to the next superstep; stops at ``max_iterations`` or
        when ``termination(prev_batch, next_batch)`` returns True."""
        return DataSet(self.env, BatchOp(
            "bulk_iterate", {"max_iterations": max_iterations, "step": step,
                             "termination": termination}, [self.op]))

    def delta_iterate(self, workset: "DataSet", key_column: str,
                      max_iterations: int,
                      step: Callable[["DataSet", "DataSet"],
                                     Tuple["DataSet", "DataSet"]]) -> "DataSet":
        """Delta iteration (``DataSet.iterateDelta``): maintains a keyed
        solution set; each superstep maps (solution, workset) -> (delta,
        next_workset); ends when the workset empties."""
        return DataSet(self.env, BatchOp(
            "delta_iterate", {"key_column": key_column,
                              "max_iterations": max_iterations, "step": step},
            [self.op, workset.op]))

    # -- execution -----------------------------------------------------------
    def collect_batch(self) -> RecordBatch:
        from flink_tpu.dataset.optimizer import execute_plan
        return execute_plan(self.op)

    def collect(self) -> List[Dict[str, Any]]:
        return self.collect_batch().to_rows()

    def stream_batches(self) -> "Any":
        """Pull-stream execution: an iterator of RecordBatch chunks under
        the row budget (``BatchTask`` driver pipelining analog) — the
        composing form behind ``count``/``write_file``."""
        from flink_tpu.dataset.optimizer import stream_plan
        return stream_plan(self.op)

    def explain(self) -> str:
        from flink_tpu.dataset.optimizer import explain_plan
        return explain_plan(self.op)

    def write_file(self, path: str, format: str = "csv") -> int:
        # streaming sink: chunks flow straight to the writer — a plan
        # larger than memory writes out under the row budget
        from flink_tpu.formats import writer_for
        return writer_for(format)(self.stream_batches(), path)

    def output(self) -> None:
        for row in self.collect():
            print(row)


class GroupedDataSet:
    def __init__(self, ds: DataSet, key_columns: List[str]):
        self.ds = ds
        self.key_columns = key_columns

    def _agg(self, how: str, column: Optional[str]) -> DataSet:
        return DataSet(self.ds.env, BatchOp(
            "group_agg", {"keys": self.key_columns, "column": column,
                          "how": how}, [self.ds.op]))

    def sum(self, column: str) -> DataSet:
        return self._agg("sum", column)

    def min(self, column: str) -> DataSet:
        return self._agg("min", column)

    def max(self, column: str) -> DataSet:
        return self._agg("max", column)

    def count(self) -> DataSet:
        return self._agg("count", None)

    def reduce_group(self, fn: Callable[[Tuple, List[Dict]], Optional[Dict]]
                     ) -> DataSet:
        """``GroupReduceFunction`` analog: fn(key_tuple, rows) -> row."""
        return DataSet(self.ds.env, BatchOp(
            "group_reduce", {"keys": self.key_columns, "fn": fn},
            [self.ds.op]))

    def sort_group(self, column: str, ascending: bool = True) -> "GroupedDataSet":
        g = GroupedDataSet(self.ds._then("sort", column=column,
                                         ascending=ascending),
                           self.key_columns)
        return g

    def first_n(self, n: int) -> DataSet:
        return DataSet(self.ds.env, BatchOp(
            "group_first_n", {"keys": self.key_columns, "n": n},
            [self.ds.op]))


class JoinOperatorBuilder:
    def __init__(self, left: DataSet, right: DataSet, how: str):
        self.left = left
        self.right = right
        self.how = how
        self._where: Optional[List[str]] = None
        self._equal_to: Optional[List[str]] = None
        self._hint: Optional[str] = None

    def where(self, *columns: str) -> "JoinOperatorBuilder":
        self._where = list(columns)
        return self

    def equal_to(self, *columns: str) -> "JoinOperatorBuilder":
        self._equal_to = list(columns)
        return self

    def with_hint(self, hint: str) -> "JoinOperatorBuilder":
        """'broadcast_hash_left'/'broadcast_hash_right'/'sort_merge' — the
        JoinHint analog; otherwise the optimizer chooses by size."""
        self._hint = hint
        return self

    def apply(self, fn: Optional[Callable] = None) -> DataSet:
        if not self._where or not self._equal_to:
            raise ValueError("join needs .where(...).equal_to(...)")
        return DataSet(self.left.env, BatchOp(
            "join", {"how": self.how, "left_keys": self._where,
                     "right_keys": self._equal_to, "fn": fn,
                     "hint": self._hint},
            [self.left.op, self.right.op]))

    # joins are commonly finished without a custom function
    def project(self) -> DataSet:
        return self.apply(None)
