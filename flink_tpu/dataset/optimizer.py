"""Batch plan optimizer + vectorized drivers.

Analog of ``flink-optimizer`` (``Optimizer.java:67`` ``compile:402`` — cost
model choosing ship/local strategies) + the ``runtime/operators/`` drivers
(sort, hash join, cogroup, cross).  Redesigned for columnar arrays:

- **Cost model**: row-count estimates propagate bottom-up; equi-joins pick
  ``broadcast_hash_{left,right}`` when one side is far smaller (the hybrid
  hash join build-side choice) and ``sort_merge`` otherwise — physical
  execution is the same vectorized kernel family either way, but the chosen
  strategy is recorded and shown by ``explain()`` exactly like the
  reference's plan dump.
- **Drivers**: argsort-based sort, ``np.unique``-segment grouping (the
  normalized-key-sort analog), span-intersection equi-join
  (``flink_tpu/operators/joins._join_pairs``), BSP bulk/delta iterations
  with superstep convergence.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import RecordBatch
from flink_tpu.dataset.api import BatchOp, DataSet
from flink_tpu.operators.joins import _join_pairs

#: one side this many times smaller than the other -> broadcast it
_BROADCAST_RATIO = 8


# ---------------------------------------------------------------------------
# composite keys: multiple key columns -> one joinable 1-D array
# ---------------------------------------------------------------------------

def _composite_key(batch: RecordBatch, columns: List[str]) -> np.ndarray:
    """Injective per-row key for grouping/joining on multiple columns.

    The encoding must be CANONICAL — a pure function of the row's values,
    never of the batch's value range — because callers compare keys ACROSS
    batches (streamed chunks, the two join sides, distinct's seen set).  A
    min/max radix packing would map the same logical key differently per
    batch.  Integer columns pack as big-endian bytes viewed as fixed-width
    void scalars (memcmp-comparable, exact, sortable — order is equality-
    only, which is all callers group/intersect on); anything else falls
    back to joined strings."""
    if len(columns) == 1:
        return np.asarray(batch.column(columns[0]))
    parts = [np.asarray(batch.column(c)) for c in columns]
    if all(np.issubdtype(p.dtype, np.integer) for p in parts):
        fields = np.dtype([(f"f{i}", ">i8") for i in range(len(parts))])
        arr = np.empty(len(parts[0]), fields)
        for i, p in enumerate(parts):
            arr[f"f{i}"] = p.astype(np.int64)
        return arr.view(f"V{fields.itemsize}").reshape(len(parts[0]))
    return np.asarray(["\x00".join(str(x) for x in row)
                       for row in zip(*[p.tolist() for p in parts])], object)


# ---------------------------------------------------------------------------
# optimizer: size estimates + join strategy selection
# ---------------------------------------------------------------------------

def _estimate(op: BatchOp) -> int:
    if op.est_rows is not None:
        return op.est_rows
    ins = [_estimate(i) for i in op.inputs]
    if op.kind == "source":
        n = len(op.args["batch"])
    elif op.kind == "sequence":
        n = max(0, op.args["end"] - op.args["start"] + 1)
    elif op.kind == "read":
        n = 10_000  # unknown until read; mid-range guess
    elif op.kind in ("map", "sort", "project"):
        n = ins[0]
    elif op.kind in ("filter", "distinct"):
        n = max(1, ins[0] // 2)
    elif op.kind == "flat_map":
        n = ins[0] * 2
    elif op.kind in ("group_agg", "group_reduce", "group_first_n"):
        n = max(1, ins[0] // 4)
    elif op.kind in ("global_agg", "global_reduce"):
        n = 1
    elif op.kind == "join":
        n = max(ins) if ins else 1
    elif op.kind == "cross":
        n = ins[0] * ins[1]
    elif op.kind == "union":
        n = sum(ins)
    elif op.kind == "first_n":
        n = min(ins[0], op.args["n"])
    else:
        n = ins[0] if ins else 1
    op.est_rows = n
    if op.kind == "join" and op.strategy is None:
        hint = op.args.get("hint")
        if hint:
            op.strategy = hint
        else:
            l, r = ins
            if r * _BROADCAST_RATIO <= l:
                op.strategy = "broadcast_hash_right"  # build small right side
            elif l * _BROADCAST_RATIO <= r:
                op.strategy = "broadcast_hash_left"
            else:
                op.strategy = "sort_merge"
    return n


def explain_plan(op: BatchOp, indent: int = 0) -> str:
    _estimate(op)
    pad = "  " * indent
    extra = f" [{op.strategy}]" if op.strategy else ""
    line = f"{pad}{op.kind}{extra} (est_rows={op.est_rows})"
    return "\n".join([line] + [explain_plan(i, indent + 1) for i in op.inputs])


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def execute_plan(op: BatchOp) -> RecordBatch:
    _estimate(op)
    return _exec(op, {})


def _exec(op: BatchOp, memo: Dict[int, RecordBatch]) -> RecordBatch:
    if id(op) in memo:
        return memo[id(op)]
    ins = [_exec(i, memo) for i in op.inputs]
    out = _DRIVERS[op.kind](op, ins)
    memo[id(op)] = out
    return out


def _drv_source(op, ins):
    return op.args["batch"]


def _drv_sequence(op, ins):
    return RecordBatch({"value": np.arange(op.args["start"],
                                           op.args["end"] + 1,
                                           dtype=np.int64)})


def _drv_read(op, ins):
    from flink_tpu.formats import reader_for
    batches = list(reader_for(op.args["format"])(op.args["path"],
                                                 **op.args["kw"]))
    return RecordBatch.concat(batches) if batches else RecordBatch({})


def _drv_map(op, ins):
    b = ins[0]
    cols = op.args["fn"](dict(b.columns))
    return RecordBatch({k: np.asarray(v) for k, v in cols.items()},
                       timestamps=b.timestamps)


def _drv_filter(op, ins):
    b = ins[0]
    if len(b) == 0:
        return b
    mask = np.asarray(op.args["fn"](dict(b.columns)), bool)
    return b.select(mask)


def _drv_flat_map(op, ins):
    b = ins[0]
    cols = op.args["fn"](dict(b.columns))
    if cols is None:
        return RecordBatch({})
    return RecordBatch({k: np.asarray(v) for k, v in cols.items()})


def _drv_project(op, ins):
    b = ins[0]
    return RecordBatch({c: b.column(c) for c in op.args["columns"]},
                       timestamps=b.timestamps)


def _drv_distinct(op, ins):
    b = ins[0]
    if len(b) == 0:
        return b
    columns = op.args["columns"] or list(b.columns)
    key = _composite_key(b, columns)
    _, idx = np.unique(key, return_index=True)
    return b.take(np.sort(idx))


def _drv_sort(op, ins):
    b = ins[0]
    if len(b) == 0:
        return b
    from flink_tpu.dataset.external import ExternalSorter, memory_budget_rows

    budget = memory_budget_rows()
    if len(b) > budget:
        # out-of-core: spill sorted runs + k-way gallop merge
        # (ExternalSorter analog).  Bounds the SORT's scratch (per-run
        # argsort/take) to the budget; the plan's own materialization of
        # input/output batches is the separate in-memory-plan limitation.
        s = ExternalSorter([op.args["column"]],
                           ascending=op.args["ascending"],
                           budget_rows=budget)
        for lo in range(0, len(b), budget):
            s.add(b.take(np.arange(lo, min(lo + budget, len(b)))))
        return s.sorted_batch()
    order = np.argsort(np.asarray(b.column(op.args["column"])), kind="stable")
    if not op.args["ascending"]:
        order = order[::-1]
    return b.take(order)


def _drv_first_n(op, ins):
    b = ins[0]
    return b.take(np.arange(min(op.args["n"], len(b))))


def _drv_union(op, ins):
    return RecordBatch.concat([b for b in ins if len(b)])


def _drv_global_agg(op, ins):
    b = ins[0]
    col = np.asarray(b.column(op.args["column"]))
    how = op.args["how"]
    val = {"sum": col.sum, "min": col.min, "max": col.max}[how]()
    return RecordBatch({op.args["column"]: np.asarray([val])})


def _drv_global_reduce(op, ins):
    rows = ins[0].to_rows()
    if not rows:
        return RecordBatch({})
    acc = rows[0]
    for r in rows[1:]:
        acc = op.args["fn"](acc, r)
    return RecordBatch.from_rows([acc])


def _group_spans(key: np.ndarray):
    order = np.argsort(key, kind="stable")
    ks = key[order]
    bounds = np.nonzero(np.concatenate([[True], ks[1:] != ks[:-1]]))[0]
    spans = [(int(b), int(bounds[j + 1]) if j + 1 < len(bounds) else len(ks))
             for j, b in enumerate(bounds)]
    return order, ks, spans


def _drv_group_agg(op, ins):
    b = ins[0]
    keys_cols = op.args["keys"]
    how = op.args["how"]
    if len(b) == 0:
        return b
    key = _composite_key(b, keys_cols)
    uniq, inv = np.unique(key, return_inverse=True)
    n_groups = len(uniq)
    out_cols: Dict[str, np.ndarray] = {}
    # representative key column values (first occurrence per group)
    first_idx = np.zeros(n_groups, np.int64)
    first_idx[inv[::-1]] = np.arange(len(b))[::-1]
    for kc in keys_cols:
        out_cols[kc] = np.asarray(b.column(kc))[first_idx]
    if how == "count":
        out_cols["count"] = np.bincount(inv, minlength=n_groups).astype(np.int64)
    else:
        col = np.asarray(b.column(op.args["column"]))
        if how == "sum":
            out_cols[op.args["column"]] = np.bincount(
                inv, weights=col.astype(np.float64), minlength=n_groups
            ).astype(col.dtype if np.issubdtype(col.dtype, np.floating)
                     else np.float64)
        else:
            # min/max: sorted-segment reduce
            order, _ks, spans = _group_spans(key)
            vals = col[order]
            red = np.minimum.reduceat if how == "min" else np.maximum.reduceat
            starts = [s for s, _e in spans]
            seg = red(vals, starts)
            # spans follow sorted-unique order == uniq order
            out_cols[op.args["column"]] = seg
    return RecordBatch(out_cols)


def _drv_group_reduce(op, ins):
    b = ins[0]
    if len(b) == 0:
        return b
    key = _composite_key(b, op.args["keys"])
    order, _ks, spans = _group_spans(key)
    rows = b.take(order).to_rows()
    out_rows = []
    for s, e in spans:
        key_vals = tuple(rows[s][kc] for kc in op.args["keys"])
        res = op.args["fn"](key_vals if len(key_vals) > 1 else key_vals[0],
                            rows[s:e])
        if res is not None:
            out_rows.append(res)
    return RecordBatch.from_rows(out_rows)


def _drv_group_first_n(op, ins):
    b = ins[0]
    if len(b) == 0:
        return b
    key = _composite_key(b, op.args["keys"])
    order, _ks, spans = _group_spans(key)
    keep = np.concatenate([order[s:min(e, s + op.args["n"])]
                           for s, e in spans]) if spans else np.zeros(0, np.int64)
    return b.take(np.sort(keep))


def _drv_join(op, ins):
    from flink_tpu.operators.joins import _merge_columns

    l, r = ins
    how = op.args["how"]
    lk = _composite_key(l, op.args["left_keys"]) if len(l) else np.zeros(0, np.int64)
    rk = _composite_key(r, op.args["right_keys"]) if len(r) else np.zeros(0, np.int64)
    if how == "cogroup":
        return _cogroup(op, l, r, lk, rk)
    if how == "inner":
        from flink_tpu.dataset.external import (GraceHashJoin,
                                                memory_budget_rows)

        if len(l) + len(r) > memory_budget_rows():
            # out-of-core inner join: hash-partition both sides to bucket
            # files, join bucket pairs in memory (grace scheme —
            # MutableHashTable spilling hybrid analog)
            gj = GraceHashJoin("__jk__", "__jk__")
            gj.add(0, RecordBatch({**{k: np.asarray(v)
                                      for k, v in l.columns.items()},
                                   "__jk__": lk}))
            gj.add(1, RecordBatch({**{k: np.asarray(v)
                                      for k, v in r.columns.items()},
                                   "__jk__": rk}))
            parts = [b for b in _grace_join_outputs(op, gj) if len(b)]
            if not parts:
                return RecordBatch({})
            return RecordBatch.concat(parts) if len(parts) > 1 else parts[0]
    li, ri = _join_pairs(lk, rk) if len(l) and len(r) else (
        np.zeros(0, np.int64), np.zeros(0, np.int64))
    parts = []
    if li.size:
        cols = _merge_columns(l, r, li, ri)
        parts.append(RecordBatch(cols))
    if how in ("left", "full") and len(l):
        unmatched = np.setdiff1d(np.arange(len(l)), li)
        if unmatched.size:
            cols = {k: np.asarray(v)[unmatched] for k, v in l.columns.items()}
            for k in r.columns:
                name = f"r_{k}" if k in cols else k
                cols[name] = np.full(unmatched.size, None, object)
            parts.append(RecordBatch(cols))
    if how in ("right", "full") and len(r):
        unmatched = np.setdiff1d(np.arange(len(r)), ri)
        if unmatched.size:
            cols = {k: np.full(unmatched.size, None, object)
                    for k in l.columns}
            for k, v in r.columns.items():
                name = f"r_{k}" if k in cols else k
                cols[name] = np.asarray(v)[unmatched]
            parts.append(RecordBatch(cols))
    if not parts:
        return RecordBatch({})
    out = RecordBatch.concat(parts) if len(parts) > 1 else parts[0]
    fn = op.args.get("fn")
    if fn is not None:
        cols = fn(dict(out.columns))
        out = RecordBatch({k: np.asarray(v) for k, v in cols.items()})
    return out


def _cogroup(op, l, r, lk, rk):
    fn = op.args.get("fn")
    if fn is None:
        raise ValueError("co_group needs an apply function")
    out_rows = []
    for key in np.union1d(np.unique(lk) if lk.size else np.zeros(0, lk.dtype),
                          np.unique(rk) if rk.size else np.zeros(0, rk.dtype)).tolist():
        lrows = l.select(lk == key).to_rows() if lk.size else []
        rrows = r.select(rk == key).to_rows() if rk.size else []
        res = fn(key, lrows, rrows)
        if res is not None:
            out_rows.append(res)
    return RecordBatch.from_rows(out_rows)


def _drv_partition_hash(op, ins):
    # physical routing only — row content is unchanged; the partitioning
    # takes effect in map_partition (streamed via the shuffle service)
    return ins[0]


def _partition_count(child: BatchOp) -> int:
    """ONE derivation for both executors (a plan must partition the same
    way whether an op runs streamed or materialized — a diamond reference
    flips the mode mid-plan)."""
    if child.args["n"]:
        return int(child.args["n"])
    from flink_tpu.dataset.external import memory_budget_rows
    budget = memory_budget_rows()
    return max(2, min(64, (child.est_rows or budget) // max(budget, 1) + 1))


def _drv_map_partition(op, ins):
    child = op.inputs[0]
    batch = ins[0]
    fn = op.args["fn"]
    if child.kind != "partition_hash" or len(batch) == 0:
        return fn(batch)
    from flink_tpu.runtime.shuffle import hash_subpartition
    n = _partition_count(child)
    sub = hash_subpartition(
        _composite_key(batch, child.args["columns"]), n)
    # batch is non-empty here, so at least one subpartition matches
    return RecordBatch.concat([fn(batch.select(sub == s))
                               for s in range(n)
                               if bool((sub == s).any())])


def _drv_cross(op, ins):
    l, r = ins
    nl, nr = len(l), len(r)
    if nl == 0 or nr == 0:
        return RecordBatch({})
    li = np.repeat(np.arange(nl), nr)
    ri = np.tile(np.arange(nr), nl)
    cols = {k: np.asarray(v)[li] for k, v in l.columns.items()}
    for k, v in r.columns.items():
        name = f"r_{k}" if k in cols else k
        cols[name] = np.asarray(v)[ri]
    return RecordBatch(cols)


def _drv_bulk_iterate(op, ins):
    from flink_tpu.dataset.api import DataSet, ExecutionEnvironment

    env = ExecutionEnvironment()
    current = ins[0]
    step = op.args["step"]
    term = op.args["termination"]
    for _i in range(op.args["max_iterations"]):
        ds = DataSet(env, BatchOp("source", {"batch": current}))
        nxt = step(ds).collect_batch()
        if term is not None and term(current, nxt):
            current = nxt
            break
        current = nxt
    return current


def _drv_delta_iterate(op, ins):
    from flink_tpu.dataset.api import DataSet, ExecutionEnvironment

    env = ExecutionEnvironment()
    solution, workset = ins
    key_col = op.args["key_column"]
    step = op.args["step"]
    for _i in range(op.args["max_iterations"]):
        if len(workset) == 0:
            break
        s_ds = DataSet(env, BatchOp("source", {"batch": solution}))
        w_ds = DataSet(env, BatchOp("source", {"batch": workset}))
        delta_ds, next_w_ds = step(s_ds, w_ds)
        delta = delta_ds.collect_batch()
        workset = next_w_ds.collect_batch()
        if len(delta):
            # merge delta into solution set by key (UPSERT semantics)
            skeys = np.asarray(solution.column(key_col))
            dkeys = np.asarray(delta.column(key_col))
            keep = ~np.isin(skeys, dkeys)
            solution = RecordBatch.concat([solution.select(keep), delta])
    return solution


_DRIVERS = {
    "source": _drv_source,
    "sequence": _drv_sequence,
    "read": _drv_read,
    "map": _drv_map,
    "filter": _drv_filter,
    "flat_map": _drv_flat_map,
    "project": _drv_project,
    "distinct": _drv_distinct,
    "sort": _drv_sort,
    "first_n": _drv_first_n,
    "union": _drv_union,
    "global_agg": _drv_global_agg,
    "global_reduce": _drv_global_reduce,
    "group_agg": _drv_group_agg,
    "group_reduce": _drv_group_reduce,
    "group_first_n": _drv_group_first_n,
    "join": _drv_join,
    "cross": _drv_cross,
    "partition_hash": _drv_partition_hash,
    "map_partition": _drv_map_partition,
    "bulk_iterate": _drv_bulk_iterate,
    "delta_iterate": _drv_delta_iterate,
}


# ---------------------------------------------------------------------------
# streamed (pipelined) execution — VERDICT r2 #5
# ---------------------------------------------------------------------------
# The reference pipelines its batch drivers under a memory manager
# (``BatchTask.java`` + ``operators/sort/``): records PULL through chained
# operators, and only genuine pipeline dams (sort, hash build, full-input
# aggregates) materialize.  The streamed executor below does the same with
# RecordBatch chunks: streamable operators transform chunk-by-chunk under
# the row budget; dams either stream THROUGH the out-of-core kernels
# (external sort via spilled runs, grouped sum/min/max/count via
# output-bounded partial combine, distinct via an output-bounded seen set)
# or materialize exactly at the dam (joins, UDF reduces, iterations) — so
# a plan's peak memory is bounded by its widest dam, not by the sum of
# every operator's input+output.

#: operator kinds whose stream driver transforms one chunk at a time
_CHUNKWISE = {"map", "filter", "flat_map", "project"}


def _count_refs(op: BatchOp, counts: Dict[int, int]) -> None:
    counts[id(op)] = counts.get(id(op), 0) + 1
    if counts[id(op)] == 1:
        for i in op.inputs:
            _count_refs(i, counts)


def stream_plan(op: BatchOp):
    """Execute as a PULL stream of RecordBatches (chunks sized by the row
    budget).  ``collect``-style callers concatenate; streaming sinks
    (``write_file``, ``count``) never hold the full result.  A plan whose
    result is empty still yields ONE empty batch carrying the schema, so
    streamed and materialized execution agree on structure."""
    from flink_tpu.dataset.external import memory_budget_rows

    _estimate(op)
    refs: Dict[int, int] = {}
    _count_refs(op, refs)
    yield from _exec_stream(op, {}, refs, memory_budget_rows())


def _chunks(b: RecordBatch, budget: int):
    if len(b) <= budget:
        yield b                    # empty batches carry the schema
        return
    for lo in range(0, len(b), budget):
        yield b.take(np.arange(lo, min(lo + budget, len(b))))


def _exec_stream(op: BatchOp, memo: Dict[int, RecordBatch],
                 refs: Dict[int, int], budget: int):
    """Schema-preserving wrapper over the per-kind stream drivers: empty
    chunks are swallowed mid-stream but the LAST one is re-emitted when
    nothing non-empty flowed — downstream dams (joins, aggregates) need
    the column schema even for zero rows (the materialized executor
    always has it)."""
    yielded = False
    empty = None
    for b in _exec_stream_raw(op, memo, refs, budget):
        if len(b):
            yielded = True
            yield b
        else:
            empty = b
    if not yielded and empty is not None:
        yield empty


def _exec_stream_raw(op: BatchOp, memo: Dict[int, RecordBatch],
                     refs: Dict[int, int], budget: int):
    # shared sub-plans (diamonds) materialize once — streaming them per
    # parent would re-run the subtree
    if refs.get(id(op), 1) > 1 or id(op) in memo:
        if id(op) not in memo:
            memo[id(op)] = _materialize(op, memo, refs, budget)
        yield from _chunks(memo[id(op)], budget)
        return
    kind = op.kind
    if kind == "source":
        yield from _chunks(op.args["batch"], budget)
    elif kind == "sequence":
        start, end = op.args["start"], op.args["end"]
        for lo in range(start, end + 1, budget):
            yield RecordBatch({"value": np.arange(
                lo, min(lo + budget, end + 1), dtype=np.int64)})
    elif kind == "read":
        from flink_tpu.formats import reader_for
        for b in reader_for(op.args["format"])(op.args["path"],
                                               **op.args["kw"]):
            yield from _chunks(b, budget)
    elif kind in _CHUNKWISE:
        for chunk in _exec_stream(op.inputs[0], memo, refs, budget):
            yield _DRIVERS[kind](op, [chunk])
    elif kind == "union":
        for i in op.inputs:
            yield from _exec_stream(i, memo, refs, budget)
    elif kind == "first_n":
        left = op.args["n"]
        for chunk in _exec_stream(op.inputs[0], memo, refs, budget):
            if left <= 0:
                break
            take = min(left, len(chunk))
            yield chunk.take(np.arange(take))
            left -= take
    elif kind == "sort":
        from flink_tpu.dataset.external import ExternalSorter
        s = ExternalSorter([op.args["column"]],
                           ascending=op.args["ascending"],
                           budget_rows=budget,
                           emit_batch_rows=min(budget, 1 << 16))
        empty = None
        for chunk in _exec_stream(op.inputs[0], memo, refs, budget):
            if len(chunk):
                s.add(chunk)
            else:
                empty = chunk
        produced = False
        for out in s.merged():
            produced = True
            yield out
        if not produced and empty is not None:
            yield empty
    elif kind == "distinct":
        # output-bounded: the seen set holds one entry per DISTINCT key
        seen: set = set()
        columns = op.args["columns"]
        for chunk in _exec_stream(op.inputs[0], memo, refs, budget):
            key = _composite_key(chunk, columns or list(chunk.columns))
            fresh = np.fromiter((k not in seen for k in key.tolist()),
                                bool, count=len(key))
            # in-chunk first occurrence
            _, first_idx = np.unique(key, return_index=True)
            in_first = np.zeros(len(key), bool)
            in_first[first_idx] = True
            keep = fresh & in_first
            seen.update(key[keep].tolist())
            yield chunk.select(keep)
    elif kind == "global_agg":
        partials: List[RecordBatch] = []
        empty = None
        for chunk in _exec_stream(op.inputs[0], memo, refs, budget):
            if len(chunk) == 0:
                empty = chunk
                continue
            partials.append(_DRIVERS[kind](op, [chunk]))
            if len(partials) > 1024:   # fold: partials are 1-row batches
                partials = [_DRIVERS[kind](op,
                                           [RecordBatch.concat(partials)])]
        if partials:
            yield _DRIVERS[kind](op, [RecordBatch.concat(partials)])
        elif empty is not None:
            yield _DRIVERS[kind](op, [empty])
    elif kind == "group_agg" and op.args["how"] in ("sum", "min", "max",
                                                    "count"):
        # partial-aggregate per chunk, combine partials (output-bounded:
        # the partial set is at most one row per distinct group)
        partials: List[RecordBatch] = []
        empty = None
        for chunk in _exec_stream(op.inputs[0], memo, refs, budget):
            if len(chunk) == 0:
                empty = chunk
                continue
            partials.append(_DRIVERS[kind](op, [chunk]))
            if sum(len(p) for p in partials) > budget:
                partials = [_combine_group_partials(op, partials)]
        if partials:
            yield _combine_group_partials(op, partials)
        elif empty is not None:
            yield _DRIVERS[kind](op, [empty])
    elif kind == "join" and op.args["how"] == "inner":
        # spilling hybrid hash join: chunks stream into bucket files, each
        # bucket pair joins in memory (MutableHashTable.java:1 analog) —
        # the join dam no longer materializes its inputs
        yield from _stream_inner_join(op, memo, refs, budget)
    elif kind == "group_reduce":
        # external sorted-group UDF reduce: sort by key out-of-core, walk
        # group spans in merge order — one GROUP resident at a time
        # (GroupReduceCombineDriver over UnilateralSortMerger analog)
        yield from _stream_group_reduce(op, memo, refs, budget)
    elif kind == "partition_hash":
        # standalone (no map_partition consumer): physical no-op
        yield from _exec_stream(op.inputs[0], memo, refs, budget)
    elif kind == "map_partition":
        yield from _stream_map_partition(op, memo, refs, budget)
    else:
        # genuine dam without a streaming kernel (outer joins, iterations):
        # materialize the inputs, run the vectorized driver
        yield from _chunks(_materialize(op, memo, refs, budget), budget)


def _stream_map_partition(op: BatchOp, memo, refs, budget: int):
    """``mapPartition`` over a hash exchange THROUGH the shuffle SPI
    (``runtime/shuffle.py``): input chunks route to subpartitions via the
    writer (the sort-merge service spills clustered regions under its own
    byte budget — the all-to-all never materializes in memory), the
    partition seals, and each subpartition streams back as ONE
    RecordBatch through the user function.  Peak memory = one partition
    + the service's clustering buffer, matching the reference's
    sort-merge blocking shuffle role (SortMergeResultPartition.java:65).
    Without a partition_hash input the whole stream is a single
    partition."""
    import os

    child = op.inputs[0]
    fn = op.args["fn"]
    if child.kind != "partition_hash":
        chunks = list(_exec_stream(child, memo, refs, budget))
        yield from _chunks(fn(RecordBatch.concat(chunks) if len(chunks) > 1
                              else chunks[0]), budget)
        return
    from flink_tpu.runtime.shuffle import (hash_subpartition,
                                           shuffle_service_for)
    n = _partition_count(child)
    svc = shuffle_service_for(child.args.get("config"),
                              name=child.args.get("service"))
    pid = f"map-partition-{id(op)}-{os.getpid()}-{os.urandom(4).hex()}"
    writer = svc.create_partition(pid, n)
    empty = None
    try:
        for chunk in _exec_stream(child.inputs[0], memo, refs, budget):
            if len(chunk) == 0:
                empty = chunk
                continue
            sub = hash_subpartition(
                _composite_key(chunk, child.args["columns"]), n)
            for s in np.unique(sub).tolist():
                writer.emit(int(s), chunk.select(sub == s))
        writer.finish()
        produced = False
        for s in range(n):
            parts = list(svc.open_reader(pid, s))
            if not parts:
                continue
            produced = True
            part = (RecordBatch.concat(parts) if len(parts) > 1
                    else parts[0])
            yield from _chunks(fn(part), budget)
        if not produced and empty is not None:
            yield fn(empty)        # schema contract: fn sees one empty
    except BaseException:
        writer.abort()
        raise
    finally:
        svc.release_partition(pid)


def _with_join_key(batch: RecordBatch, keys: List[str]) -> RecordBatch:
    """Attach the canonical composite join key as the ``__jk__`` column."""
    return RecordBatch(
        {**{k: np.asarray(v) for k, v in batch.columns.items()},
         "__jk__": _composite_key(batch, keys)})


def _grace_join_outputs(op: BatchOp, gj):
    """Joined output batches from a fed GraceHashJoin — the single
    assembly shared by the materialized driver's out-of-core branch and
    the streamed executor (key-column stripping + optional join fn)."""
    from flink_tpu.operators.joins import _merge_columns

    fn = op.args.get("fn")
    for lb, li, rb, ri in gj.join_pairs():
        cols = _merge_columns(lb, rb, li, ri)
        cols = {k: v for k, v in cols.items()
                if k not in ("__jk__", "r___jk__")}
        out = RecordBatch(cols)
        if fn is not None:
            out = RecordBatch({k: np.asarray(v)
                               for k, v in fn(dict(out.columns)).items()})
        yield out


def _stream_inner_join(op: BatchOp, memo, refs, budget: int):
    from flink_tpu.dataset.external import GraceHashJoin

    gj = GraceHashJoin("__jk__", "__jk__", budget_rows=budget)
    schema: List[Optional[RecordBatch]] = [None, None]
    for side, inp, keys in ((0, op.inputs[0], op.args["left_keys"]),
                            (1, op.inputs[1], op.args["right_keys"])):
        for chunk in _exec_stream(inp, memo, refs, budget):
            # keep only a zero-row slice for the empty-result schema —
            # retaining the full chunk would pin a budget-sized batch
            schema[side] = chunk.select(np.zeros(len(chunk), bool))
            if len(chunk):
                gj.add(side, _with_join_key(chunk, keys))
    produced = False
    for out in _grace_join_outputs(op, gj):
        if len(out):
            produced = True
            yield from _chunks(out, budget)
    if not produced:
        # schema-carrying empty result: run the vectorized driver on the
        # zero-row schema batches (matches the materialized executor)
        l0 = schema[0] if schema[0] is not None else RecordBatch({})
        r0 = schema[1] if schema[1] is not None else RecordBatch({})
        yield _DRIVERS["join"](op, [l0, r0])


def _stream_group_reduce(op: BatchOp, memo, refs, budget: int):
    from flink_tpu.dataset.external import ExternalSorter

    keys = op.args["keys"]
    fn = op.args["fn"]
    sorter = ExternalSorter(keys, budget_rows=budget,
                            emit_batch_rows=min(budget, 1 << 16))
    empty = None
    for chunk in _exec_stream(op.inputs[0], memo, refs, budget):
        if len(chunk):
            sorter.add(chunk)
        else:
            empty = chunk
    out_rows: List[dict] = []
    cur_key = _NO_GROUP = object()
    cur_rows: List[dict] = []

    def flush_group():
        if cur_key is _NO_GROUP:
            return
        res = fn(cur_key if len(keys) > 1 else cur_key[0], cur_rows)
        if res is not None:
            out_rows.append(res)

    any_rows = False
    for batch in sorter.merged():
        any_rows = any_rows or len(batch) > 0
        for row in batch.to_rows():
            kv = tuple(row[k] for k in keys)
            if kv != cur_key:
                flush_group()
                cur_key = kv
                cur_rows = []
            cur_rows.append(row)
        while len(out_rows) >= (1 << 14):
            emit, out_rows = out_rows[: 1 << 14], out_rows[1 << 14:]
            yield RecordBatch.from_rows(emit)
    flush_group()
    if out_rows:
        yield RecordBatch.from_rows(out_rows)
    elif not any_rows and empty is not None:
        yield empty                       # schema-carrying empty input


def _materialize(op: BatchOp, memo, refs, budget) -> RecordBatch:
    ins = []
    for i in op.inputs:
        parts = list(_exec_stream(i, memo, refs, budget))
        nonempty = [b for b in parts if len(b)]
        if nonempty:
            ins.append(RecordBatch.concat(nonempty))
        else:
            # the wrapper guarantees >= 1 (schema-carrying) batch when the
            # sub-plan has any schema at all
            ins.append(parts[-1] if parts else RecordBatch({}))
    return _DRIVERS[op.kind](op, ins)


def _combine_group_partials(op, partials: List[RecordBatch]) -> RecordBatch:
    merged = RecordBatch.concat([p for p in partials if len(p)]) \
        if any(len(p) for p in partials) else RecordBatch({})
    if len(merged) == 0:
        return merged
    how = op.args["how"]
    if how == "count":
        # counts of counts SUM; reuse the sum kernel over the count column
        combine = BatchOp("group_agg", {"keys": op.args["keys"],
                                        "column": "count", "how": "sum"})
        out = _DRIVERS["group_agg"](combine, [merged])
        out_cols = dict(out.columns)
        out_cols["count"] = np.asarray(out_cols["count"], np.int64)
        return RecordBatch(out_cols)
    return _DRIVERS["group_agg"](op, [merged])
