from flink_tpu.dataset.api import DataSet, ExecutionEnvironment

__all__ = ["DataSet", "ExecutionEnvironment"]
