"""ORC, pure Python from the spec — no pyarrow needed.

Analog of the reference's ``flink-formats/flink-orc``
(``OrcColumnarRowSplitReader``/``OrcBulkWriterFactory``); this
environment has no pyarrow, so the format is implemented from first
principles the same way ``avro.py`` and ``parquet.py`` were:

- **File layout**: ``ORC`` magic, stripes (data streams + a protobuf
  stripe footer), file footer (types, stripe directory, row count),
  postscript (footer length, compression kind), one trailing byte with
  the postscript length.
- **Protobuf**: a minimal encoder/decoder (varints, length-delimited
  fields) covers the orc_proto messages used: PostScript, Footer,
  StripeInformation, Type, StripeFooter, Stream, ColumnEncoding.
- **Types**: BOOLEAN (bit-packed byte-RLE), INT/LONG (int RLE),
  FLOAT/DOUBLE (IEEE little-endian), STRING (DATA + LENGTH streams).
  Columns are flat and non-null on write (the columnar runtime carries
  no nulls); PRESENT streams are honored on read.
- **Integer encodings**: the writer emits DIRECT (RLEv1 — runs with a
  signed delta byte, literal groups of varints, legal per the spec's
  per-column ColumnEncoding); the reader handles DIRECT **and**
  DIRECT_V2 (all four RLEv2 sub-encodings: short-repeat, direct, delta,
  patched-base — validated against the spec's worked byte examples) plus
  DICTIONARY_V2 strings, so files from modern writers read back.
- **Compression**: NONE or ZLIB (raw-deflate chunks behind the 3-byte
  ``length*2+isOriginal`` headers), per the gated-dependency policy.

``read_orc`` yields one RecordBatch per stripe; ``write_orc`` drains
batches into stripes.  Interop caveat (PARITY.md): validated against
spec-derived golden bytes and round-trips, not against a foreign
implementation — none exists in this image.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import RecordBatch

MAGIC = b"ORC"

# orc_proto enums
K_BOOLEAN, K_BYTE, K_SHORT, K_INT, K_LONG = 0, 1, 2, 3, 4
K_FLOAT, K_DOUBLE, K_STRING, K_BINARY = 5, 6, 7, 8
K_TIMESTAMP, K_DECIMAL = 9, 14
K_STRUCT = 12
#: timestamp values are seconds relative to the ORC epoch 2015-01-01
#: 00:00:00 UTC, plus a scaled-nanosecond SECONDARY stream
_ORC_EPOCH_S = 1_420_070_400
COMP_NONE, COMP_ZLIB = 0, 1
STREAM_PRESENT, STREAM_DATA, STREAM_LENGTH = 0, 1, 2
STREAM_DICT_DATA = 3
STREAM_SECONDARY = 5
ENC_DIRECT, ENC_DICTIONARY, ENC_DIRECT_V2, ENC_DICTIONARY_V2 = 0, 1, 2, 3

#: RLEv2 5-bit width codes -> bit widths (FixedBitSizes of the spec)
_V2_WIDTHS = list(range(1, 25)) + [26, 28, 30, 32, 40, 48, 56, 64]


# ---------------------------------------------------------------------------
# protobuf primitives
# ---------------------------------------------------------------------------

def _uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _svarint(n: int) -> bytes:
    """Zigzag-encoded signed varint."""
    return _uvarint((n << 1) ^ (n >> 63) if n < 0 else n << 1)


def _read_uvarint(data, pos: int) -> Tuple[int, int]:
    n = shift = 0
    while True:
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def _unzig(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


class _Msg:
    """Protobuf message writer (wire types 0 varint / 2 bytes only —
    the ORC metadata subset needs nothing else)."""

    def __init__(self):
        self._out = bytearray()

    def varint(self, field: int, v: int) -> "_Msg":
        self._out += _uvarint(field << 3 | 0) + _uvarint(int(v))
        return self

    def bytes_(self, field: int, b: bytes) -> "_Msg":
        self._out += _uvarint(field << 3 | 2) + _uvarint(len(b)) + b
        return self

    def msg(self, field: int, m: "_Msg") -> "_Msg":
        return self.bytes_(field, bytes(m._out))

    def string(self, field: int, s: str) -> "_Msg":
        return self.bytes_(field, s.encode())

    def encode(self) -> bytes:
        return bytes(self._out)


def _pb_decode(data: bytes) -> Dict[int, List[Any]]:
    """Generic decode: field -> list of values (int for varint, bytes for
    length-delimited); repeated fields accumulate in order."""
    out: Dict[int, List[Any]] = {}
    pos = 0
    while pos < len(data):
        tag, pos = _read_uvarint(data, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, pos = _read_uvarint(data, pos)
        elif wt == 2:
            ln, pos = _read_uvarint(data, pos)
            v = data[pos:pos + ln]
            pos += ln
        elif wt == 5:
            v = struct.unpack("<I", data[pos:pos + 4])[0]
            pos += 4
        elif wt == 1:
            v = struct.unpack("<Q", data[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        out.setdefault(field, []).append(v)
    return out


def _one(msg: Dict[int, List[Any]], field: int, default=None):
    return msg[field][0] if field in msg else default


# ---------------------------------------------------------------------------
# compression (chunked: 3-byte little-endian header = length*2 + isOriginal)
# ---------------------------------------------------------------------------

_CHUNK = 256 * 1024


def _compress_stream(data: bytes, kind: int) -> bytes:
    if kind == COMP_NONE or not data:
        return data
    out = bytearray()
    for lo in range(0, len(data), _CHUNK):
        chunk = data[lo:lo + _CHUNK]
        comp = zlib.compressobj(wbits=-15)
        z = comp.compress(chunk) + comp.flush()
        if len(z) < len(chunk):
            hdr = len(z) * 2
            body = z
        else:
            hdr = len(chunk) * 2 + 1
            body = chunk
        out += struct.pack("<I", hdr)[:3] + body
    return bytes(out)


def _decompress_stream(data: bytes, kind: int) -> bytes:
    if kind == COMP_NONE:
        return data
    out = bytearray()
    pos = 0
    while pos < len(data):
        hdr = struct.unpack("<I", data[pos:pos + 3] + b"\0")[0]
        pos += 3
        ln, original = hdr >> 1, hdr & 1
        chunk = data[pos:pos + ln]
        pos += ln
        out += chunk if original else zlib.decompress(chunk, wbits=-15)
    return bytes(out)


# ---------------------------------------------------------------------------
# byte RLE + boolean bit RLE
# ---------------------------------------------------------------------------

def _byte_rle_encode(vals: bytes) -> bytes:
    out = bytearray()
    i, n = 0, len(vals)
    while i < n:
        run = 1
        while i + run < n and run < 130 and vals[i + run] == vals[i]:
            run += 1
        if run >= 3:
            out.append(run - 3)
            out.append(vals[i])
            i += run
            continue
        lit = i
        while i < n and i - lit < 128:
            nxt = 1
            while i + nxt < n and nxt < 3 and vals[i + nxt] == vals[i]:
                nxt += 1
            if nxt >= 3 and i > lit:
                break
            if nxt >= 3:
                break
            i += 1
        if i == lit:                 # run >= 3 starts right here
            continue
        out.append(256 - (i - lit))  # -count as unsigned byte
        out += vals[lit:i]
    return bytes(out)


def _byte_rle_decode(data: bytes, n: int) -> bytes:
    out = bytearray()
    pos = 0
    while len(out) < n:
        ctrl = data[pos]
        pos += 1
        if ctrl < 128:               # run of ctrl+3 copies
            out += bytes([data[pos]]) * (ctrl + 3)
            pos += 1
        else:                        # 256-ctrl literals
            k = 256 - ctrl
            out += data[pos:pos + k]
            pos += k
    return bytes(out[:n])


def _bool_encode(mask: np.ndarray) -> bytes:
    bits = np.packbits(mask.astype(bool))  # MSB-first, the ORC bit order
    return _byte_rle_encode(bits.tobytes())


def _bool_decode(data: bytes, n: int) -> np.ndarray:
    nbytes = (n + 7) // 8
    raw = np.frombuffer(_byte_rle_decode(data, nbytes), np.uint8)
    return np.unpackbits(raw)[:n].astype(bool)


# ---------------------------------------------------------------------------
# integer RLE version 1 (the writer's encoding; DIRECT)
# ---------------------------------------------------------------------------

def _rle1_encode(vals: np.ndarray, signed: bool) -> bytes:
    enc = (_svarint if signed else _uvarint)
    out = bytearray()
    v = vals.tolist()
    i, n = 0, len(v)
    while i < n:
        # run: >=3 values with a constant delta in [-128, 127]
        run = 1
        if i + 1 < n:
            delta = v[i + 1] - v[i]
            if -128 <= delta <= 127:
                run = 2
                while i + run < n and run < 130 \
                        and v[i + run] - v[i + run - 1] == delta:
                    run += 1
        if run >= 3:
            out.append(run - 3)
            out += struct.pack("b", delta)
            out += enc(v[i])
            i += run
            continue
        lit = i
        while i < n and i - lit < 128:
            if i + 2 < n and v[i + 1] - v[i] == v[i + 2] - v[i + 1] \
                    and -128 <= v[i + 1] - v[i] <= 127:
                break                # a run starts here
            i += 1
        if i == lit:
            i += 1                   # lone head of a run boundary
        out.append(256 - (i - lit))
        for x in v[lit:i]:
            out += enc(x)
    return bytes(out)


def _rle1_decode(data: bytes, n: int, signed: bool) -> np.ndarray:
    out = np.empty(n, np.int64)
    m = pos = 0
    while m < n:
        ctrl = data[pos]
        pos += 1
        if ctrl < 128:
            count = ctrl + 3
            delta = struct.unpack("b", data[pos:pos + 1])[0]
            pos += 1
            base, pos = _read_uvarint(data, pos)
            if signed:
                base = _unzig(base)
            out[m:m + count] = base + delta * np.arange(count)
            m += count
        else:
            for _ in range(256 - ctrl):
                x, pos = _read_uvarint(data, pos)
                v = _unzig(x) if signed else x
                if v >= 1 << 63:
                    v -= 1 << 64    # 64-bit two's-complement wrap (the
                    #                 signed nanos in the unsigned stream)
                out[m] = v
                m += 1
    return out


# ---------------------------------------------------------------------------
# integer RLE version 2 (reader; DIRECT_V2 of modern writers)
# ---------------------------------------------------------------------------

def _unpack_bits(data: bytes, pos: int, count: int, width: int
                 ) -> Tuple[np.ndarray, int]:
    """``count`` big-endian ``width``-bit unsigned ints from ``data``:
    vectorized via a [count, width] bit matrix dotted with powers of 2."""
    nbytes = (count * width + 7) // 8
    bits = np.unpackbits(np.frombuffer(data[pos:pos + nbytes], np.uint8),
                         count=count * width).reshape(count, width)
    powers = (np.uint64(1) << np.arange(width - 1, -1, -1,
                                        dtype=np.uint64))
    out = (bits.astype(np.uint64) * powers).sum(axis=1)
    return out.astype(np.int64, copy=False), pos + nbytes


def _rle2_decode(data: bytes, n: int, signed: bool) -> np.ndarray:
    out = np.empty(n, np.int64)
    m = pos = 0
    while m < n:
        hdr = data[pos]
        kind = hdr >> 6
        if kind == 0:                      # SHORT_REPEAT
            width = ((hdr >> 3) & 7) + 1
            count = (hdr & 7) + 3
            val = int.from_bytes(data[pos + 1:pos + 1 + width], "big")
            pos += 1 + width
            if signed:
                val = _unzig(val)
            out[m:m + count] = val
            m += count
        elif kind == 1:                    # DIRECT
            width = _V2_WIDTHS[(hdr >> 1) & 0x1F]
            count = ((hdr & 1) << 8 | data[pos + 1]) + 1
            pos += 2
            vals, pos = _unpack_bits(data, pos, count, width)
            if signed:
                vals = (vals >> 1) ^ -(vals & 1)
            out[m:m + count] = vals
            m += count
        elif kind == 3:                    # DELTA
            wcode = (hdr >> 1) & 0x1F
            width = 0 if wcode == 0 else _V2_WIDTHS[wcode]
            count = ((hdr & 1) << 8 | data[pos + 1]) + 1
            pos += 2
            base, pos = _read_uvarint(data, pos)
            if signed:
                base = _unzig(base)
            db, pos = _read_uvarint(data, pos)
            delta_base = _unzig(db)        # delta base is ALWAYS signed
            seq = [base]
            if count > 1:
                seq.append(base + delta_base)
            if count > 2:
                if width == 0:
                    for _ in range(count - 2):
                        seq.append(seq[-1] + delta_base)
                else:
                    deltas, pos = _unpack_bits(data, pos, count - 2, width)
                    sign = 1 if delta_base >= 0 else -1
                    for d in deltas.tolist():
                        seq.append(seq[-1] + sign * d)
            out[m:m + count] = seq
            m += count
        else:                              # PATCHED_BASE
            width = _V2_WIDTHS[(hdr >> 1) & 0x1F]
            count = ((hdr & 1) << 8 | data[pos + 1]) + 1
            b3, b4 = data[pos + 2], data[pos + 3]
            bw = ((b3 >> 5) & 7) + 1       # base width, bytes
            pw = _V2_WIDTHS[b3 & 0x1F]     # patch width, bits
            pgw = ((b4 >> 5) & 7) + 1      # patch gap width, bits
            pll = b4 & 0x1F                # patch list length
            pos += 4
            base = int.from_bytes(data[pos:pos + bw], "big")
            msb = 1 << (bw * 8 - 1)
            if base & msb:                 # sign-magnitude base
                base = -(base & (msb - 1))
            pos += bw
            vals, pos = _unpack_bits(data, pos, count, width)
            if pll:
                entries, pos = _unpack_bits(data, pos, pll, pgw + pw)
                idx = 0
                for e in entries.tolist():
                    gap, patch = e >> pw, e & ((1 << pw) - 1)
                    idx += gap
                    if patch:
                        vals[idx] |= patch << width
            out[m:m + count] = base + vals
            m += count
    return out


def _int_decode(data: bytes, n: int, signed: bool, encoding: int
                ) -> np.ndarray:
    if encoding in (ENC_DIRECT_V2, ENC_DICTIONARY_V2):
        return _rle2_decode(data, n, signed)
    return _rle1_decode(data, n, signed)


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

def _orc_kind(arr: np.ndarray) -> int:
    dt = arr.dtype
    if dt == np.bool_:
        return K_BOOLEAN
    if np.issubdtype(dt, np.datetime64):
        return K_TIMESTAMP
    if dt == np.int32:
        return K_INT
    if np.issubdtype(dt, np.integer):
        return K_LONG
    if dt == np.float32:
        return K_FLOAT
    if np.issubdtype(dt, np.floating):
        return K_DOUBLE
    if dt == object and len(arr):
        import decimal
        head = next((v for v in arr.tolist() if v is not None), None)
        if isinstance(head, decimal.Decimal):
            return K_DECIMAL
    return K_STRING


def _nanos_encode(nanos: np.ndarray) -> np.ndarray:
    """ORC scaled nanoseconds: trailing decimal zeros strip off, their
    count (minus one) rides the low 3 bits — 1000 serializes as
    ``(1 << 3) | 2``.  Values may be NEGATIVE (pre-1970 sub-second
    remainders under the truncate-toward-zero seconds convention): the
    shifted mantissa keeps its sign and the low bits ride two's
    complement, matching the C++ ORC writer (-0.5s → enc -33)."""
    out = np.empty(len(nanos), np.int64)
    for i, n in enumerate(nanos.tolist()):
        a = -n if n < 0 else n
        z = 0
        if a:
            while a % 10 == 0 and z < 8:
                a //= 10
                z += 1
        if z >= 2:
            m = -a if n < 0 else a
            out[i] = (m << 3) | (z - 1)
        else:
            # 0 or 1 trailing zeros cannot be stripped (the 3-bit field
            # encodes 2..8 removed zeros); store the raw value
            out[i] = int(n) << 3
    return out


def _nanos_decode(enc: np.ndarray) -> np.ndarray:
    zeros = enc & 7
    vals = enc >> 3
    scale = np.where(zeros > 0, 10 ** (zeros + 1), 1)
    return vals * scale


def _decimal_streams(arr: np.ndarray) -> List[Tuple[int, bytes]]:
    """DECIMAL: unbounded zigzag-varint mantissas + a signed RLE scale
    stream (per-value scales are legal; readers rescale to the declared
    type scale)."""
    data = bytearray()
    scales = np.empty(len(arr), np.int64)
    for i, v in enumerate(arr.tolist()):
        t = v.as_tuple()
        scale = max(-t.exponent, 0)
        mantissa = int(v.scaleb(scale))
        scales[i] = scale
        # zigzag over arbitrary-precision ints: -1 flips all bits
        n = ((mantissa << 1) ^ -1) if mantissa < 0 else mantissa << 1
        while True:
            b = n & 0x7F
            n >>= 7
            if n:
                data.append(b | 0x80)
            else:
                data.append(b)
                break
    return [(STREAM_DATA, bytes(data)),
            (STREAM_SECONDARY, _rle1_encode(scales, signed=True))]


def _column_streams(arr: np.ndarray, kind: int) -> List[Tuple[int, bytes]]:
    """(stream kind, raw bytes) for one non-null column."""
    if kind == K_BOOLEAN:
        return [(STREAM_DATA, _bool_encode(np.asarray(arr, bool)))]
    if kind in (K_INT, K_LONG, K_SHORT, K_BYTE):
        return [(STREAM_DATA,
                 _rle1_encode(np.asarray(arr, np.int64), signed=True))]
    if kind == K_FLOAT:
        return [(STREAM_DATA,
                 np.asarray(arr, "<f4").tobytes())]
    if kind == K_DOUBLE:
        return [(STREAM_DATA, np.asarray(arr, "<f8").tobytes())]
    if kind == K_TIMESTAMP:
        ns = np.asarray(arr, "datetime64[ns]").astype(np.int64)
        # seconds TRUNCATE toward zero and the nanos remainder carries the
        # sign (the C++ ORC convention: floor-encoded pre-1970 fractional
        # values read back one second early in foreign readers)
        secs = ns // 1_000_000_000
        rem = ns - secs * 1_000_000_000
        adjust = (ns < 0) & (rem != 0)
        secs = secs + adjust
        nanos = rem - adjust * 1_000_000_000
        enc = _nanos_encode(nanos)
        # negative encodings ride the unsigned stream as 64-bit two's
        # complement (what the C++ writer emits)
        enc_u = [int(x) & 0xFFFFFFFFFFFFFFFF for x in enc.tolist()]
        return [(STREAM_DATA,
                 _rle1_encode(secs - _ORC_EPOCH_S, signed=True)),
                (STREAM_SECONDARY,
                 _rle1_encode(np.asarray(enc_u, object), signed=False))]
    if kind == K_DECIMAL:
        return _decimal_streams(arr)
    if kind == K_STRING:
        blobs = [("" if v is None else str(v)).encode() for v in
                 arr.tolist()]
        lengths = np.asarray([len(b) for b in blobs], np.int64)
        return [(STREAM_DATA, b"".join(blobs)),
                (STREAM_LENGTH, _rle1_encode(lengths, signed=False))]
    raise ValueError(f"unsupported ORC kind {kind}")


def write_orc(batches: Iterable[RecordBatch], path: str,
              compression: str = "zlib",
              stripe_rows: int = 1 << 16) -> int:
    """Drain ``batches`` into an ORC file (one stripe per ``stripe_rows``
    rows).  Returns rows written."""
    comp = {"none": COMP_NONE, "zlib": COMP_ZLIB}[compression]
    pending: List[RecordBatch] = []
    pending_rows = 0
    names: Optional[List[str]] = None
    kinds: Optional[List[int]] = None
    stripes: List[Dict[str, int]] = []
    total_rows = 0

    with open(path, "wb") as f:
        f.write(MAGIC)

        def flush_stripe():
            nonlocal pending, pending_rows, total_rows
            if not pending_rows:
                return
            merged = (pending[0] if len(pending) == 1
                      else RecordBatch.concat(pending))
            pending, pending_rows = [], 0
            offset = f.tell()
            sf_streams = _Msg()
            data_parts: List[bytes] = []
            # struct root (column 0) has no streams; encodings cover it
            encodings = [_Msg().varint(1, ENC_DIRECT)]
            for col, (name, kind) in enumerate(zip(names, kinds), start=1):
                arr = np.asarray(merged.column(name))
                for skind, raw in _column_streams(arr, kind):
                    blob = _compress_stream(raw, comp)
                    sf_streams.msg(1, _Msg().varint(1, skind)
                                   .varint(2, col).varint(3, len(blob)))
                    data_parts.append(blob)
                encodings.append(_Msg().varint(1, ENC_DIRECT))
            data = b"".join(data_parts)
            f.write(data)
            for e in encodings:
                sf_streams.msg(2, e)
            sfoot = _compress_stream(sf_streams.encode(), comp)
            f.write(sfoot)
            stripes.append({"offset": offset, "index": 0,
                            "data": len(data), "footer": len(sfoot),
                            "rows": len(merged)})
            total_rows += len(merged)

        for b in batches:
            if len(b) == 0:
                if names is None:
                    names = list(b.columns)
                    kinds = [_orc_kind(np.asarray(b.column(c)))
                             for c in names]
                continue
            if names is None:
                names = list(b.columns)
                kinds = [_orc_kind(np.asarray(b.column(c))) for c in names]
            pending.append(b)
            pending_rows += len(b)
            if pending_rows >= stripe_rows:
                flush_stripe()
        flush_stripe()
        if names is None:
            names, kinds = [], []

        body_end = f.tell()
        footer = _Msg()
        footer.varint(1, len(MAGIC))                 # headerLength
        footer.varint(2, body_end)                   # contentLength
        for s in stripes:
            footer.msg(3, _Msg().varint(1, s["offset"])
                       .varint(2, s["index"]).varint(3, s["data"])
                       .varint(4, s["footer"]).varint(5, s["rows"]))
        root = _Msg().varint(1, K_STRUCT)
        for i, name in enumerate(names, start=1):
            root.varint(2, i)
        for name in names:
            root.string(3, name)
        footer.msg(4, root)
        for kind in kinds:
            tm = _Msg().varint(1, kind)
            if kind == K_DECIMAL:
                tm.varint(5, 38).varint(6, 18)   # precision/scale attrs
            footer.msg(4, tm)
        footer.varint(6, total_rows)
        footer.varint(8, 0)                          # rowIndexStride: none
        fblob = _compress_stream(footer.encode(), comp)
        f.write(fblob)

        ps = _Msg()
        ps.varint(1, len(fblob))                     # footerLength
        ps.varint(2, comp)
        ps.varint(3, _CHUNK)
        ps.varint(4, 0).varint(4, 12)                # version 0.12
        ps.varint(5, 0)                              # metadataLength
        ps.varint(6, 1)                              # writerVersion
        ps.string(8000, "ORC")
        psb = ps.encode()
        f.write(psb)
        f.write(bytes([len(psb)]))
    return total_rows


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------

_KIND_SIGNED = {K_BYTE, K_SHORT, K_INT, K_LONG}


def read_orc(path: str, batch_size: int = 0,
             timestamp_column: Optional[str] = None
             ) -> Iterator[RecordBatch]:
    """One RecordBatch per stripe (``batch_size`` ignored: the stripe is
    the natural vectorized unit, as in the reference's columnar reader)."""
    with open(path, "rb") as f:
        raw = f.read()
    if not raw.startswith(MAGIC):
        raise ValueError("not an ORC file (bad magic)")
    ps_len = raw[-1]
    ps = _pb_decode(raw[-1 - ps_len:-1])
    comp = _one(ps, 2, COMP_NONE)
    flen = _one(ps, 1)
    footer = _pb_decode(_decompress_stream(
        raw[-1 - ps_len - flen:-1 - ps_len], comp))
    types = [_pb_decode(t) for t in footer.get(4, [])]
    if not types or _one(types[0], 1, K_STRUCT) != K_STRUCT:
        raise ValueError("unsupported ORC schema: root must be a struct")
    names = [n.decode() for n in types[0].get(3, [])]
    kinds = [_one(types[i], 1) for i in range(1, len(types))]
    for s in footer.get(3, []):
        si = _pb_decode(s)
        offset = _one(si, 1, 0)
        ilen = _one(si, 2, 0)
        dlen = _one(si, 3, 0)
        sflen = _one(si, 4, 0)
        rows = _one(si, 5, 0)
        sfoot = _pb_decode(_decompress_stream(
            raw[offset + ilen + dlen:offset + ilen + dlen + sflen], comp))
        enc_msgs = [_pb_decode(e) for e in sfoot.get(2, [])]
        encodings = [_one(e, 1, ENC_DIRECT) for e in enc_msgs]
        dict_sizes = [_one(e, 2, 0) for e in enc_msgs]
        # stream directory: walk in order, tracking byte offsets
        streams: Dict[Tuple[int, int], bytes] = {}
        cursor = offset
        for st in sfoot.get(1, []):
            sm = _pb_decode(st)
            skind = _one(sm, 1, STREAM_DATA)
            col = _one(sm, 2, 0)
            ln = _one(sm, 3, 0)
            streams[(col, skind)] = raw[cursor:cursor + ln]
            cursor += ln
        cols: Dict[str, np.ndarray] = {}
        for j, (name, kind) in enumerate(zip(names, kinds)):
            col = j + 1
            enc = encodings[col] if col < len(encodings) else ENC_DIRECT

            def stream(skind, _col=col):
                blob = streams.get((_col, skind))
                return (None if blob is None
                        else _decompress_stream(blob, comp))

            present = stream(STREAM_PRESENT)
            n_phys = rows
            mask = None
            if present is not None:
                mask = _bool_decode(present, rows)
                n_phys = int(mask.sum())
            data = stream(STREAM_DATA)
            if kind == K_BOOLEAN:
                vals: Any = _bool_decode(data, n_phys)
            elif kind in _KIND_SIGNED:
                vals = _int_decode(data, n_phys, True, enc)
                if kind == K_INT:
                    vals = vals.astype(np.int32)
            elif kind == K_FLOAT:
                vals = np.frombuffer(data, "<f4", count=n_phys).copy()
            elif kind == K_DOUBLE:
                vals = np.frombuffer(data, "<f8", count=n_phys).copy()
            elif kind == K_TIMESTAMP:
                secs = _int_decode(data, n_phys, True, enc)
                nanos = _nanos_decode(_int_decode(
                    stream(STREAM_SECONDARY), n_phys, False, enc))
                ns = (secs + _ORC_EPOCH_S) * 1_000_000_000 + nanos
                vals = ns.astype("datetime64[ns]")
            elif kind == K_DECIMAL:
                import decimal
                scale_attr = _one(types[col], 6, 0)
                scales = None
                sec = stream(STREAM_SECONDARY)
                if sec:
                    scales = _int_decode(sec, n_phys, True, enc)
                mants: List[int] = []
                pos = 0
                for _ in range(n_phys):
                    u, pos = _read_uvarint(data, pos)
                    mants.append(_unzig(u))
                vals = np.asarray(
                    [decimal.Decimal(m).scaleb(
                        -int(scales[i] if scales is not None
                             else scale_attr))
                     for i, m in enumerate(mants)], object)
            elif kind in (K_STRING, K_BINARY):
                is_dict = enc in (ENC_DICTIONARY, ENC_DICTIONARY_V2)
                lens = _int_decode(
                    stream(STREAM_LENGTH),
                    dict_sizes[col] if is_dict else n_phys, False, enc)
                if is_dict:
                    dict_blob = stream(STREAM_DICT_DATA) or b""
                    ends = np.cumsum(lens)
                    starts = ends - lens
                    entries = [dict_blob[s:e].decode()
                               for s, e in zip(starts.tolist(),
                                               ends.tolist())]
                    idx = _int_decode(data, n_phys, False, enc)
                    vals = np.asarray([entries[i] for i in idx.tolist()],
                                      object)
                else:
                    ends = np.cumsum(lens)
                    starts = ends - lens
                    vals = np.asarray([data[s:e].decode()
                                       for s, e in zip(starts.tolist(),
                                                       ends.tolist())],
                                      object)
            else:
                raise ValueError(f"unsupported ORC type kind {kind}")
            if mask is not None and n_phys != rows:
                full = np.empty(rows, object)
                full[:] = None
                full[np.flatnonzero(mask)] = (
                    vals.tolist() if isinstance(vals, np.ndarray) else vals)
                vals = full
            cols[name] = np.asarray(vals)
        ts = (np.asarray(cols[timestamp_column], np.int64)
              if timestamp_column else None)
        yield RecordBatch(cols, timestamps=ts)
