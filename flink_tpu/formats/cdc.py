"""CDC changelog formats: Debezium / Canal / Maxwell JSON envelopes.

Analog of ``flink-formats/flink-json``'s changelog deserializers —
``DebeziumJsonDeserializationSchema.java:56``,
``CanalJsonDeserializationSchema``, ``MaxwellJsonDeserializationSchema`` —
and their serialization mirrors.  Each decoder maps one external envelope
to the framework's changelog rows: plain dicts carrying the payload columns
plus an ``op`` column (``+I`` insert, ``-U``/``+U`` update
retract/replace, ``-D`` delete), exactly the row shape the retraction
runtime (``flink_tpu.operators.sql_ops``) consumes and the streaming
joins/aggregates fold.

Envelope shapes handled:

- **Debezium** ``{"before": .., "after": .., "op": "c|r|u|d", ...}``;
  ``op`` c (create) and r (snapshot read) -> ``+I after``; u ->
  ``-U before`` + ``+U after``; d -> ``-D before``.
- **Canal** ``{"data": [rows], "old": [changed-cols], "type":
  "INSERT|UPDATE|DELETE"}`` — ``old[i]`` holds only the CHANGED columns of
  ``data[i]``'s previous image, so the before-row is ``data[i]`` overlaid
  with ``old[i]``.
- **Maxwell** ``{"data": row, "old": changed-cols, "type":
  "insert|update|delete"}`` — single-row variant of the Canal shape.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Union

Payload = Union[bytes, str, dict]

OP_INSERT = "+I"
OP_UPDATE_BEFORE = "-U"
OP_UPDATE_AFTER = "+U"
OP_DELETE = "-D"


def _as_dict(payload: Payload) -> dict:
    if isinstance(payload, dict):
        return payload
    if isinstance(payload, bytes):
        payload = payload.decode()
    return json.loads(payload)


def _row(op: str, data: dict) -> dict:
    out = dict(data)
    out["op"] = op
    return out


# ---------------------------------------------------------------------------
# decoders
# ---------------------------------------------------------------------------


def decode_debezium(payload: Payload) -> List[dict]:
    env = _as_dict(payload)
    if "payload" in env and "op" in (env.get("payload") or {}):
        env = env["payload"]    # schema-included envelope: unwrap
    op = env.get("op")
    before, after = env.get("before"), env.get("after")
    if op in ("c", "r"):
        if after is None:
            raise ValueError(f"debezium op {op!r} without 'after'")
        return [_row(OP_INSERT, after)]
    if op == "u":
        if before is None or after is None:
            raise ValueError("debezium op 'u' needs 'before' and 'after'")
        return [_row(OP_UPDATE_BEFORE, before),
                _row(OP_UPDATE_AFTER, after)]
    if op == "d":
        if before is None:
            raise ValueError("debezium op 'd' without 'before'")
        return [_row(OP_DELETE, before)]
    raise ValueError(f"unknown debezium op {op!r}")


def decode_canal(payload: Payload) -> List[dict]:
    env = _as_dict(payload)
    typ = (env.get("type") or "").upper()
    data = env.get("data") or []
    old = env.get("old") or []
    if typ == "INSERT":
        return [_row(OP_INSERT, r) for r in data]
    if typ == "DELETE":
        return [_row(OP_DELETE, r) for r in data]
    if typ == "UPDATE":
        out: List[dict] = []
        for i, r in enumerate(data):
            changed = old[i] if i < len(old) and old[i] else {}
            out.append(_row(OP_UPDATE_BEFORE, {**r, **changed}))
            out.append(_row(OP_UPDATE_AFTER, r))
        return out
    raise ValueError(f"unknown canal type {env.get('type')!r}")


def decode_maxwell(payload: Payload) -> List[dict]:
    env = _as_dict(payload)
    typ = (env.get("type") or "").lower()
    data = env.get("data") or {}
    old = env.get("old") or {}
    if typ == "insert":
        return [_row(OP_INSERT, data)]
    if typ == "delete":
        return [_row(OP_DELETE, data)]
    if typ == "update":
        return [_row(OP_UPDATE_BEFORE, {**data, **old}),
                _row(OP_UPDATE_AFTER, data)]
    raise ValueError(f"unknown maxwell type {env.get('type')!r}")


_DECODERS: Dict[str, Callable[[Payload], List[dict]]] = {
    "debezium-json": decode_debezium,
    "canal-json": decode_canal,
    "maxwell-json": decode_maxwell,
}


def cdc_decoder(fmt: str) -> Callable[[Payload], List[dict]]:
    """Decoder for a CDC format name — plugs into
    ``KafkaWireSource(value_decoder=...)``."""
    if fmt not in _DECODERS:
        raise ValueError(f"unknown CDC format {fmt!r}; "
                         f"have {sorted(_DECODERS)}")
    return _DECODERS[fmt]


# ---------------------------------------------------------------------------
# encoders (changelog rows -> external envelopes, the serialization mirror)
# ---------------------------------------------------------------------------


def _strip_op(row: dict) -> dict:
    return {k: v for k, v in row.items() if k != "op"}


def encode_debezium(rows: List[dict]) -> List[dict]:
    """Changelog rows -> debezium envelopes.  A ``-U``/``+U`` pair folds
    into ONE ``op: u`` envelope (before/after); ``-U`` without a following
    ``+U`` of the same shape encodes as a delete, matching what the
    reference's serializer emits for upsert materialization."""
    out: List[dict] = []
    i = 0
    while i < len(rows):
        r = rows[i]
        op = r.get("op", OP_INSERT)
        if op == OP_INSERT:
            out.append({"before": None, "after": _strip_op(r), "op": "c"})
        elif op == OP_DELETE:
            out.append({"before": _strip_op(r), "after": None, "op": "d"})
        elif op == OP_UPDATE_BEFORE and i + 1 < len(rows) \
                and rows[i + 1].get("op") == OP_UPDATE_AFTER:
            out.append({"before": _strip_op(r),
                        "after": _strip_op(rows[i + 1]), "op": "u"})
            i += 1
        elif op == OP_UPDATE_BEFORE:
            out.append({"before": _strip_op(r), "after": None, "op": "d"})
        elif op == OP_UPDATE_AFTER:
            out.append({"before": None, "after": _strip_op(r), "op": "c"})
        else:
            raise ValueError(f"unknown changelog op {op!r}")
        i += 1
    return out


def encode_canal(rows: List[dict]) -> List[dict]:
    out: List[dict] = []
    i = 0
    while i < len(rows):
        r = rows[i]
        op = r.get("op", OP_INSERT)
        if op == OP_INSERT:
            out.append({"data": [_strip_op(r)], "old": None,
                        "type": "INSERT"})
        elif op == OP_DELETE:
            out.append({"data": [_strip_op(r)], "old": None,
                        "type": "DELETE"})
        elif op == OP_UPDATE_BEFORE and i + 1 < len(rows) \
                and rows[i + 1].get("op") == OP_UPDATE_AFTER:
            before, after = _strip_op(r), _strip_op(rows[i + 1])
            changed = {k: v for k, v in before.items()
                       if after.get(k) != v}
            out.append({"data": [after], "old": [changed],
                        "type": "UPDATE"})
            i += 1
        else:
            raise ValueError(f"unpaired changelog op {op!r} at row {i}")
        i += 1
    return out
