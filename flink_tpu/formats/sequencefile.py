"""Hadoop SequenceFile format (version 6, record-oriented).

Analog of the reference's sequence-file support
(``flink-formats/flink-sequence-file``): the Hadoop container header
(``SEQ`` magic + version, key/value class names as Hadoop Text, the
compression flags, a metadata Text map, a 16-byte sync marker), followed
by records framed as ``record-length, key-length, key bytes, value
bytes`` with periodic ``-1 + sync`` resynchronization points — the
layout HDFS-era tooling (Hive external tables, MapReduce inputs) reads.

Scope: uncompressed record format with ``org.apache.hadoop.io.Text``
keys and values.  Rows serialize as ``key = <key column text>``,
``value = JSON of the remaining columns`` — the
``SequenceFileWriterFactory<Text, Text>`` shape.  Block compression and
other Writable classes are not implemented.
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from flink_tpu.core.batch import RecordBatch

MAGIC = b"SEQ"
VERSION = 6
TEXT = b"org.apache.hadoop.io.Text"
_SYNC_INTERVAL = 2000   # bytes between sync markers, like Hadoop's


def _write_vint(out: io.BytesIO, n: int) -> None:
    """Hadoop WritableUtils.writeVInt (zero-compressed)."""
    if -112 <= n <= 127:
        out.write(struct.pack("b", n))
        return
    length = -112
    if n < 0:
        n ^= -1
        length = -120
    tmp = n
    while tmp:
        tmp >>= 8
        length -= 1
    out.write(struct.pack("b", length))
    size = (-length) - 112 if length >= -120 else (-length) - 120
    for i in range(size - 1, -1, -1):
        out.write(struct.pack("B", (n >> (8 * i)) & 0xFF))


def _read_vint(f) -> int:
    (first,) = struct.unpack("b", f.read(1))
    if first >= -112:
        return first
    negative = first < -120
    size = (-first) - 120 if negative else (-first) - 112
    n = 0
    for _ in range(size):
        n = (n << 8) | f.read(1)[0]
    return (n ^ -1) if negative else n


def _text(b: bytes) -> bytes:
    out = io.BytesIO()
    _write_vint(out, len(b))
    out.write(b)
    return out.getvalue()


def _read_text(f) -> bytes:
    n = _read_vint(f)
    return f.read(n)


def write_sequencefile(batches, path: str,
                       key_column: Optional[str] = None) -> int:
    """Drain batches into a SequenceFile; ``key_column`` becomes the Text
    key (empty when None), every column JSON-serializes into the Text
    value.  Returns rows written."""
    from flink_tpu.connectors.util import json_default

    sync = os.urandom(16)
    n = 0
    with open(path, "wb") as f:
        f.write(MAGIC + bytes([VERSION]))
        f.write(_text(TEXT))                 # key class
        f.write(_text(TEXT))                 # value class
        f.write(b"\x00\x00")                 # no value/block compression
        f.write(struct.pack(">i", 0))        # empty metadata map
        f.write(sync)
        since_sync = 0
        for b in batches:
            for row in b.to_rows():
                key = (b"" if key_column is None
                       else str(row[key_column]).encode())
                val = json.dumps(row, default=json_default).encode()
                krec = _text(key)
                vrec = _text(val)
                if since_sync >= _SYNC_INTERVAL:
                    f.write(struct.pack(">i", -1) + sync)
                    since_sync = 0
                rec = struct.pack(">ii", len(krec) + len(vrec),
                                  len(krec)) + krec + vrec
                f.write(rec)
                since_sync += len(rec)
                n += 1
    return n


def read_sequencefile(path: str, batch_size: int = 8192,
                      timestamp_column: Optional[str] = None,
                      skip_rows: int = 0) -> Iterator[RecordBatch]:
    """SequenceFile -> RecordBatch iterator.  Text values parse as JSON
    rows when possible; otherwise each record yields
    ``{"key": <str>, "value": <str>}`` (foreign files with plain text
    payloads stay readable)."""
    from flink_tpu.connectors.util import rows_to_batch

    with open(path, "rb") as f:
        hdr = f.read(4)
        if len(hdr) < 4 or hdr[:3] != MAGIC:
            raise ValueError("not a SequenceFile (bad magic)")
        if hdr[3] != VERSION:
            raise ValueError(f"unsupported SequenceFile version {hdr[3]}")
        key_cls = _read_text(f)
        val_cls = _read_text(f)
        if key_cls != TEXT or val_cls != TEXT:
            raise ValueError(
                f"unsupported Writable classes {key_cls!r}/{val_cls!r} "
                f"(Text/Text only)")
        comp, block = f.read(2)
        if comp or block:
            raise ValueError("compressed SequenceFiles are not supported")
        (nmeta,) = struct.unpack(">i", f.read(4))
        for _ in range(nmeta):
            _read_text(f)
            _read_text(f)
        sync = f.read(16)
        rows: List[dict] = []
        seen = 0
        while True:
            lenb = f.read(4)
            if len(lenb) < 4:
                break
            (rec_len,) = struct.unpack(">i", lenb)
            if rec_len == -1:                  # sync marker
                got = f.read(16)
                if len(got) < 16:
                    break                      # torn tail inside the sync
                if got != sync:
                    raise ValueError("sync marker mismatch (corrupt file)")
                continue
            if rec_len < 0:
                raise ValueError(f"corrupt record length {rec_len}")
            klenb = f.read(4)
            if len(klenb) < 4:
                break                          # torn tail: keep the prefix
            (key_len,) = struct.unpack(">i", klenb)
            if not 0 <= key_len <= rec_len:
                raise ValueError(f"corrupt key length {key_len} "
                                 f"(record {rec_len})")
            kv = f.read(rec_len)
            if len(kv) < rec_len:
                break                          # torn tail record
            kbuf = io.BytesIO(kv[:key_len])
            vbuf = io.BytesIO(kv[key_len:])
            key = _read_text(kbuf).decode()
            val = _read_text(vbuf).decode()
            seen += 1
            if seen <= skip_rows:
                continue
            try:
                row = json.loads(val)
                if not isinstance(row, dict):
                    raise ValueError
                if key:
                    # the record KEY is data too — a foreign file may keep
                    # meaning only there; never silently drop it (when the
                    # value already owns "key", park it next door)
                    row["key" if "key" not in row else "_seq_key"] = key
            except ValueError:
                row = {"key": key, "value": val}
            rows.append(row)
            if len(rows) >= batch_size:
                yield rows_to_batch(rows, timestamp_column)
                rows = []
        if rows:
            yield rows_to_batch(rows, timestamp_column)
