"""Parquet, pure Python from the spec — no pyarrow needed.

Analog of the reference's ``flink-formats/flink-parquet``
(``ParquetColumnarRowInputFormat.java:1`` — vectorized columnar reads,
``ParquetWriterFactory`` writes).  The format is implemented from first
principles the same way ``avro.py`` was — a dependency-free codec is the
point, not a workaround: it keeps the wire format auditable and the runtime
image minimal.  pyarrow, where present, serves only as the FOREIGN
implementation in the interop tests (``tests/test_foreign_interop.py``
round-trips live pyarrow <-> this module, plus checked-in pyarrow-written
fixture bytes that validate reads even without it):

- **File layout**: ``PAR1`` magic, row groups of column chunks (one data
  page each, optional dictionary page), then the thrift-compact-encoded
  ``FileMetaData`` footer, its int32-LE length, ``PAR1``.
- **Thrift compact protocol**: a minimal encoder/decoder (varint + zigzag,
  field-delta headers, lists, nested structs) covers the metadata structs
  used: FileMetaData, SchemaElement, RowGroup, ColumnChunk,
  ColumnMetaData, PageHeader, DataPageHeader, DictionaryPageHeader.
- **Types**: BOOLEAN (bit-packed), INT32, INT64, FLOAT, DOUBLE,
  BYTE_ARRAY (UTF8 strings).  Columns are flat and REQUIRED (the columnar
  runtime carries no nulls), so pages hold values only — no
  definition/repetition levels, exactly as the spec prescribes for
  max-def-level 0.
- **Encodings**: PLAIN everywhere; PLAIN_DICTIONARY (dictionary page +
  RLE/bit-packed hybrid index page) for BYTE_ARRAY columns with small
  cardinality ("auto") or on request.  The reader handles both RLE runs
  and bit-packed groups of the hybrid.
- **Compression**: UNCOMPRESSED or GZIP (stdlib), per the gated-dependency
  policy (no snappy in this image).

``read_parquet`` yields one RecordBatch per row group; ``write_parquet``
drains batches into row groups.  Validated against spec-derived golden
bytes, round-trips, AND foreign-interop fixtures (files written by the
Apache Arrow C++ writers) — see ``tests/test_foreign_interop.py``.
"""

from __future__ import annotations

import gzip as _gzip
import io
import struct
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import RecordBatch

MAGIC = b"PAR1"

# parquet.thrift enums
T_BOOLEAN, T_INT32, T_INT64, T_FLOAT, T_DOUBLE, T_BYTE_ARRAY = 0, 1, 2, 4, 5, 6
ENC_PLAIN, ENC_PLAIN_DICTIONARY, ENC_RLE = 0, 2, 3
CODEC_UNCOMPRESSED, CODEC_GZIP = 0, 2
PAGE_DATA, PAGE_DICTIONARY = 0, 2
REP_REQUIRED = 0
CONV_UTF8 = 0
CONV_UINT_32, CONV_UINT_64 = 13, 14

# thrift compact field types
_CT_BOOL_TRUE, _CT_BOOL_FALSE, _CT_BYTE = 1, 2, 3
_CT_I16, _CT_I32, _CT_I64, _CT_DOUBLE = 4, 5, 6, 7
_CT_BINARY, _CT_LIST, _CT_SET, _CT_MAP, _CT_STRUCT = 8, 9, 10, 11, 12


# ---------------------------------------------------------------------------
# thrift compact protocol
# ---------------------------------------------------------------------------

def _uvarint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _zz(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


class _StructW:
    """Thrift-compact struct writer (field-id delta headers)."""

    def __init__(self, out: bytearray):
        self.out = out
        self.last = 0

    def _hdr(self, fid: int, ftype: int) -> None:
        delta = fid - self.last
        if 0 < delta <= 15:
            self.out.append((delta << 4) | ftype)
        else:
            self.out.append(ftype)
            self.out += _uvarint(_zz(fid))
        self.last = fid

    def i32(self, fid: int, v: int) -> None:
        self._hdr(fid, _CT_I32)
        self.out += _uvarint(_zz(int(v)))

    def i64(self, fid: int, v: int) -> None:
        self._hdr(fid, _CT_I64)
        self.out += _uvarint(_zz(int(v)))

    def binary(self, fid: int, b: bytes) -> None:
        self._hdr(fid, _CT_BINARY)
        self.out += _uvarint(len(b))
        self.out += b

    def string(self, fid: int, s: str) -> None:
        self.binary(fid, s.encode())

    def list_begin(self, fid: int, etype: int, n: int) -> None:
        self._hdr(fid, _CT_LIST)
        if n < 15:
            self.out.append((n << 4) | etype)
        else:
            self.out.append(0xF0 | etype)
            self.out += _uvarint(n)

    def struct(self, fid: int) -> "_StructW":
        self._hdr(fid, _CT_STRUCT)
        return _StructW(self.out)

    def stop(self) -> None:
        self.out.append(0)


class _TR:
    """Thrift-compact reader: structs decode to {field_id: value}."""

    def __init__(self, data: bytes, pos: int = 0):
        self.d = data
        self.p = pos

    def u8(self) -> int:
        v = self.d[self.p]
        self.p += 1
        return v

    def uvarint(self) -> int:
        n = 0
        shift = 0
        while True:
            b = self.u8()
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7

    def zig(self) -> int:
        u = self.uvarint()
        return (u >> 1) ^ -(u & 1)

    def value(self, ftype: int):
        if ftype == _CT_BOOL_TRUE:
            return True
        if ftype == _CT_BOOL_FALSE:
            return False
        if ftype in (_CT_BYTE,):
            v = self.u8()
            return v - 256 if v > 127 else v
        if ftype in (_CT_I16, _CT_I32, _CT_I64):
            return self.zig()
        if ftype == _CT_DOUBLE:
            v = struct.unpack_from("<d", self.d, self.p)[0]
            self.p += 8
            return v
        if ftype == _CT_BINARY:
            n = self.uvarint()
            b = self.d[self.p:self.p + n]
            self.p += n
            return b
        if ftype == _CT_LIST or ftype == _CT_SET:
            h = self.u8()
            n = h >> 4
            et = h & 0x0F
            if n == 15:
                n = self.uvarint()
            return [self.value(et) for _ in range(n)]
        if ftype == _CT_STRUCT:
            return self.struct()
        raise ValueError(f"thrift compact: unsupported type {ftype}")

    def struct(self) -> Dict[int, Any]:
        out: Dict[int, Any] = {}
        fid = 0
        while True:
            h = self.u8()
            if h == 0:
                return out
            delta = h >> 4
            ftype = h & 0x0F
            fid = fid + delta if delta else self.zig()
            if ftype == _CT_BOOL_TRUE:
                out[fid] = True
            elif ftype == _CT_BOOL_FALSE:
                out[fid] = False
            else:
                out[fid] = self.value(ftype)


# ---------------------------------------------------------------------------
# value codecs
# ---------------------------------------------------------------------------

_NP_OF = {T_INT32: np.int32, T_INT64: np.int64, T_FLOAT: np.float32,
          T_DOUBLE: np.float64}


def _column_type(arr: np.ndarray) -> Tuple[int, Optional[int]]:
    """-> (physical type, converted type or None).  Unsigned ints store as
    the same-width signed physical with a UINT converted type (the spec's
    scheme: bit reinterpretation, re-viewed on read)."""
    if arr.dtype.kind in "OU":
        return T_BYTE_ARRAY, CONV_UTF8
    if arr.dtype.kind == "b":
        return T_BOOLEAN, None
    if arr.dtype.kind == "u":
        return ((T_INT32, CONV_UINT_32) if arr.dtype.itemsize <= 4
                else (T_INT64, CONV_UINT_64))
    if arr.dtype.kind == "i":
        return (T_INT32 if arr.dtype.itemsize <= 4 else T_INT64), None
    if arr.dtype.kind == "f":
        return (T_FLOAT if arr.dtype.itemsize == 4 else T_DOUBLE), None
    raise ValueError(f"unsupported parquet column dtype {arr.dtype}")


def _encode_plain(arr: np.ndarray, ptype: int) -> bytes:
    if ptype == T_BOOLEAN:
        return np.packbits(np.asarray(arr, bool), bitorder="little").tobytes()
    if ptype == T_BYTE_ARRAY:
        out = bytearray()
        for s in arr.tolist():
            b = s if isinstance(s, bytes) else str(s).encode()
            out += struct.pack("<I", len(b))
            out += b
        return bytes(out)
    npt = _NP_OF[ptype]
    if arr.dtype.kind == "u":
        # unsigned: store the BITS (view), not the value (astype would
        # clamp/wrap differently across widths) — reader re-views
        wide = arr.astype(np.uint32 if ptype == T_INT32 else np.uint64,
                          copy=False)
        return np.ascontiguousarray(wide).view(npt).tobytes()
    return np.ascontiguousarray(arr.astype(npt, copy=False)).tobytes()


def _decode_plain(data: bytes, ptype: int, n: int) -> np.ndarray:
    if ptype == T_BOOLEAN:
        bits = np.unpackbits(np.frombuffer(data, np.uint8),
                             bitorder="little")[:n]
        return bits.astype(bool)
    if ptype == T_BYTE_ARRAY:
        out = []
        p = 0
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", data, p)
            p += 4
            out.append(data[p:p + ln].decode())
            p += ln
        return np.asarray(out, object)
    return np.frombuffer(data, _NP_OF[ptype], count=n).copy()


def _rle_bitpack_write(indices: np.ndarray, bit_width: int) -> bytes:
    """RLE/bit-packed hybrid, bit-packed groups only (spec-conformant:
    readers must accept either run kind)."""
    n = len(indices)
    groups = (n + 7) // 8
    padded = np.zeros(groups * 8, np.int64)
    padded[:n] = indices
    bits = np.zeros(groups * 8 * bit_width, np.uint8)
    for b in range(bit_width):
        bits[b::bit_width] = (padded >> b) & 1
    packed = np.packbits(bits, bitorder="little").tobytes()
    return bytes(_uvarint((groups << 1) | 1)) + packed


def _rle_bitpack_read(data: bytes, bit_width: int, n: int) -> np.ndarray:
    out = np.empty(n, np.int64)
    got = 0
    r = _TR(data)
    width_bytes = (bit_width + 7) // 8
    while got < n:
        header = r.uvarint()
        if header & 1:
            groups = header >> 1
            count = groups * 8
            nbytes = groups * bit_width
            raw = np.frombuffer(r.d, np.uint8, count=nbytes, offset=r.p)
            r.p += nbytes
            bits = np.unpackbits(raw, bitorder="little")
            vals = np.zeros(count, np.int64)
            for b in range(bit_width):
                vals |= bits[b::bit_width].astype(np.int64) << b
            take = min(count, n - got)
            out[got:got + take] = vals[:take]
            got += take
        else:
            run = header >> 1
            raw = r.d[r.p:r.p + width_bytes]
            r.p += width_bytes
            val = int.from_bytes(raw, "little")
            take = min(run, n - got)
            out[got:got + take] = val
            got += take
    return out


def _compress(data: bytes, codec: int) -> bytes:
    return _gzip.compress(data) if codec == CODEC_GZIP else data


def _decompress(data: bytes, codec: int, _orig: int) -> bytes:
    return _gzip.decompress(data) if codec == CODEC_GZIP else data


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

def write_parquet(batches: Iterable[RecordBatch], path: str,
                  row_group_rows: int = 1 << 20,
                  compression: Optional[str] = None,
                  dictionary: str = "auto", **_kw) -> int:
    """Drain batches into one Parquet file; returns rows written.  Memory
    is bounded by ONE row group (batches stream straight to the open file;
    footer offsets come from ``tell``).

    ``compression``: None | "gzip".  ``dictionary``: "auto" (BYTE_ARRAY
    columns with <50% distinct values), "always", "never"."""
    codec = CODEC_GZIP if compression == "gzip" else CODEC_UNCOMPRESSED
    if isinstance(batches, RecordBatch):
        batches = [batches]
    row_groups_meta: List[dict] = []
    columns: Optional[List[str]] = None
    ptypes: Dict[str, Tuple[int, Optional[int]]] = {}
    n_rows = 0
    pending: List[RecordBatch] = []
    pending_rows = 0

    with open(path, "wb") as f:
        f.write(MAGIC)

        def flush_group():
            nonlocal pending, pending_rows
            if not pending:
                return
            group = (pending[0] if len(pending) == 1
                     else RecordBatch.concat(pending))
            pending, pending_rows = [], 0
            _write_row_group(f, group, columns, ptypes, codec, dictionary,
                             row_groups_meta)

        for b in batches:
            if len(b) == 0:
                continue
            if columns is None:
                columns = list(b.columns)
                ptypes = {c: _column_type(np.asarray(b.column(c)))
                          for c in columns}
            n_rows += len(b)
            pending.append(b)
            pending_rows += len(b)
            while pending_rows >= row_group_rows:
                whole = (pending[0] if len(pending) == 1
                         else RecordBatch.concat(pending))
                cut = whole.take(np.arange(row_group_rows))
                rest = whole.take(np.arange(row_group_rows, len(whole)))
                pending, pending_rows = [cut], row_group_rows
                flush_group()
                pending = [rest] if len(rest) else []
                pending_rows = len(rest)
        if columns is None:
            raise ValueError("write_parquet: no rows (schema source) given")
        flush_group()
        footer = _file_metadata(columns, ptypes, n_rows, row_groups_meta)
        f.write(footer)
        f.write(struct.pack("<I", len(footer)))
        f.write(MAGIC)
    return n_rows


def _write_row_group(f, group: RecordBatch, columns, ptypes, codec,
                     dictionary, row_groups_meta) -> None:
    chunks_meta = []
    group_bytes = 0
    n = len(group)
    for c in columns:
        arr = np.asarray(group.column(c))
        ptype, _conv = ptypes[c]
        use_dict = False
        uniq: List[Any] = []
        if ptype == T_BYTE_ARRAY and dictionary != "never":
            uniq = sorted(set(arr.tolist()))   # raw values: str OR bytes
            use_dict = dictionary == "always" or len(uniq) * 2 < n
        dict_off = None
        first_off = f.tell()
        encodings = [ENC_PLAIN]
        uncomp_total = 0
        if use_dict:
            uniq_arr = np.asarray(uniq, object)
            lookup = {v: i for i, v in enumerate(uniq)}
            idx = np.asarray([lookup[v] for v in arr.tolist()], np.int64)
            dict_off = f.tell()
            raw = _encode_plain(uniq_arr, ptype)
            comp = _compress(raw, codec)
            hdr = _page_header(PAGE_DICTIONARY, len(raw), len(comp),
                               num_values=len(uniq_arr))
            f.write(hdr)
            f.write(comp)
            uncomp_total += len(hdr) + len(raw)
            bw = max(int(np.ceil(np.log2(max(len(uniq_arr), 2)))), 1)
            raw_p = bytes([bw]) + _rle_bitpack_write(idx, bw)
            comp_p = _compress(raw_p, codec)
            data_off = f.tell()
            hdr = _page_header(PAGE_DATA, len(raw_p), len(comp_p),
                               num_values=n,
                               encoding=ENC_PLAIN_DICTIONARY)
            f.write(hdr)
            f.write(comp_p)
            uncomp_total += len(hdr) + len(raw_p)
            encodings = [ENC_PLAIN, ENC_PLAIN_DICTIONARY, ENC_RLE]
        else:
            raw = _encode_plain(arr, ptype)
            comp = _compress(raw, codec)
            data_off = f.tell()
            hdr = _page_header(PAGE_DATA, len(raw), len(comp),
                               num_values=n)
            f.write(hdr)
            f.write(comp)
            uncomp_total += len(hdr) + len(raw)
        chunk_bytes = f.tell() - first_off
        group_bytes += chunk_bytes
        chunks_meta.append({
            "name": c, "type": ptype, "encodings": encodings,
            "codec": codec, "num_values": n,
            "data_off": data_off, "dict_off": dict_off,
            "total_comp": chunk_bytes, "total_uncomp": uncomp_total,
            "file_off": first_off})
    row_groups_meta.append({"columns": chunks_meta,
                            "bytes": group_bytes, "rows": n})


def _page_header(ptype: int, uncomp: int, comp: int, num_values: int,
                 encoding: int = ENC_PLAIN) -> bytes:
    out = bytearray()
    w = _StructW(out)
    w.i32(1, ptype)
    w.i32(2, uncomp)
    w.i32(3, comp)
    if ptype == PAGE_DATA:
        dph = w.struct(5)
        dph.i32(1, num_values)
        dph.i32(2, encoding)
        dph.i32(3, ENC_RLE)            # definition levels (absent: required)
        dph.i32(4, ENC_RLE)            # repetition levels (absent: flat)
        dph.stop()
    else:
        dph = w.struct(7)
        dph.i32(1, num_values)
        dph.i32(2, ENC_PLAIN)
        dph.stop()
    w.stop()
    return bytes(out)


def _file_metadata(columns, ptypes, n_rows, row_groups) -> bytes:
    out = bytearray()
    w = _StructW(out)
    w.i32(1, 1)                        # version
    w.list_begin(2, _CT_STRUCT, 1 + len(columns))
    root = _StructW(out)               # SchemaElement root
    root.string(4, "schema")
    root.i32(5, len(columns))
    root.stop()
    for c in columns:
        ptype, conv = ptypes[c]
        el = _StructW(out)
        el.i32(1, ptype)
        el.i32(3, REP_REQUIRED)
        el.string(4, c)
        if conv is not None:
            el.i32(6, conv)
        el.stop()
    w.i64(3, n_rows)
    w.list_begin(4, _CT_STRUCT, len(row_groups))
    for rg in row_groups:
        g = _StructW(out)
        g.list_begin(1, _CT_STRUCT, len(rg["columns"]))
        for cm in rg["columns"]:
            cc = _StructW(out)
            cc.i64(2, cm["file_off"])
            md = cc.struct(3)          # ColumnMetaData
            md.i32(1, cm["type"])
            md.list_begin(2, _CT_I32, len(cm["encodings"]))
            for e in cm["encodings"]:
                md.out += _uvarint(_zz(e))
            md.list_begin(3, _CT_BINARY, 1)
            name = cm["name"].encode()
            md.out += _uvarint(len(name))
            md.out += name
            md.i32(4, cm["codec"])
            md.i64(5, cm["num_values"])
            md.i64(6, cm["total_uncomp"])
            md.i64(7, cm["total_comp"])
            md.i64(9, cm["data_off"])
            if cm["dict_off"] is not None:
                md.i64(11, cm["dict_off"])
            md.stop()
            cc.stop()
        g.i64(2, rg["bytes"])
        g.i64(3, rg["rows"])
        g.stop()
    w.string(6, "flink-tpu parquet 1.0")
    w.stop()
    return bytes(out)


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------

def read_parquet(path: str, batch_size: int = 0, **_kw):
    """Yield one RecordBatch per row group (the vectorized columnar read,
    ``ParquetColumnarRowInputFormat`` analog)."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != MAGIC or data[-4:] != MAGIC:
        raise ValueError("not a parquet file (missing PAR1 magic)")
    (flen,) = struct.unpack_from("<I", data, len(data) - 8)
    meta = _TR(data, len(data) - 8 - flen).struct()
    schema = meta[2]
    leaves = schema[1:]                # flat: root then leaf elements
    names = [el[4].decode() for el in leaves]
    convs = {el[4].decode(): el.get(6) for el in leaves}
    for rg in meta[4]:
        cols: Dict[str, np.ndarray] = {}
        n_rows = rg[3]
        for cc in rg[1]:
            md = cc[3]
            name = md[3][0].decode()
            ptype = md[1]
            codec = md.get(4, CODEC_UNCOMPRESSED)
            dict_off = md.get(11)
            data_off = md[9]
            num_values = md[5]
            dictionary = None
            pos = data_off
            if dict_off is not None:
                r = _TR(data, dict_off)
                hdr = r.struct()
                comp = data[r.p:r.p + hdr[3]]
                raw = _decompress(comp, codec, hdr[2])
                dictionary = _decode_plain(raw, ptype, hdr[7][1])
                if dict_off < data_off:
                    pos = max(pos, data_off)
                else:                  # dictionary written first inline
                    pos = r.p + hdr[3]
            # a chunk may hold MANY data pages (foreign writers page at
            # ~1MB): decode until the chunk's value count is reached
            parts: List[np.ndarray] = []
            got = 0
            while got < num_values:
                r = _TR(data, pos)
                hdr = r.struct()
                comp = data[r.p:r.p + hdr[3]]
                pos = r.p + hdr[3]
                if hdr[1] == PAGE_DICTIONARY:
                    raw = _decompress(comp, codec, hdr[2])
                    dictionary = _decode_plain(raw, ptype, hdr[7][1])
                    continue
                raw = _decompress(comp, codec, hdr[2])
                dph = hdr[5]
                nvals = dph[1]
                enc = dph[2]
                if enc == ENC_PLAIN:
                    parts.append(_decode_plain(raw, ptype, nvals))
                elif enc in (ENC_PLAIN_DICTIONARY, 8):  # 8 = RLE_DICTIONARY
                    if dictionary is None:
                        raise ValueError(f"{name}: dictionary page missing")
                    bw = raw[0]
                    idx = _rle_bitpack_read(raw[1:], bw, nvals)
                    parts.append(dictionary[idx])
                else:
                    raise ValueError(f"{name}: unsupported encoding {enc}")
                got += nvals
            if got != num_values:
                raise ValueError(
                    f"{name}: decoded {got} values, chunk declares "
                    f"{num_values}")
            col = parts[0] if len(parts) == 1 else np.concatenate(parts)
            conv = convs.get(name)
            if conv == CONV_UINT_32:
                col = col.view(np.uint32)
            elif conv == CONV_UINT_64:
                col = col.view(np.uint64)
            cols[name] = col
        batch = RecordBatch({nm: cols[nm] for nm in names if nm in cols})
        if len(batch) != n_rows:
            raise ValueError(f"row group declares {n_rows} rows, decoded "
                             f"{len(batch)}")
        yield batch                    # schema order preserved
