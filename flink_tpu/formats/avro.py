"""Avro Object Container Files, pure Python — no fastavro/pyarrow needed.

Analog of the reference's ``flink-formats/flink-avro`` (``AvroInputFormat``/
``AvroWriterFactory``): reads and writes the Avro 1.11 object container
format (magic ``Obj\\x01``, file metadata map with embedded JSON schema,
sync-marker-delimited blocks) for RECORD schemas over the scalar types the
columnar runtime uses: null, boolean, int, long, float, double, string,
bytes, and nullable unions thereof.  Deflate codec supported (zlib);
snappy is not (not in the stdlib), matching the gated-dependency policy.

The columnar bridge mirrors the repo's other formats: ``read_avro`` yields
``RecordBatch``es; ``write_avro`` drains batches into one container file,
deriving the schema from the first batch's dtypes unless one is given.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from flink_tpu.core.batch import RecordBatch

_MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# primitive codecs (Avro binary encoding)
# ---------------------------------------------------------------------------

def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(buf: io.BytesIO, n: int) -> None:
    n = _zigzag_encode(int(n))
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def read_long(buf) -> int:
    shift = 0
    acc = 0
    while True:
        (b,) = buf.read(1)
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return _zigzag_decode(acc)
        shift += 7


def write_bytes(buf: io.BytesIO, data: bytes) -> None:
    write_long(buf, len(data))
    buf.write(data)


def read_bytes(buf) -> bytes:
    return buf.read(read_long(buf))


def write_string(buf: io.BytesIO, s: str) -> None:
    write_bytes(buf, s.encode("utf-8"))


def read_string(buf) -> str:
    return read_bytes(buf).decode("utf-8")


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

_DTYPE_TO_AVRO = [
    (np.bool_, "boolean"),
    (np.int32, "int"),
    (np.int64, "long"),
    (np.float32, "float"),
    (np.float64, "double"),
]

_AVRO_TO_DTYPE = {"boolean": np.bool_, "int": np.int32, "long": np.int64,
                  "float": np.float32, "double": np.float64,
                  "string": object, "bytes": object, "null": object}


def schema_for_columns(columns: Dict[str, np.ndarray],
                       name: str = "Record") -> Dict[str, Any]:
    """Derive a RECORD schema from a batch's column dtypes."""
    fields = []
    for cname, arr in columns.items():
        arr = np.asarray(arr)
        avro_t: Any = None
        for dt, t in _DTYPE_TO_AVRO:
            if arr.dtype == np.dtype(dt):
                avro_t = t
                break
        if avro_t is None and np.issubdtype(arr.dtype, np.integer):
            avro_t = "long"
        if avro_t is None and np.issubdtype(arr.dtype, np.floating):
            avro_t = "double"
        if avro_t is None:
            # object column: string, nullable when any None present
            has_none = any(v is None for v in arr.tolist())
            avro_t = ["null", "string"] if has_none else "string"
        fields.append({"name": cname, "type": avro_t})
    return {"type": "record", "name": name, "fields": fields}


def _field_type(t: Any) -> Tuple[str, bool]:
    """-> (base primitive, nullable)."""
    if isinstance(t, list):
        non_null = [x for x in t if x != "null"]
        if len(non_null) != 1:
            raise ValueError(f"unsupported union {t!r} (one non-null branch)")
        base, _ = _field_type(non_null[0])
        return base, True
    if isinstance(t, dict):
        return _field_type(t.get("type"))
    if t in _AVRO_TO_DTYPE:
        return t, False
    raise ValueError(f"unsupported Avro type {t!r} (scalar records only)")


# ---------------------------------------------------------------------------
# datum encoding
# ---------------------------------------------------------------------------

def _encode_value(buf: io.BytesIO, base: str, nullable: bool, v: Any) -> None:
    if nullable:
        if v is None or (isinstance(v, float) and np.isnan(v)
                         and base in ("string", "bytes")):
            write_long(buf, 0)   # union branch: null
            return
        write_long(buf, 1)
    elif v is None:
        # schema was derived non-nullable (e.g. from a first batch without
        # nulls): refusing beats silently writing the string "None"
        raise ValueError(
            "null value in a non-nullable Avro field — pass an explicit "
            "schema with a ['null', ...] union for this column")
    if base == "boolean":
        buf.write(b"\x01" if v else b"\x00")
    elif base in ("int", "long"):
        write_long(buf, int(v))
    elif base == "float":
        buf.write(struct.pack("<f", float(v)))
    elif base == "double":
        buf.write(struct.pack("<d", float(v)))
    elif base == "string":
        write_string(buf, str(v))
    elif base == "bytes":
        write_bytes(buf, bytes(v))
    elif base == "null":
        pass
    else:
        raise ValueError(f"unsupported type {base}")


def _decode_value(buf, base: str, nullable: bool) -> Any:
    if nullable:
        if read_long(buf) == 0:
            return None
    if base == "boolean":
        return buf.read(1) == b"\x01"
    if base in ("int", "long"):
        return read_long(buf)
    if base == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if base == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if base == "string":
        return read_string(buf)
    if base == "bytes":
        return read_bytes(buf)
    if base == "null":
        return None
    raise ValueError(f"unsupported type {base}")


# ---------------------------------------------------------------------------
# container file
# ---------------------------------------------------------------------------

def write_avro(batches: Iterable[RecordBatch], path: str,
               schema: Optional[Dict[str, Any]] = None,
               codec: str = "deflate") -> int:
    """Write batches into one Avro object container file; returns rows
    written.  ``codec``: 'null' or 'deflate'."""
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported codec {codec!r} (null/deflate)")
    sync = os.urandom(16)
    total = 0
    f = open(path, "wb")
    try:
        wrote_header = False
        fields: List[Tuple[str, str, bool]] = []
        for batch in batches:
            if len(batch) == 0:
                continue
            if not wrote_header:
                if schema is None:
                    schema = schema_for_columns(batch.columns)
                fields = [(fd["name"], *_field_type(fd["type"]))
                          for fd in schema["fields"]]
                hdr = io.BytesIO()
                hdr.write(_MAGIC)
                meta = {"avro.schema": json.dumps(schema).encode(),
                        "avro.codec": codec.encode()}
                write_long(hdr, len(meta))
                for k, v in meta.items():
                    write_string(hdr, k)
                    write_bytes(hdr, v)
                write_long(hdr, 0)  # end of metadata map
                hdr.write(sync)
                f.write(hdr.getvalue())
                wrote_header = True
            cols = {n: np.asarray(batch.columns[n]).tolist()
                    for n, _, _ in fields}
            blk = io.BytesIO()
            n_rows = len(batch)
            for i in range(n_rows):
                for name, base, nullable in fields:
                    _encode_value(blk, base, nullable, cols[name][i])
            payload = blk.getvalue()
            if codec == "deflate":
                payload = zlib.compress(payload)[2:-4]  # raw deflate
            out = io.BytesIO()
            write_long(out, n_rows)
            write_bytes(out, payload)
            out.write(sync)
            f.write(out.getvalue())
            total += n_rows
        if not wrote_header:
            # empty input: still a valid container (schema required)
            if schema is None:
                schema = {"type": "record", "name": "Record", "fields": []}
            hdr = io.BytesIO()
            hdr.write(_MAGIC)
            meta = {"avro.schema": json.dumps(schema).encode(),
                    "avro.codec": codec.encode()}
            write_long(hdr, len(meta))
            for k, v in meta.items():
                write_string(hdr, k)
                write_bytes(hdr, v)
            write_long(hdr, 0)
            hdr.write(sync)
            f.write(hdr.getvalue())
    finally:
        f.close()
    return total


def read_avro(path: str, batch_size: int = 8192):
    """Yield ``RecordBatch``es from an Avro object container file,
    streaming block by block (sync markers self-delimit blocks, so memory
    stays bounded by one block + the pending batch)."""
    with open(path, "rb") as f:
        if f.read(4) != _MAGIC:
            raise ValueError(f"{path}: not an Avro object container file")
        meta: Dict[str, bytes] = {}
        n = read_long(f)
        while n != 0:
            if n < 0:  # negative count: size precedes (spec allows)
                read_long(f)
                n = -n
            for _ in range(n):
                k = read_string(f)
                meta[k] = read_bytes(f)
            n = read_long(f)
        schema = json.loads(meta["avro.schema"].decode())
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("null", "deflate"):
            raise ValueError(f"unsupported codec {codec!r}")
        sync = f.read(16)
        fields = [(fd["name"], *_field_type(fd["type"]))
                  for fd in schema.get("fields", [])]

        pending: List[Dict[str, Any]] = []
        while True:
            head = f.read(1)
            if not head:
                break
            f.seek(-1, io.SEEK_CUR)
            n_rows = read_long(f)
            payload = read_bytes(f)
            if f.read(16) != sync:
                raise ValueError(
                    f"{path}: sync marker mismatch (corrupt block)")
            if codec == "deflate":
                payload = zlib.decompress(payload, wbits=-15)
            blk = io.BytesIO(payload)
            for _ in range(n_rows):
                row = {name: _decode_value(blk, base, nullable)
                       for name, base, nullable in fields}
                pending.append(row)
                if len(pending) >= batch_size:
                    yield _rows_to_batch(pending, fields)
                    pending = []
        if pending:
            yield _rows_to_batch(pending, fields)


def _rows_to_batch(rows: List[Dict[str, Any]],
                   fields: List[Tuple[str, str, bool]]) -> RecordBatch:
    cols: Dict[str, np.ndarray] = {}
    for name, base, nullable in fields:
        vals = [r[name] for r in rows]
        if nullable and any(v is None for v in vals):
            arr = np.empty(len(vals), object)
            arr[:] = vals
        else:
            arr = np.asarray(vals, dtype=_AVRO_TO_DTYPE.get(base, object))
        cols[name] = arr
    return RecordBatch(cols)
