"""Schema registry + Confluent Avro wire format.

Analog of ``flink-formats/flink-avro-confluent-registry``
(``ConfluentRegistryAvroDeserializationSchema`` /
``RegistryAvroSerializationSchema``): Kafka record values frame as
``magic 0x00 | int32 schema id (big endian) | Avro binary datum``, with
schemas registered in and fetched from a registry service.

``SchemaRegistryServer`` speaks the Confluent REST surface the
serializers need — ``POST /subjects/{s}/versions`` (deduplicating
identical schemas, enforcing BACKWARD compatibility),
``GET /schemas/ids/{id}``, ``GET /subjects/{s}/versions/latest``,
``GET /subjects`` — and ``AvroRegistrySerializer`` plugs into the Kafka
connector's ``value_encoder``/``value_decoder`` seams, so evolving
producers and old consumers interoperate through the registry the same
way the reference's schemas do.
"""

from __future__ import annotations

import io
import json
import struct
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.formats.avro import (_decode_value, _encode_value,
                                    _field_type)

MAGIC = 0


class SchemaRegistryError(Exception):
    pass


def _fields_of(schema: dict) -> List[Tuple[str, str, bool]]:
    return [(f["name"], *_field_type(f["type"]))
            for f in schema.get("fields", [])]


def _is_backward_compatible(new: dict, old: dict) -> Optional[str]:
    """BACKWARD: data written with ``old`` must be readable with ``new``.
    For the scalar-record subset: every old field must survive with the
    same base type (a non-null branch may widen to nullable), and fields
    NEW adds must be nullable (there is no default machinery here).
    Returns None when compatible, else the reason."""
    old_f = {n: (b, nul) for n, b, nul in _fields_of(old)}
    new_f = {n: (b, nul) for n, b, nul in _fields_of(new)}
    for name, (base, nullable) in old_f.items():
        got = new_f.get(name)
        if got is None:
            return f"field {name!r} removed"
        if got[0] != base:
            return (f"field {name!r} changed type "
                    f"{base} -> {got[0]}")
        if nullable and not got[1]:
            return f"field {name!r} narrowed from nullable"
    for name, (_base, nullable) in new_f.items():
        if name not in old_f and not nullable:
            return f"new field {name!r} must be nullable"
    return None


class SchemaRegistryServer:
    """Single-node Confluent-REST-shaped registry: global schema ids,
    per-subject version lists, BACKWARD compatibility on register."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._lock = threading.Lock()
        self._by_id: Dict[int, str] = {}          # id -> schema json text
        self._ids: Dict[str, int] = {}            # canonical text -> id
        self._subjects: Dict[str, List[int]] = {}  # subject -> version ids
        self._next = 1
        srv = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, code: int, body: dict) -> None:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type",
                                 "application/vnd.schemaregistry.v1+json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                # compute the response UNDER the lock, write it outside:
                # a stalled client socket must never block the registry
                parts = self.path.strip("/").split("/")
                code, body = 404, {"error_code": 404,
                                   "message": "not found"}
                with srv._lock:
                    if parts == ["subjects"]:
                        code, body = 200, sorted(srv._subjects)
                    elif (len(parts) == 3 and parts[0] == "schemas"
                            and parts[1] == "ids"):
                        text = srv._by_id.get(int(parts[2]))
                        if text is None:
                            code, body = 404, {
                                "error_code": 40403,
                                "message": "Schema not found"}
                        else:
                            code, body = 200, {"schema": text}
                    elif (len(parts) == 4 and parts[0] == "subjects"
                            and parts[2] == "versions"
                            and parts[3] == "latest"):
                        vers = srv._subjects.get(parts[1])
                        if not vers:
                            code, body = 404, {
                                "error_code": 40401,
                                "message": "Subject not found"}
                        else:
                            sid = vers[-1]
                            code, body = 200, {
                                "subject": parts[1], "version": len(vers),
                                "id": sid, "schema": srv._by_id[sid]}
                self._reply(code, body)

            def do_POST(self):  # noqa: N802
                parts = self.path.strip("/").split("/")
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                if not (len(parts) == 3 and parts[0] == "subjects"
                        and parts[2] == "versions"):
                    return self._reply(404, {"error_code": 404,
                                             "message": "not found"})
                subject = parts[1]
                try:
                    schema = json.loads(body["schema"])
                except (KeyError, ValueError):
                    return self._reply(422, {
                        "error_code": 42201,
                        "message": "Invalid schema"})
                canon = json.dumps(schema, sort_keys=True,
                                   separators=(",", ":"))
                code, resp = 200, {}
                with srv._lock:
                    vers = srv._subjects.setdefault(subject, [])
                    why = None
                    if vers:
                        latest = json.loads(srv._by_id[vers[-1]])
                        why = _is_backward_compatible(schema, latest)
                    if why is not None:
                        code, resp = 409, {
                            "error_code": 409,
                            "message": f"Schema being registered is "
                                       f"incompatible: {why}"}
                    else:
                        sid = srv._ids.get(canon)
                        if sid is None:
                            sid = srv._next
                            srv._next += 1
                            srv._ids[canon] = sid
                            srv._by_id[sid] = canon
                        if sid not in vers:
                            vers.append(sid)
                        resp = {"id": sid}
                return self._reply(code, resp)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()       # release the listening fd


class SchemaRegistryClient:
    """REST client with id- and text-level caches (the serializers call
    per record; only NEW schemas hit the wire)."""

    def __init__(self, url: str, timeout_s: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout_s = timeout_s
        self._by_id: Dict[int, dict] = {}
        self._ids: Dict[str, int] = {}

    def _call(self, method: str, path: str,
              body: Optional[dict] = None) -> Any:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.url + path, data=data,
                                     method=method)
        if data is not None:
            req.add_header("Content-Type",
                           "application/vnd.schemaregistry.v1+json")
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                err = json.loads(e.read() or b"{}")
            except ValueError:
                err = {}
            raise SchemaRegistryError(
                err.get("message", f"HTTP {e.code}")) from e
        except urllib.error.URLError as e:
            raise SchemaRegistryError(str(e.reason)) from e

    def register(self, subject: str, schema: dict) -> int:
        canon = json.dumps(schema, sort_keys=True, separators=(",", ":"))
        sid = self._ids.get(canon)
        if sid is None:
            sid = self._call("POST", f"/subjects/{subject}/versions",
                             {"schema": canon})["id"]
            self._ids[canon] = sid
            self._by_id[sid] = schema
        return sid

    def get_by_id(self, schema_id: int) -> dict:
        schema = self._by_id.get(schema_id)
        if schema is None:
            text = self._call("GET", f"/schemas/ids/{schema_id}")["schema"]
            schema = json.loads(text)
            self._by_id[schema_id] = schema
        return schema

    def latest(self, subject: str) -> Tuple[int, dict]:
        res = self._call("GET", f"/subjects/{subject}/versions/latest")
        return res["id"], json.loads(res["schema"])

    def subjects(self) -> List[str]:
        return self._call("GET", "/subjects")


class AvroRegistrySerializer:
    """Confluent wire format over the registry: rows encode as
    ``0x00 | schema id | Avro datum`` against a registered schema;
    decode reads ANY schema id (old producers keep working — the decoded
    row has that WRITER's fields, the consumer-side projection decides
    what to use)."""

    def __init__(self, registry_url: str, subject: str,
                 schema: Optional[dict] = None):
        self.client = SchemaRegistryClient(registry_url)
        self.subject = subject
        self._schema = schema
        self._schema_id: Optional[int] = None

    def _writer_schema(self, row: dict) -> Tuple[int, dict]:
        if self._schema is None:
            from flink_tpu.formats.avro import schema_for_columns
            import numpy as np
            if any(v is None for v in row.values()):
                # None gives no type to infer — guessing nullable-string
                # would silently stringify later numeric values
                raise SchemaRegistryError(
                    "cannot infer a schema from a row with null values; "
                    "pass an explicit schema= with ['null', <type>] "
                    "unions")
            self._schema = schema_for_columns(
                {k: np.asarray([v]) for k, v in row.items()},
                name=self.subject)
        if self._schema_id is None:
            self._schema_id = self.client.register(self.subject,
                                                   self._schema)
        return self._schema_id, self._schema

    def encode(self, row: dict) -> bytes:
        sid, schema = self._writer_schema(row)
        buf = io.BytesIO()
        buf.write(struct.pack(">bI", MAGIC, sid))
        for name, base, nullable in _fields_of(schema):
            _encode_value(buf, base, nullable, row.get(name))
        return buf.getvalue()

    def decode(self, payload: bytes) -> dict:
        if len(payload) < 5 or payload[0] != MAGIC:
            raise SchemaRegistryError(
                f"not Confluent wire format "
                f"(magic/len {payload[:5]!r})")
        (sid,) = struct.unpack_from(">I", payload, 1)
        schema = self.client.get_by_id(sid)
        buf = io.BytesIO(payload[5:])
        return {name: _decode_value(buf, base, nullable)
                for name, base, nullable in _fields_of(schema)}

    # Kafka connector seams
    def decoder(self):
        """``KafkaWireSource(value_decoder=...)`` hook."""
        return lambda value: [self.decode(value)]

    def encoder(self):
        """``KafkaWireSink(value_encoder=...)`` hook."""
        return self.encode
