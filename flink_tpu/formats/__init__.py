"""Record formats: CSV, JSON-lines, FTB binary, Avro, Parquet.

Analog of ``flink-formats/*`` (Avro/Parquet/ORC/CSV/JSON): encoders/decoders
between files and columnar ``RecordBatch``es.  Columnar-first: a format reads
a whole batch of rows into typed numpy columns (the batched-boundary pattern
the TPU runtime needs), never record-at-a-time objects.

FTB is the framework's own binary format (``flink_tpu/native/codec.py``):
length-prefixed compressed column blocks.  Avro (``formats/avro.py``) and
Parquet (``formats/parquet.py``) and ORC (``formats/orc.py``) are
implemented from their specs — no fastavro/pyarrow in this environment.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence

import numpy as np

from flink_tpu.core.batch import RecordBatch


def _coerce_columns(rows: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    """Rows -> typed columns: all-int values -> int64, all-bool -> bool,
    numeric-with-floats/None -> float64 (None becomes NaN), anything
    mixed -> object.  Column set = union over all rows.  int64 is only
    chosen when EVERY value is an integer — ``np.asarray([1.5], int64)``
    silently truncates, so a try-int-first ladder would corrupt float
    columns."""
    if not rows:
        return {}
    names: Dict[str, None] = {}
    for r in rows:
        for k in r:
            names.setdefault(k)
    cols: Dict[str, np.ndarray] = {}
    for name in names:
        vals = [r.get(name) for r in rows]
        if all(isinstance(v, bool) for v in vals):
            cols[name] = np.asarray(vals, bool)
            continue
        if all(isinstance(v, (int, np.integer))
               and not isinstance(v, bool) for v in vals):
            try:
                cols[name] = np.asarray(vals, np.int64)
                continue
            except OverflowError:
                pass                    # beyond int64: fall through
        try:
            cols[name] = np.asarray(vals, np.float64)
        except (ValueError, TypeError, OverflowError):
            cols[name] = np.asarray(vals, object)
    return cols


# ---------------------------------------------------------------------------
# CSV
# ---------------------------------------------------------------------------

def read_csv(path: str, batch_size: int = 8192, delimiter: str = ",",
             timestamp_column: Optional[str] = None,
             skip_rows: int = 0) -> Iterator[RecordBatch]:
    """CSV file -> RecordBatch iterator with type inference per batch.
    ``skip_rows`` skips *data* rows (resume position), not the header."""
    import csv as _csv

    with open(path, newline="") as f:
        reader = _csv.DictReader(f, delimiter=delimiter)
        buf: List[Dict[str, Any]] = []
        for i, row in enumerate(reader):
            if i < skip_rows:
                continue
            buf.append(row)
            if len(buf) >= batch_size:
                yield _batch_from_rows(buf, timestamp_column)
                buf = []
        if buf:
            yield _batch_from_rows(buf, timestamp_column)


def write_csv(batches, path: str, delimiter: str = ",") -> int:
    import csv as _csv

    n = 0
    writer = None
    with open(path, "w", newline="") as f:
        for b in batches:
            for row in b.to_rows():
                if writer is None:
                    writer = _csv.DictWriter(f, fieldnames=list(row.keys()),
                                             delimiter=delimiter)
                    writer.writeheader()
                writer.writerow(row)
                n += 1
    return n


# ---------------------------------------------------------------------------
# JSON lines
# ---------------------------------------------------------------------------

def read_jsonl(path: str, batch_size: int = 8192,
               timestamp_column: Optional[str] = None,
               skip_rows: int = 0) -> Iterator[RecordBatch]:
    with open(path) as f:
        buf: List[Dict[str, Any]] = []
        data_row = 0  # skip_rows counts DATA rows (matches reader positions)
        for line in f:
            if not line.strip():
                continue
            data_row += 1
            if data_row <= skip_rows:
                continue
            buf.append(json.loads(line))
            if len(buf) >= batch_size:
                yield _batch_from_rows(buf, timestamp_column)
                buf = []
        if buf:
            yield _batch_from_rows(buf, timestamp_column)


def write_jsonl(batches, path: str) -> int:
    n = 0
    with open(path, "w") as f:
        for b in batches:
            for row in b.to_rows():
                f.write(json.dumps(row, default=_json_default) + "\n")
                n += 1
    return n


def _json_default(o):
    from flink_tpu.connectors.util import json_default
    return json_default(o)


def _batch_from_rows(rows: List[Dict[str, Any]],
                     timestamp_column: Optional[str]) -> RecordBatch:
    cols = _coerce_columns(rows)
    ts = (np.asarray(cols[timestamp_column], np.int64)
          if timestamp_column and timestamp_column in cols else None)
    return RecordBatch(cols, timestamps=ts)


# ---------------------------------------------------------------------------
# FTB binary (length-prefixed encoded RecordBatches; CRC-checked frames)
# ---------------------------------------------------------------------------

_FRAME = struct.Struct("<II")  # payload_len, crc32


def write_frame(fileobj, payload: bytes) -> None:
    """One CRC-checked length-prefixed frame (shared by FTB files and the
    partitioned-log connector — single source of truth for the framing)."""
    from flink_tpu.native import crc32

    fileobj.write(_FRAME.pack(len(payload), crc32(payload)))
    fileobj.write(payload)


def read_frames(path: str, start_offset: int = 0):
    """Yield ``(payload, next_offset)`` per complete frame; stops cleanly at
    a torn tail write; raises on CRC mismatch."""
    from flink_tpu.native import crc32

    with open(path, "rb") as f:
        if start_offset:
            f.seek(start_offset)
        while True:
            hdr = f.read(_FRAME.size)
            if len(hdr) < _FRAME.size:
                return
            ln, crc = _FRAME.unpack(hdr)
            payload = f.read(ln)
            if len(payload) < ln:
                return  # torn tail write: stop at last complete frame
            if crc32(payload) != crc:
                raise IOError(f"frame CRC mismatch in {path}")
            yield payload, f.tell()


def iter_frames(data: bytes):
    """Yield frame payloads from an in-memory buffer (wire fetch bodies)."""
    from flink_tpu.native import crc32

    off = 0
    while off + _FRAME.size <= len(data):
        ln, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        if start + ln > len(data):
            return
        payload = data[start:start + ln]
        if crc32(payload) != crc:
            raise IOError("frame CRC mismatch in buffer")
        yield payload
        off = start + ln


def frame_span(data: bytes) -> int:
    """Byte length of the WHOLE frames at the head of ``data`` (a fetch
    response must never split a frame)."""
    off = 0
    while off + _FRAME.size <= len(data):
        ln, _ = _FRAME.unpack_from(data, off)
        if off + _FRAME.size + ln > len(data):
            break
        off += _FRAME.size + ln
    return off


def write_ftb(batches, path: str, compress: bool = True,
              append: bool = False) -> int:
    from flink_tpu.native.codec import encode_batch

    n = 0
    with open(path, "ab" if append else "wb") as f:
        for b in batches:
            write_frame(f, encode_batch(b, compress=compress))
            n += len(b)
    return n


def read_ftb(path: str, skip_batches: int = 0,
             start_offset: int = 0) -> Iterator[RecordBatch]:
    from flink_tpu.native.codec import decode_batch

    for i, (payload, _off) in enumerate(read_frames(path, start_offset)):
        if i >= skip_batches:
            yield decode_batch(payload)


def _read_avro(path: str, batch_size: int = 8192, **kw):
    from flink_tpu.formats.avro import read_avro
    return read_avro(path, batch_size=batch_size)


def _write_avro(batches, path: str, **kw) -> int:
    from flink_tpu.formats.avro import write_avro
    return write_avro(batches, path, **kw)


def _read_parquet(path: str, batch_size: int = 0, **kw):
    from flink_tpu.formats.parquet import read_parquet
    return read_parquet(path, batch_size=batch_size, **kw)


def _write_parquet(batches, path: str, **kw) -> int:
    from flink_tpu.formats.parquet import write_parquet
    return write_parquet(batches, path, **kw)


def _read_orc(path: str, batch_size: int = 0, **kw):
    from flink_tpu.formats.orc import read_orc
    return read_orc(path, batch_size=batch_size, **kw)


def _write_orc(batches, path: str, **kw) -> int:
    from flink_tpu.formats.orc import write_orc
    return write_orc(batches, path, **kw)


def _read_seq(path: str, batch_size: int = 8192, **kw):
    from flink_tpu.formats.sequencefile import read_sequencefile
    return read_sequencefile(path, batch_size=batch_size, **kw)


def _write_seq(batches, path: str, **kw) -> int:
    from flink_tpu.formats.sequencefile import write_sequencefile
    return write_sequencefile(batches, path, **kw)


FORMATS = {
    "csv": (read_csv, write_csv),
    "jsonl": (read_jsonl, write_jsonl),
    "ftb": (read_ftb, write_ftb),
    "avro": (_read_avro, _write_avro),
    "parquet": (_read_parquet, _write_parquet),
    "orc": (_read_orc, _write_orc),
    "seq": (_read_seq, _write_seq),
}


def reader_for(fmt: str):
    if fmt not in FORMATS:
        raise ValueError(f"unknown format {fmt!r}; have {sorted(FORMATS)}")
    return FORMATS[fmt][0]


def writer_for(fmt: str):
    if fmt not in FORMATS:
        raise ValueError(f"unknown format {fmt!r}; have {sorted(FORMATS)}")
    return FORMATS[fmt][1]
