"""RPC framework: single-threaded endpoints + gateway proxies.

Analog of the reference's Akka-based RPC (``runtime/rpc/akka/AkkaRpcService.java``,
``AkkaRpcActor.java``): every coordinator (Dispatcher, JobMaster,
ResourceManager, TaskExecutor) is an ``RpcEndpoint`` whose state is mutated
ONLY by its own main thread — calls from other components are marshalled into
the endpoint's mailbox and executed sequentially.  The single-thread invariant
is asserted at runtime exactly like ``MainThreadValidatorUtil.java``.

Transport is in-process (MiniCluster mode, the reference's shared
``AkkaRpcService`` inside ``MiniCluster.java:271``): a gateway is a dynamic
proxy posting closures to the target endpoint's mailbox and returning
``concurrent.futures.Future``s.  Multi-host deployments put a gRPC/TCP bridge
behind the same ``RpcService.connect`` seam (SURVEY §5.8 control plane).
"""

from __future__ import annotations

import queue
import threading
import traceback
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional

from flink_tpu.testing import chaos


class RpcTimeout(Exception):
    pass


class RpcEndpoint:
    """Base endpoint: owns a mailbox thread; subclasses implement rpc methods
    as plain methods and MUST only touch state from the main thread."""

    def __init__(self, name: str):
        self.name = name
        self._mailbox: "queue.Queue[Optional[Callable]]" = queue.Queue()
        self._main_thread: Optional[threading.Thread] = None
        self._running = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._main_thread = threading.Thread(
            target=self._run_mailbox, name=f"rpc-{self.name}", daemon=True)
        self._main_thread.start()
        self.run_async(self.on_start)

    def stop(self) -> None:
        if not self._running:
            return
        def _shutdown():
            self.on_stop()
            self._running = False
        self._mailbox.put(_shutdown)
        self._mailbox.put(None)  # poison

    def on_start(self) -> None:
        pass

    def on_stop(self) -> None:
        pass

    def _run_mailbox(self) -> None:
        while True:
            item = self._mailbox.get()
            if item is None:
                return
            try:
                item()
            except Exception:
                traceback.print_exc()

    # -- main-thread discipline ---------------------------------------------
    def validate_runs_in_main_thread(self) -> None:
        """``MainThreadValidatorUtil.isRunningInExpectedThread`` analog."""
        assert threading.current_thread() is self._main_thread, (
            f"endpoint {self.name}: state touched from "
            f"{threading.current_thread().name}, not the endpoint main thread")

    def run_async(self, fn: Callable, *args) -> None:
        """Post a closure to the mailbox (``runAsync`` analog)."""
        self._mailbox.put(lambda: fn(*args))

    def call_async(self, fn: Callable, *args) -> Future:
        """Post and return a Future of the result (``callAsync`` analog)."""
        fut: Future = Future()

        def run():
            try:
                fut.set_result(fn(*args))
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        self._mailbox.put(run)
        return fut


class Gateway:
    """Dynamic proxy: attribute access returns a callable that executes the
    endpoint method on the endpoint's main thread and returns a Future
    (``AkkaInvocationHandler`` analog)."""

    def __init__(self, endpoint: RpcEndpoint):
        object.__setattr__(self, "_endpoint", endpoint)

    def __getattr__(self, item: str):
        ep = object.__getattribute__(self, "_endpoint")
        method = getattr(ep, item)
        if not callable(method):
            raise AttributeError(item)

        def call(*args, **kwargs) -> Future:
            # fault point: a dropped RPC never reaches the mailbox — the
            # caller's future stays pending (timeout at await_future), the
            # lost-message model; fail schedules raise synchronously
            if not chaos.fire("rpc.call", endpoint=ep.name, method=item):
                return Future()
            return ep.call_async(lambda: method(*args, **kwargs))

        return call

    @property
    def address(self) -> str:
        return object.__getattribute__(self, "_endpoint").name


class RpcService:
    """Endpoint registry + connection factory (``AkkaRpcService`` analog)."""

    def __init__(self):
        self._endpoints: Dict[str, RpcEndpoint] = {}
        self._lock = threading.Lock()

    def start_endpoint(self, endpoint: RpcEndpoint) -> Gateway:
        with self._lock:
            self._endpoints[endpoint.name] = endpoint
        endpoint.start()
        return Gateway(endpoint)

    def connect(self, address: str) -> Gateway:
        with self._lock:
            ep = self._endpoints.get(address)
        if ep is None or not ep._running:
            raise ConnectionError(f"no endpoint at {address!r}")
        return Gateway(ep)

    def stop_endpoint(self, address: str) -> None:
        with self._lock:
            ep = self._endpoints.pop(address, None)
        if ep is not None:
            ep.stop()

    def stop(self) -> None:
        with self._lock:
            eps = list(self._endpoints.values())
            self._endpoints.clear()
        for ep in eps:
            ep.stop()


def await_future(fut: Future, timeout_s: float = 30.0):
    """Block on an RPC future (client-side convenience)."""
    try:
        return fut.result(timeout=timeout_s)
    except TimeoutError as e:
        raise RpcTimeout(f"rpc did not complete within {timeout_s}s") from e
