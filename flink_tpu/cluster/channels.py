"""Data-plane channels between subtasks, with credit-style backpressure.

Analog of the reference's network stack (``ResultPartition``/``InputGate``
over Netty with credit-based flow control, SURVEY §2.2 "Network stack"):
in-process exchanges are bounded queues — a full queue blocks the producer,
which is exactly the reference's credit-exhaustion backpressure, while
barrier alignment *stops polling* a blocked channel so its data queues up
behind the barrier (``SingleCheckpointBarrierHandler`` semantics: blocked
channels buffer, they don't drop).

Partitioners mirror ``runtime/partitioner/``: forward, hash (key groups →
operator index, the exact ``KeyGroupStreamPartitioner`` formula), rebalance
(round-robin), broadcast.  Control elements (watermarks, barriers, end of
input) always go to every target channel, like the reference's
``RecordWriter.broadcastEvent``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.core import keygroups
from flink_tpu.core.batch import (CheckpointBarrier, EndOfInput, RecordBatch,
                                  StreamElement)
from flink_tpu.testing import chaos


def take_until_barrier_locked(q: deque, announced: deque,
                              checkpoint_id: int):
    """Shared barrier-extraction loop (the caller holds the queue's lock):
    pop the elements queued IN FRONT of checkpoint ``checkpoint_id``'s
    barrier; consume the barrier itself when present (returning the
    ELEMENT — its ``is_savepoint`` flag matters) and keep the announced
    deque in sync.  Stops at any barrier or EndOfInput, never extracting
    past a channel-terminating event.  One implementation for BOTH channel
    flavors (``LocalChannel`` and ``net._ReceiveQueue``) so the
    stop/announce invariants cannot silently diverge."""
    out = []
    barrier = None
    while q:
        el = q[0]
        if isinstance(el, CheckpointBarrier):
            if el.checkpoint_id == checkpoint_id:
                barrier = q.popleft()
                if announced:
                    announced.popleft()
            break
        if isinstance(el, EndOfInput):
            break
        out.append(q.popleft())
    return out, barrier


def element_bytes(el: StreamElement) -> int:
    """Approximate wire size of one stream element (RecordBatch column
    nbytes; control elements a small constant) — the unit the unaligned
    checkpoint accounting (overtaken / persisted in-flight bytes) and the
    backpressure gauges report in."""
    if isinstance(el, RecordBatch):
        total = 0
        for name in el.columns:
            col = el.column(name)
            nbytes = getattr(col, "nbytes", None)
            total += int(nbytes) if nbytes is not None else 8 * len(el)
        return max(total, 16)
    return 16


class LocalChannel:
    """Bounded in-memory channel (one producer subtask → one consumer
    subtask).  ``capacity`` plays the role of the channel's credit budget.

    Observability: ``backpressured_ns`` accumulates the time producers
    spend blocked in :meth:`put` waiting for credit (the reference's
    per-channel ``backPressuredTimeMsPerSecond``), and :meth:`depth` /
    :meth:`queued_bytes` read the current backlog — both monitoring-grade
    (one lock acquisition, no barriers)."""

    def __init__(self, capacity: int = 32, name: str = ""):
        self.capacity = capacity
        self.name = name
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._closed = False
        #: producer time spent waiting for credit (backpressured)
        self.backpressured_ns = 0
        #: checkpoint ids of barriers currently QUEUED (oldest first) — the
        #: priority-event announcement of the reference: the consumer's
        #: barrier handler learns a barrier arrived without draining the
        #: backlog in front of it
        self._announced: deque = deque()

    def put(self, el: StreamElement, timeout_s: Optional[float] = None) -> bool:
        # fault point: a partitioned link stalls (bytes neither flow nor
        # error — FreezableProxy semantics); fail/delay schedules raise/slow.
        # Fired ONCE per put — while dropped, poll blocked() so the firing
        # counter/history stay deterministic regardless of stall duration
        if not chaos.fire("channel.send", channel=self.name):
            deadline = (None if timeout_s is None
                        else time.monotonic() + timeout_s)
            while chaos.blocked("channel.send"):
                if self._closed:
                    return False
                if deadline is not None and time.monotonic() >= deadline:
                    return False
                time.sleep(0.01)
        with self._not_full:
            if len(self._q) >= self.capacity and not self._closed:
                t0 = time.monotonic_ns()
                while len(self._q) >= self.capacity and not self._closed:
                    if not self._not_full.wait(timeout=timeout_s):
                        self.backpressured_ns += time.monotonic_ns() - t0
                        return False
                self.backpressured_ns += time.monotonic_ns() - t0
            if self._closed:
                return False
            self._q.append(el)
            if isinstance(el, CheckpointBarrier):
                self._announced.append(el.checkpoint_id)
            self._not_empty.notify()
            return True

    def poll(self, timeout_s: float = 0.0) -> Optional[StreamElement]:
        with self._not_empty:
            if not self._q and timeout_s > 0:
                self._not_empty.wait(timeout=timeout_s)
            if not self._q:
                return None
            el = self._q.popleft()
            if isinstance(el, CheckpointBarrier) and self._announced:
                self._announced.popleft()
            self._not_full.notify()
        # fault point: a SLOW CONSUMER drains this channel with bursty
        # stalls (chaos.SlowConsumer).  Outside the lock — a stalled
        # consumer must not also block the producer's put
        chaos.fire("channel.recv", channel=self.name)
        return el

    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def queued_bytes(self) -> int:
        with self._lock:
            return sum(element_bytes(el) for el in self._q)

    def announced_barrier(self) -> Optional[int]:
        """Oldest checkpoint barrier currently queued (or None): the
        consumer's barrier handler reads this to react to a barrier ON
        ARRIVAL instead of after draining the backlog in front of it."""
        with self._lock:
            return self._announced[0] if self._announced else None

    def take_until_barrier(self, checkpoint_id: int):
        """Barrier overtake (unaligned checkpoints): atomically extract the
        queued elements IN FRONT of checkpoint ``checkpoint_id``'s barrier
        — the in-flight data the barrier jumps over.  Returns
        ``(elements, barrier)`` where ``barrier`` is the consumed barrier
        ELEMENT (its ``is_savepoint`` flag matters to the caller) or None
        when it was not queued.  Extraction stops at any barrier or
        EndOfInput; it never reaches past a channel-terminating event.
        Bypasses :meth:`poll` (and its slow-consumer fault point) by
        design: persisting in-flight data must not be throttled by the
        very backpressure it escapes."""
        with self._not_full:
            out, barrier = take_until_barrier_locked(
                self._q, self._announced, checkpoint_id)
            if out or barrier is not None:
                self._not_full.notify_all()
        return out, barrier

    def close(self) -> None:
        """Unblock producers/consumers (used on cancel/teardown)."""
        with self._lock:
            self._closed = True
            self._not_full.notify_all()
            self._not_empty.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)


class OutputDispatcher:
    """Routes one subtask's emissions to target channels per edge semantics
    (``RecordWriter`` + ``StreamPartitioner`` analog)."""

    def __init__(self, partitioning: str, channels: Sequence[LocalChannel],
                 max_parallelism: int = 128, subtask_index: int = 0,
                 key_column: Optional[str] = None):
        self.partitioning = partitioning
        self.channels = list(channels)
        self.max_parallelism = max_parallelism
        self.key_column = key_column  # hash edges key on this column
        self._rr = subtask_index  # stagger round-robin starts across producers

    def emit(self, el: StreamElement) -> None:
        n = len(self.channels)
        if n == 0:
            return
        if not isinstance(el, RecordBatch):
            from flink_tpu.core.batch import TaggedBatch
            if isinstance(el, TaggedBatch):
                # side-output DATA: route to one consumer (round-robin), not
                # the control-broadcast path — broadcasting would duplicate
                # side-output rows x parallelism
                self.channels[self._rr % n].put(el)
                self._rr += 1
                return
            for ch in self.channels:   # broadcast control elements
                ch.put(el)
            return
        if len(el) == 0:
            return
        if n == 1:
            self.channels[0].put(el)
        elif self.partitioning == "hash":
            self._emit_hash(el)
        elif self.partitioning == "broadcast":
            for ch in self.channels:
                ch.put(el)
        elif self.partitioning == "global":
            self.channels[0].put(el)   # everything to subtask 0
        elif self.partitioning in ("rebalance", "rescale", "shuffle"):
            self.channels[self._rr % n].put(el)
            self._rr += 1
        else:  # forward with n>1 targets is a wiring bug
            raise ValueError(
                f"forward edge cannot fan out to {n} channels")

    def _emit_hash(self, batch: RecordBatch) -> None:
        kg = batch.key_groups
        if kg is None and self.key_column is not None:
            # the keying operator lives at the consumer chain head; the
            # producer-side partitioner derives key groups from the key
            # column itself (KeyGroupStreamPartitioner's key selector)
            keys = np.asarray(batch.column(self.key_column))
            kg = keygroups.assign_to_key_group(keygroups.hash_keys(keys),
                                               self.max_parallelism)
        if kg is None:
            raise ValueError("hash edge requires key_groups on the batch "
                             "(key_by upstream)")
        n = len(self.channels)
        # KeyGroupRangeAssignment.computeOperatorIndexForKeyGroup
        target = (np.asarray(kg, np.int64) * n) // self.max_parallelism
        for t in range(n):
            sel = target == t
            if sel.any():
                self.channels[t].put(batch.select(sel))
