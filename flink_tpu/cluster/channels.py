"""Data-plane channels between subtasks, with credit-style backpressure.

Analog of the reference's network stack (``ResultPartition``/``InputGate``
over Netty with credit-based flow control, SURVEY §2.2 "Network stack"):
in-process exchanges are bounded queues — a full queue blocks the producer,
which is exactly the reference's credit-exhaustion backpressure, while
barrier alignment *stops polling* a blocked channel so its data queues up
behind the barrier (``SingleCheckpointBarrierHandler`` semantics: blocked
channels buffer, they don't drop).

Partitioners mirror ``runtime/partitioner/``: forward, hash (key groups →
operator index, the exact ``KeyGroupStreamPartitioner`` formula), rebalance
(round-robin), broadcast.  Control elements (watermarks, barriers, end of
input) always go to every target channel, like the reference's
``RecordWriter.broadcastEvent``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.core import keygroups
from flink_tpu.core.batch import RecordBatch, StreamElement
from flink_tpu.testing import chaos


class LocalChannel:
    """Bounded in-memory channel (one producer subtask → one consumer
    subtask).  ``capacity`` plays the role of the channel's credit budget."""

    def __init__(self, capacity: int = 32, name: str = ""):
        self.capacity = capacity
        self.name = name
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._closed = False

    def put(self, el: StreamElement, timeout_s: Optional[float] = None) -> bool:
        # fault point: a partitioned link stalls (bytes neither flow nor
        # error — FreezableProxy semantics); fail/delay schedules raise/slow.
        # Fired ONCE per put — while dropped, poll blocked() so the firing
        # counter/history stay deterministic regardless of stall duration
        if not chaos.fire("channel.send", channel=self.name):
            deadline = (None if timeout_s is None
                        else time.monotonic() + timeout_s)
            while chaos.blocked("channel.send"):
                if self._closed:
                    return False
                if deadline is not None and time.monotonic() >= deadline:
                    return False
                time.sleep(0.01)
        with self._not_full:
            while len(self._q) >= self.capacity and not self._closed:
                if not self._not_full.wait(timeout=timeout_s):
                    return False
            if self._closed:
                return False
            self._q.append(el)
            self._not_empty.notify()
            return True

    def poll(self, timeout_s: float = 0.0) -> Optional[StreamElement]:
        with self._not_empty:
            if not self._q and timeout_s > 0:
                self._not_empty.wait(timeout=timeout_s)
            if not self._q:
                return None
            el = self._q.popleft()
            self._not_full.notify()
            return el

    def close(self) -> None:
        """Unblock producers/consumers (used on cancel/teardown)."""
        with self._lock:
            self._closed = True
            self._not_full.notify_all()
            self._not_empty.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)


class OutputDispatcher:
    """Routes one subtask's emissions to target channels per edge semantics
    (``RecordWriter`` + ``StreamPartitioner`` analog)."""

    def __init__(self, partitioning: str, channels: Sequence[LocalChannel],
                 max_parallelism: int = 128, subtask_index: int = 0,
                 key_column: Optional[str] = None):
        self.partitioning = partitioning
        self.channels = list(channels)
        self.max_parallelism = max_parallelism
        self.key_column = key_column  # hash edges key on this column
        self._rr = subtask_index  # stagger round-robin starts across producers

    def emit(self, el: StreamElement) -> None:
        n = len(self.channels)
        if n == 0:
            return
        if not isinstance(el, RecordBatch):
            from flink_tpu.core.batch import TaggedBatch
            if isinstance(el, TaggedBatch):
                # side-output DATA: route to one consumer (round-robin), not
                # the control-broadcast path — broadcasting would duplicate
                # side-output rows x parallelism
                self.channels[self._rr % n].put(el)
                self._rr += 1
                return
            for ch in self.channels:   # broadcast control elements
                ch.put(el)
            return
        if len(el) == 0:
            return
        if n == 1:
            self.channels[0].put(el)
        elif self.partitioning == "hash":
            self._emit_hash(el)
        elif self.partitioning == "broadcast":
            for ch in self.channels:
                ch.put(el)
        elif self.partitioning == "global":
            self.channels[0].put(el)   # everything to subtask 0
        elif self.partitioning in ("rebalance", "rescale", "shuffle"):
            self.channels[self._rr % n].put(el)
            self._rr += 1
        else:  # forward with n>1 targets is a wiring bug
            raise ValueError(
                f"forward edge cannot fan out to {n} channels")

    def _emit_hash(self, batch: RecordBatch) -> None:
        kg = batch.key_groups
        if kg is None and self.key_column is not None:
            # the keying operator lives at the consumer chain head; the
            # producer-side partitioner derives key groups from the key
            # column itself (KeyGroupStreamPartitioner's key selector)
            keys = np.asarray(batch.column(self.key_column))
            kg = keygroups.assign_to_key_group(keygroups.hash_keys(keys),
                                               self.max_parallelism)
        if kg is None:
            raise ValueError("hash edge requires key_groups on the batch "
                             "(key_by upstream)")
        n = len(self.channels)
        # KeyGroupRangeAssignment.computeOperatorIndexForKeyGroup
        target = (np.asarray(kg, np.int64) * n) // self.max_parallelism
        for t in range(n):
            sel = target == t
            if sel.any():
                self.channels[t].put(batch.select(sel))
