"""High availability: leader election + durable job metadata.

Analog of the reference's HA services (``runtime/highavailability/``:
ZooKeeper/K8s leader election via ``ZooKeeperLeaderElectionDriver`` +
``DefaultLeaderElectionService``, job-graph and checkpoint-pointer
persistence).  No quorum service exists in this environment, so leadership
is a **file lease**: the leader holds a lock file with a heartbeat
timestamp; contenders campaign by atomically creating it (O_EXCL) or taking
over once the lease expires.  Same contract as the reference: at most one
leader per election path, leadership revocable, listeners notified.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional


class FileLeaderElection:
    """flock-based leader election (one election per ``path``).

    Leadership = holding an exclusive ``flock`` on the lock file: the kernel
    guarantees a single holder, and releases the lock automatically when the
    holder's fd closes (crash included) — strictly stronger than a timestamp
    lease, which has a dual-leader window between expiry checks.  The
    ``lease_ms`` parameter is kept for API compatibility (it bounds nothing
    under flock; takeover latency is one ``renew_ms`` poll).
    """

    def __init__(self, path: str, contender_id: Optional[str] = None,
                 lease_ms: int = 1000, renew_ms: int = 200):
        self.path = path
        self.contender_id = contender_id or uuid.uuid4().hex[:12]
        self.lease_ms = lease_ms
        self.renew_ms = renew_ms
        self.is_leader = False
        self._listeners: List[Callable[[bool], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fd: Optional[int] = None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def add_listener(self, fn: Callable[[bool], None]) -> None:
        self._listeners.append(fn)

    def _notify(self, leading: bool) -> None:
        if leading != self.is_leader:
            self.is_leader = leading
            for fn in self._listeners:
                fn(leading)

    def _campaign_once(self) -> bool:
        import fcntl

        if self._fd is not None:
            # still holding the lock; refresh the observability heartbeat
            try:
                os.lseek(self._fd, 0, os.SEEK_SET)
                os.truncate(self._fd, 0)
                os.write(self._fd, json.dumps(
                    {"leader": self.contender_id, "ts": time.time()}).encode())
            except OSError:
                pass
            return True
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        self._fd = fd
        return True

    def start(self) -> "FileLeaderElection":
        def run():
            while not self._stop.is_set():
                try:
                    self._notify(self._campaign_once())
                except OSError:
                    self._notify(False)
                self._stop.wait(self.renew_ms / 1000.0)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"leader-{self.contender_id}")
        self._thread.start()
        return self

    def stop(self, abdicate: bool = True) -> None:
        """``abdicate`` releases the lock (clean handover); either way the
        kernel would release it when the process/fd dies."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._fd is not None:
            import fcntl

            try:
                if abdicate:
                    fcntl.flock(self._fd, fcntl.LOCK_UN)
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
        self._notify(False)


class LeaseLeaderElection:
    """CROSS-HOST leader election over the object-store lease service
    (``runtime/checkpoint/objectstore.py``) — the ZooKeeper/etcd analog the
    file lease cannot provide: any number of contenders on any machines
    campaign through one shared service; at most one holds the TTL lease;
    the **fencing token** (monotone per grant) lets downstream stores
    reject a deposed leader's stale writes (the classic split-brain guard).

    Same interface as :class:`FileLeaderElection`: ``start``/``stop``,
    ``is_leader``, ``add_listener(fn(bool))``; plus ``fencing_token``.
    k8s deployment: point every coordinator pod at the same objectstore
    Service and gate job submission on leadership."""

    def __init__(self, url: str, election: str = "coordinator",
                 contender_id: Optional[str] = None,
                 lease_ms: int = 2000, renew_ms: int = 500):
        from flink_tpu.runtime.checkpoint.objectstore import ObjectStoreClient

        self.client = ObjectStoreClient(url)
        self.election = election
        self.contender_id = contender_id or uuid.uuid4().hex[:12]
        self.lease_ms = lease_ms
        self.renew_ms = renew_ms
        self.is_leader = False
        self.fencing_token: Optional[int] = None
        self._listeners: List[Callable[[bool], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_listener(self, fn: Callable[[bool], None]) -> None:
        self._listeners.append(fn)

    def _notify(self, leading: bool) -> None:
        if leading != self.is_leader:
            self.is_leader = leading
            for fn in self._listeners:
                fn(leading)

    def _post(self, verb: str, body: Dict[str, Any]) -> Dict[str, Any]:
        with self.client._req("POST", f"/lease/{self.election}/{verb}",
                              json.dumps(body).encode()) as r:
            return json.loads(r.read())

    def _campaign_once(self) -> bool:
        # ANY transport/parse failure means "cannot prove leadership":
        # urllib raises http.client exceptions and ValueError besides
        # OSError, and an uncaught one would kill the campaign thread with
        # is_leader frozen True — the exact split-brain this class prevents
        try:
            if self.fencing_token is not None:
                res = self._post("renew", {"holder": self.contender_id,
                                           "token": self.fencing_token,
                                           "ttl_ms": self.lease_ms})
                if res.get("renewed"):
                    return True
                self.fencing_token = None  # lease lost: must re-acquire
            res = self._post("acquire", {"holder": self.contender_id,
                                         "ttl_ms": self.lease_ms})
            if res.get("acquired"):
                self.fencing_token = int(res["token"])
                return True
            return False
        except Exception:  # noqa: BLE001 — fail toward "not leader"
            self.fencing_token = None
            return False

    def start(self) -> "LeaseLeaderElection":
        def run():
            while not self._stop.is_set():
                leading = self._campaign_once()
                if self._stop.is_set():
                    break  # stop() already notified False: never overwrite
                self._notify(leading)
                self._stop.wait(self.renew_ms / 1000.0)

        self._thread = threading.Thread(
            target=run, daemon=True, name=f"lease-leader-{self.contender_id}")
        self._thread.start()
        return self

    def stop(self, abdicate: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            # outwait a campaign blocked in the HTTP round-trip: the run
            # loop re-checks _stop after _campaign_once, so once joined no
            # further _notify can race this one
            self._thread.join(timeout=self.client.timeout_s + 5)
        if abdicate and self.fencing_token is not None:
            try:
                self._post("release", {"holder": self.contender_id,
                                       "token": self.fencing_token})
            except Exception:  # noqa: BLE001
                pass
        self.fencing_token = None
        self._notify(False)


class HaServices:
    """Durable job metadata (``JobGraphStore`` + ``CompletedCheckpointStore``
    pointer analog): the NEW leader reads what the old one persisted."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _p(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def persist_job(self, job_id: str, payload: Dict[str, Any]) -> None:
        import pickle
        tmp = self._p(f"job-{job_id}.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(payload, f)
        os.replace(tmp, self._p(f"job-{job_id}.pkl"))

    def load_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        import pickle
        try:
            with open(self._p(f"job-{job_id}.pkl"), "rb") as f:
                return pickle.load(f)
        except FileNotFoundError:
            return None

    def job_ids(self) -> List[str]:
        return sorted(f[4:-4] for f in os.listdir(self.directory)
                      if f.startswith("job-") and f.endswith(".pkl"))

    def remove_job(self, job_id: str) -> None:
        try:
            os.remove(self._p(f"job-{job_id}.pkl"))
        except FileNotFoundError:
            pass

    def set_latest_checkpoint(self, job_id: str, checkpoint_id: int) -> None:
        tmp = self._p(f"ckpt-{job_id}.tmp")
        with open(tmp, "w") as f:
            json.dump({"checkpoint_id": checkpoint_id}, f)
        os.replace(tmp, self._p(f"ckpt-{job_id}.json"))

    def latest_checkpoint(self, job_id: str) -> Optional[int]:
        try:
            with open(self._p(f"ckpt-{job_id}.json")) as f:
                return json.load(f)["checkpoint_id"]
        except (FileNotFoundError, ValueError, KeyError):
            return None
