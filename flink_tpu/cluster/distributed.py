"""Cross-process cluster: coordinator + TaskExecutor worker processes.

The multi-process analog of the reference's Dispatcher/JobMaster ↔
TaskExecutor deployment (``Execution.deploy`` →
``TaskExecutor.submitTask:554`` over RPC): a :class:`ProcessCluster`
coordinator spawns N worker processes, each hosting a deterministic slice of
the job's subtasks.  Data-plane edges whose endpoints live in different
processes ride the TCP credit-controlled channels of ``cluster/net.py`` (the
Netty-shuffle analog); same-process edges stay in-memory ``LocalChannel``s —
exactly the reference's local-vs-remote input channel split
(``LocalInputChannel`` / ``RemoteInputChannel``).

**Job shipping** follows the jar model (BLOB service analog): the job is a
``module:function`` reference returning a ``StreamExecutionEnvironment`` (or
``ExecutionPlan``); every process imports it and rebuilds the SAME plan, then
instantiates only its assigned subtasks.  This requires the builder to be
deterministic (source split creation included) — the same property a
reference job jar must have for task deployment to be consistent.

**Control plane** is a length-prefixed pickle protocol over one TCP
connection per worker (the Akka RPC analog, single coordinator thread per
worker connection):

  worker → coordinator: ``hello`` (data-plane address), ``state`` (task
  transitions), ``ack`` (checkpoint snapshots), ``final`` (FLIP-147 final
  snapshots of finished tasks), ``rows`` (collect-sink results),
  ``worker_done``
  coordinator → worker: ``deploy`` (address map + restore), ``checkpoint``
  (source barrier injection, ``CheckpointCoordinator.triggerCheckpoint``
  analog), ``notify`` (checkpoint complete), ``stop``

Checkpoints run the same protocol as the in-process MiniCluster: the
coordinator triggers sources, barriers flow in-band through local AND remote
channels, every subtask acks with its snapshot, and the coordinator
assembles + stores the completed checkpoint (restorable at a different
worker count — the assignment is re-computed, state is per-subtask).
"""

from __future__ import annotations

import importlib
import json
import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_LEN = struct.Struct("<I")

#: handshake frames must never exceed this — a pre-auth peer cannot make
#: the coordinator buffer arbitrary amounts
_MAX_HANDSHAKE = 4096

#: coordinator HA (ISSUE-20): every coordinator→worker control message
#: carries the leader epoch as its LAST element; this table maps each
#: message kind to its base arity so workers can pop the epoch off
#: regardless of the kind's own optional fields.  Epoch 0 = HA off.
_MSG_ARITY = {"deploy": 6, "checkpoint": 2, "notify": 2, "split_assign": 5,
              "reset": 1, "reset_tasks": 2, "trace_request": 1,
              "cancel": 1, "stop": 1, "ping": 1}

#: leader epochs partition the checkpoint-id space: epoch e's coordinator
#: numbers its checkpoints from ``(e-1) * stride + 1``, so a zombie
#: ex-leader racing the new leader into a SHARED checkpoint directory can
#: never collide with (or overwrite) the new incarnation's cuts — the
#: cross-incarnation id fencing PR-14's autoscaler introduced, scaled to
#: leader changes
_CID_EPOCH_STRIDE = 1_000_000


def _recv_raw(sock: socket.socket, limit: Optional[int] = None
              ) -> Optional[bytes]:
    buf = b""
    while len(buf) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(buf))
        if not chunk:
            return None
        buf += chunk
    (n,) = _LEN.unpack(buf)
    if limit is not None and n > limit:
        return None
    data = b""
    while len(data) < n:
        chunk = sock.recv(min(1 << 20, n - len(data)))
        if not chunk:
            return None
        data += chunk
    return data


def _send_msg(sock: socket.socket, obj: Any, lock: threading.Lock) -> None:
    data = pickle.dumps(obj)
    with lock:
        sock.sendall(_LEN.pack(len(data)) + data)


def _recv_msg(sock: socket.socket) -> Optional[Any]:
    """Post-handshake control message (pickle).  Only ever called on a
    connection whose peer already passed the JSON hello/challenge exchange
    (and its HMAC, when the cluster has a token) — an unauthenticated peer
    never reaches a ``pickle.loads``."""
    data = _recv_raw(sock)
    return None if data is None else pickle.loads(data)


def _send_json(sock: socket.socket, obj: Any, lock: threading.Lock) -> None:
    """Handshake frame: length-prefixed JSON — non-executable by design, so
    both ends can parse the peer's FIRST message before trusting it."""
    data = json.dumps(obj).encode()
    with lock:
        sock.sendall(_LEN.pack(len(data)) + data)


def _recv_json(sock: socket.socket) -> Optional[Any]:
    data = _recv_raw(sock, limit=_MAX_HANDSHAKE)
    if data is None:
        return None
    try:
        return json.loads(data)
    except (ValueError, UnicodeDecodeError):
        return None


def _require_secure_bind(bind_host: str, security, role: str) -> None:
    """Shared bind policy (``cluster.net.require_secure_bind``) applied to a
    :class:`SecurityConfig`."""
    from flink_tpu.cluster.net import require_secure_bind

    has_tls = security is not None and security.internal_ssl
    require_secure_bind(bind_host, has_tls, role)


def build_plan(job: str):
    """``module:function`` → ExecutionPlan (the jar-main analog)."""
    mod_name, fn_name = job.rsplit(":", 1)
    obj = getattr(importlib.import_module(mod_name), fn_name)()
    if hasattr(obj, "to_plan"):
        return obj.to_plan()
    if hasattr(obj, "get_stream_graph"):
        return obj.get_stream_graph(job).to_plan()
    return obj  # already an ExecutionPlan


def plan_structure_digest(plan) -> str:
    """Stable fingerprint of a plan's deploy-relevant STRUCTURE: vertex
    uids/names/parallelisms, subtask counts (source split counts included),
    and edges with partitioning/key columns.

    Job shipping rebuilds the plan in every process from the
    ``module:function`` reference, which silently assumes the builder is
    deterministic; a nondeterministic builder (unseeded shuffles, dict-order
    uids, host-dependent split enumeration) makes workers deploy DIFFERENT
    jobs and diverge without any error.  The coordinator ships this digest
    with every deploy and workers verify their own rebuild against it —
    mismatches fail fast at deploy instead of corrupting the run."""
    import hashlib

    counts, _splits = subtask_counts_of(plan)
    parts = []
    for v in plan.vertices:
        parts.append(f"v:{v.uid}:{v.name}:{counts.get(v.uid)}:"
                     f"{v.max_parallelism}:{int(bool(v.is_source))}")
        for e in v.out_edges:
            tgt = plan.by_id[e.target_id]
            parts.append(f"e:{v.uid}->{tgt.uid}"
                         f"#{getattr(e, 'input_index', 0)}:"
                         f"{getattr(e, 'partitioning', None)}:"
                         f"{getattr(e, 'key_column', None)}")
    return hashlib.sha256("\n".join(parts).encode()).hexdigest()[:16]


def subtask_counts_of(plan) -> Tuple[Dict[str, int], Dict[int, list]]:
    """Subtask count per vertex (sources: one per split, like the
    MiniCluster; runtime-enumerated sources: fixed reader count, splits
    assigned over the control plane) and the static split lists."""
    counts: Dict[str, int] = {}
    splits_by_vertex: Dict[int, list] = {}
    for v in plan.vertices:
        if v.is_source:
            src = v.chain[0].source
            if getattr(src, "create_enumerator", None) is not None:
                splits_by_vertex[v.id] = None  # dynamic: request at runtime
                counts[v.uid] = v.parallelism
                continue
            splits = src.create_splits(v.parallelism)
            splits_by_vertex[v.id] = splits
            counts[v.uid] = max(1, len(splits))
        else:
            counts[v.uid] = v.parallelism
    return counts, splits_by_vertex


def assign_subtasks(plan, counts: Dict[str, int],
                    n_workers: int) -> Dict[Tuple[str, int], int]:
    """Deterministic subtask → worker placement (round-robin over the
    plan's vertex order — the declarative SlotManager's match, made a pure
    function of (plan, n) so every process computes it identically)."""
    out: Dict[Tuple[str, int], int] = {}
    i = 0
    for v in plan.vertices:
        for s in range(counts[v.uid]):
            out[(v.uid, s)] = i % n_workers
            i += 1
    return out


def _edge_pairs(part: str, np_: int, nc: int):
    """(producer, consumer, effective_partitioning) tuples for one edge —
    the same channel topology the MiniCluster builds."""
    if part == "forward" and np_ == nc:
        return [(pi, pi) for pi in range(np_)], "forward"
    eff = "rebalance" if (part == "forward" and nc > 1) else part
    return [(pi, ci) for pi in range(np_) for ci in range(nc)], eff


# --------------------------------------------------------------------------
# worker process
# --------------------------------------------------------------------------

def _security_from_env() -> Optional["SecurityConfig"]:
    """Worker-side security settings, shipped via environment variables by
    the coordinator (the reference ships keystores via the container env /
    mounted secrets the same way)."""
    from flink_tpu.security import SecurityConfig

    cert = os.environ.get("FLINK_TPU_SSL_CERT")
    token = os.environ.get("FLINK_TPU_AUTH_TOKEN")
    if not cert and not token:
        return None
    return SecurityConfig(
        internal_ssl=bool(cert),
        cert_path=cert,
        key_path=os.environ.get("FLINK_TPU_SSL_KEY"),
        ca_path=os.environ.get("FLINK_TPU_SSL_CA"),
        auth_token=token or None)


class _WorkerRuntime:
    """TaskListener inside a worker: deploys the local subtask slice and
    relays task events to the coordinator."""

    def __init__(self, index: int, n_workers: int, job: str,
                 coord_host: str, coord_port: int,
                 bind_host: str = "127.0.0.1",
                 advertise_host: Optional[str] = None,
                 local_recovery_dir: Optional[str] = None):
        from flink_tpu.cluster.net import ChannelServer

        #: checkpoint-policy options shipped with deploy (unaligned /
        #: alignment-timeout escalation / alignment-queue cap) plus the
        #: observability opts (tracing / latency-marker cadence)
        self._ckpt_opts: Dict[str, Any] = {}
        #: per-(source, hop) latency histograms for THIS worker's hops;
        #: shipped to the coordinator with the trace dump
        self.latency_tracker = None

        #: local recovery (TaskLocalStateStoreImpl.java:54): secondary
        #: worker-local snapshot copies; restore prefers them over the
        #: coordinator-shipped (remote-storage) state
        self.local_store = None
        #: run scoping: only checkpoints of THIS cluster run restore from
        #: the local store (ids restart per run; a reused dir must not
        #: serve a previous run's chk-N files)
        self.run_token = os.environ.get("FLINK_TPU_RUN_TOKEN")
        if local_recovery_dir is None:
            local_recovery_dir = os.environ.get("FLINK_TPU_LOCAL_RECOVERY")
        if local_recovery_dir:
            from flink_tpu.runtime.checkpoint.local import TaskLocalStateStore
            scoped = (os.path.join(local_recovery_dir,
                                   f"run-{self.run_token}")
                      if self.run_token else local_recovery_dir)
            self.local_store = TaskLocalStateStore(scoped, index)
        #: per-deploy counters, reported to the coordinator after each
        #: restore so tests (and operators) can assert local-recovery hits
        self.recovery_local = 0
        self.recovery_remote = 0
        self.index = index
        self.n_workers = n_workers
        self.job = job
        self.security = _security_from_env()
        server_ctx = client_ctx = None
        if self.security is not None and self.security.internal_ssl:
            server_ctx = self.security.server_context()
            client_ctx = self.security.client_context()
        self._client_ssl = client_ctx
        #: data-plane HMAC: channel HELLOs are signed with the cluster
        #: token, so worker ports never decode unauthenticated batches
        self._data_token = (self.security.auth_token
                            if self.security is not None else None)
        self.server = ChannelServer(host=bind_host, ssl_context=server_ctx,
                                    auth_token=self._data_token)
        #: address other workers dial (pod IP / service DNS on k8s)
        self.advertise_host = advertise_host or self.server.host
        self.sock = socket.create_connection((coord_host, coord_port),
                                             timeout=30)
        if client_ctx is not None:
            self.sock = client_ctx.wrap_socket(self.sock,
                                               server_hostname=coord_host)
        # the connect timeout must not linger: the worker blocks on this
        # socket indefinitely waiting for deploy/stop (sibling workers can
        # take arbitrarily long to cold-start before the coordinator
        # broadcasts deploy)
        self.sock.settimeout(None)
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        #: per-worker queryable serving (ISSUE-13): THIS worker's live
        #: views + its own subtasks' replica shards behind a local
        #: QueryableStateServer; the coordinator aggregates every
        #: worker's (state -> subtasks -> endpoint) registration into the
        #: routing table clients fan out on
        self.qservice = None
        self._q_states: Dict[str, Dict[str, Any]] = {}
        self._q_acks: Dict[Tuple[str, int], Dict[str, Any]] = {}
        self.tasks: List[Any] = []
        self._terminal = set()
        self._done_sent = False
        self._remote_writers: List[Any] = []
        self._split_queues: Dict[Tuple[str, int], Any] = {}
        #: region-scoped recovery bookkeeping: which remote writers a local
        #: producer owns, and which server channel ids feed a local consumer
        self._writers_by_task: Dict[Tuple[str, int], List[Any]] = {}
        self._inchans_by_task: Dict[Tuple[str, int], List[str]] = {}
        #: coordinator HA (ISSUE-20): highest leader epoch observed on the
        #: control plane — messages carrying a LOWER (non-zero) epoch are a
        #: zombie ex-leader's and are rejected, never acted on
        self._leader_epoch = 0
        self._fenced_msgs = 0
        #: orphan-worker reaper: tracks coordinator liveness through the
        #: shared heartbeat seam; armed at deploy when the coordinator
        #: ships an ``orphan_timeout_s`` (None until then)
        self._hb = None
        self.orphaned = False

    # -- coordinator HA -----------------------------------------------------
    def _admit_epoch(self, epoch: int, kind: str) -> bool:
        """Leader-epoch fence: adopt a HIGHER epoch (a new leader took
        over), reject a LOWER one (a zombie ex-leader still sending).
        Epoch 0 means HA is off and everything is admitted."""
        if epoch > self._leader_epoch:
            self._leader_epoch = epoch
            server = getattr(self, "server", None)
            if server is not None and hasattr(server, "min_epoch"):
                # fence the data plane too: a stale incarnation's remote
                # writers fail the channel HELLO against this worker
                server.min_epoch = epoch
            return True
        if epoch and epoch < self._leader_epoch:
            self._fenced_msgs += 1
            self._send(("fenced", self.index, kind, epoch))
            return False
        return True

    def _arm_orphan_reaper(self, timeout_s: float) -> None:
        """Satellite 1: self-terminate (committing nothing) when the lease
        holder goes silent past ``timeout_s`` — a dead-but-unreaped
        coordinator must not leak worker processes holding sockets and
        device state forever.  Every control message (pings included)
        counts as a heartbeat."""
        if self._hb is not None:
            return
        from flink_tpu.cluster.heartbeat import (HeartbeatManager,
                                                 HeartbeatTarget)
        self._hb = HeartbeatManager(
            interval_s=max(0.2, float(timeout_s) / 4.0),
            timeout_s=float(timeout_s),
            on_timeout=self._coordinator_silent)
        # the coordinator PUSHES pings; the request side is a no-op
        self._hb.monitor_target("coordinator",
                                HeartbeatTarget(lambda: None))
        self._hb.receive_heartbeat("coordinator")
        self._hb.start()

    def _coordinator_silent(self, resource_id: str) -> None:
        self.orphaned = True
        for t in self.tasks:
            t.cancel()
        try:
            # unblocks the control loop's recv -> clean exit path; nothing
            # is committed (commits only ever happen on notify-complete)
            self.sock.close()
        except OSError:
            pass

    def _send(self, obj: Any) -> None:
        try:
            _send_msg(self.sock, obj, self._send_lock)
        except OSError:
            pass

    # -- TaskListener ------------------------------------------------------
    def task_state_changed(self, vertex_uid: str, subtask_index: int,
                           state: str, error: Optional[str]) -> None:
        self._send(("state", vertex_uid, subtask_index, state, error))
        if state == "FINISHED":
            t = next((t for t in self.tasks
                      if t.vertex_uid == vertex_uid
                      and t.subtask_index == subtask_index), None)
            final = getattr(t, "final_snapshot", None) if t else None
            if final is not None:
                self._send(("final", vertex_uid, subtask_index, final))
        if state in ("FINISHED", "CANCELED", "FAILED"):
            with self._lock:
                self._terminal.add((vertex_uid, subtask_index))
                done = (len(self._terminal) >= len(self.tasks)
                        and not self._done_sent)
                if done:
                    self._done_sent = True
            if done:
                self._collect_and_finish()

    def acknowledge_checkpoint(self, checkpoint_id: int, vertex_uid: str,
                               subtask_index: int,
                               snapshot: Dict[str, Any]) -> None:
        if self.local_store is not None:
            # secondary local copy BEFORE the ack ships: a same-worker
            # restart restores from here without touching remote storage
            self.local_store.store(checkpoint_id, vertex_uid,
                                   subtask_index, snapshot)
        if self.qservice is not None and any(
                info["uid"] == vertex_uid for info in self._q_states.values()):
            # stash for the worker-local replica tier: on notify-complete
            # the stashed snapshots feed THIS worker's replica shards (the
            # worker never sees the coordinator-assembled checkpoint).
            # An incremental ack resolves against the previous stash so
            # the replica tier always ingests dense state; an unresolvable
            # chain just skips the stash (the replica stays one cut stale)
            from flink_tpu.runtime.checkpoint import delta
            stash = snapshot
            if delta.tree_has_increment(stash):
                try:
                    stash = delta.apply_increments(
                        self._q_acks.get((vertex_uid, subtask_index)),
                        stash)
                except delta.IncrementChainError:
                    stash = None
            if stash is not None:
                self._q_acks[(vertex_uid, subtask_index)] = stash
        self._send(("ack", checkpoint_id, vertex_uid, subtask_index,
                    snapshot, self._leader_epoch))

    def decline_checkpoint(self, checkpoint_id: int, vertex_uid: str,
                           subtask_index: int, error: str) -> None:
        """A subtask's snapshot failed: ship the decline to the coordinator
        (``declineCheckpoint`` RPC) so the pending checkpoint is aborted and
        charged to the failure budget — the task itself keeps running."""
        self._send(("decline", checkpoint_id, vertex_uid, subtask_index,
                    error))

    # -- runtime split requests (FLIP-27 RequestSplitEvent over the
    # control plane; replies land on a per-reader queue) ------------------
    def _make_split_requester(self, uid: str, idx: int):
        import queue as _q

        q: "_q.Queue" = _q.Queue()
        self._split_queues[(uid, idx)] = q

        def request():
            self._send(("split_request", uid, idx))
            try:
                split, done = q.get(timeout=60)
            except _q.Empty:
                # a silent finish here would report FINISHED with unread
                # files; failing the task triggers restart + restore instead
                raise RuntimeError(
                    "split request timed out — coordinator unreachable")
            return split, done
        return request

    # -- results -----------------------------------------------------------
    def _collect_and_finish(self) -> None:
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.operators.basic import SinkOperator

        for t in self.tasks:
            ops = getattr(t.operator, "operators", [t.operator])
            for op in ops:
                sink = getattr(op, "sink", None)
                if isinstance(op, SinkOperator) and isinstance(sink,
                                                               CollectSink):
                    self._send(("rows", t.vertex_uid, t.subtask_index,
                                sink.rows()))
        self._send(("worker_done", self.index))

    # -- deploy ------------------------------------------------------------
    def deploy(self, addresses: Dict[int, Tuple[str, int]],
               restore: Optional[Dict[str, Any]],
               only: Optional[set] = None,
               expected_digest: Optional[str] = None,
               ckpt_opts: Optional[Dict[str, Any]] = None) -> bool:
        """Build and start this worker's subtask slice.  ``only``: restrict
        to these (vertex_uid, subtask_index) — region-scoped recovery
        redeploys just the affected regions' tasks, leaving the rest
        running (``RestartPipelinedRegionFailoverStrategy``).  Regions are
        edge-closed, so every channel of an ``only`` task has both
        endpoints inside ``only``.

        ``expected_digest``: the coordinator's plan-structure digest.  This
        worker rebuilds the plan from the job reference and REFUSES to
        deploy on mismatch (nondeterministic job builder) — failing fast
        beats silently deploying a divergent job.  Returns False on the
        refusal."""
        from flink_tpu.cluster.channels import LocalChannel, OutputDispatcher
        from flink_tpu.cluster.net import RemoteChannel
        from flink_tpu.cluster.task import SourceSubtask, Subtask
        from flink_tpu.core.functions import RuntimeContext

        plan = build_plan(self.job)
        if expected_digest is not None:
            local = plan_structure_digest(plan)
            if local != expected_digest:
                self._send(("plan_mismatch", self.index, local,
                            expected_digest))
                return False
        if ckpt_opts is not None:
            self._ckpt_opts = dict(ckpt_opts)
        opts = self._ckpt_opts
        if opts.get("orphan_timeout_s"):
            self._arm_orphan_reaper(opts["orphan_timeout_s"])
        # observability: install the span journal when the coordinator
        # asked for tracing, and stand up the per-worker latency tracker
        # (markers record at every local hop; the panel ships with the
        # trace dump for cross-process assembly)
        if opts.get("tracing"):
            from flink_tpu.observability import tracing as tracing_mod
            if not tracing_mod.enabled():
                tracing_mod.install(
                    capacity=int(opts.get("trace_capacity", 65536)))
        if self.latency_tracker is None:
            from flink_tpu.observability import LatencyTracker
            self.latency_tracker = LatencyTracker()
        counts, splits_by_vertex = subtask_counts_of(plan)
        assign = assign_subtasks(plan, counts, self.n_workers)
        me = self.index

        def n_subs(v) -> int:
            return counts[v.uid]

        def wanted(uid: str, i: int) -> bool:
            return only is None or (uid, i) in only

        inputs: Dict[int, List[List[Any]]] = {
            v.id: [[] for _ in range(n_subs(v))] for v in plan.vertices}
        input_logical: Dict[int, List[List[int]]] = {
            v.id: [[] for _ in range(n_subs(v))] for v in plan.vertices}
        # per-input-channel routing metadata written into the v2
        # channel-state section (rescale restores re-route persisted
        # in-flight elements by record key — state/redistribute)
        input_routing: Dict[int, List[List[Dict[str, Any]]]] = {
            v.id: [[] for _ in range(n_subs(v))] for v in plan.vertices}
        outputs: Dict[int, List[List[OutputDispatcher]]] = {
            v.id: [[] for _ in range(n_subs(v))] for v in plan.vertices}

        for v in plan.vertices:
            for ei, e in enumerate(v.out_edges):
                tgt = plan.by_id[e.target_id]
                np_, nc = n_subs(v), n_subs(tgt)
                pairs, eff = _edge_pairs(e.partitioning, np_, nc)
                routing = {"partitioning": e.partitioning,
                           "key_column": e.key_column,
                           "max_parallelism": v.max_parallelism,
                           "logical": e.input_index}
                # group channels per producer (dispatcher wants ci order)
                per_producer: Dict[int, List[Any]] = {}
                for pi, ci in pairs:
                    if not (wanted(v.uid, pi) or wanted(tgt.uid, ci)):
                        continue
                    p_local = assign[(v.uid, pi)] == me
                    c_local = assign[(tgt.uid, ci)] == me
                    chan_id = f"{v.uid}[{pi}]->{tgt.uid}[{ci}]#{ei}"
                    ch = None
                    if p_local and c_local:
                        ch = LocalChannel(name=chan_id)
                        inputs[tgt.id][ci].append(ch)
                        input_logical[tgt.id][ci].append(e.input_index)
                        input_routing[tgt.id][ci].append(dict(routing))
                    elif p_local:
                        host, port = addresses[assign[(tgt.uid, ci)]]
                        ch = RemoteChannel(host, port, chan_id,
                                           ssl_context=self._client_ssl,
                                           auth_token=self._data_token,
                                           epoch=self._leader_epoch)
                        self._remote_writers.append(ch)
                        self._writers_by_task.setdefault(
                            (v.uid, pi), []).append(ch)
                    elif c_local:
                        q = self.server.channel(chan_id)
                        inputs[tgt.id][ci].append(q)
                        input_logical[tgt.id][ci].append(e.input_index)
                        input_routing[tgt.id][ci].append(dict(routing))
                        self._inchans_by_task.setdefault(
                            (tgt.uid, ci), []).append(chan_id)
                    if p_local:
                        per_producer.setdefault(pi, []).append(ch)
                for pi, chans in per_producer.items():
                    outputs[v.id][pi].append(OutputDispatcher(
                        eff, chans, max_parallelism=v.max_parallelism,
                        subtask_index=pi, key_column=e.key_column))

        # build EVERY local task first, then start: a fast task finishing
        # while deploy is mid-flight must not trip the all-terminal check
        # against a partial task list
        restore = restore or {}
        job_meta = restore.get("__job__") or {}
        restore_cid = job_meta.get("checkpoint_id")
        # the local store only serves checkpoints taken by THIS run: a
        # cross-run restore (snap passed into a fresh cluster) carries a
        # different run token and must read the shipped state
        same_run = (self.run_token is not None
                    and job_meta.get("run_token") == self.run_token)
        self.recovery_local = 0
        self.recovery_remote = 0

        def pick_restore(uid: str, i: int, sub_snaps) -> Optional[Dict]:
            """Local-recovery preference: this worker's own local copy of
            (checkpoint, uid, subtask) wins over the coordinator-shipped
            remote state; the shipped copy is the fallback."""
            from flink_tpu.testing import chaos
            shipped = sub_snaps[i] if i < len(sub_snaps) else None
            if self.local_store is not None and restore_cid is not None \
                    and same_run:
                local = self.local_store.load(restore_cid, uid, i)
                if local is not None:
                    self.recovery_local += 1
                    return local
                if shipped is not None:
                    self.recovery_remote += 1
            if shipped is not None and not chaos.fire(
                    "restore.fetch", direction="storage->worker",
                    worker=self.index, uid=uid, subtask=i):
                # Partition(direction="storage->worker"): the remote
                # (primary-storage) copy is unreachable — fail the deploy
                # loudly rather than silently restoring empty state
                raise RuntimeError(
                    f"restore fetch partitioned (storage->worker) for "
                    f"{uid}[{i}] and no local copy available")
            return shipped

        to_start: List[Tuple[Any, Optional[Dict[str, Any]]]] = []
        for v in plan.vertices:
            vr = restore.get(v.uid, {})
            sub_snaps = vr.get("subtasks", [])
            if v.is_source:
                splits = splits_by_vertex[v.id]
                if splits is None:
                    # runtime enumeration: every reader pulls splits from
                    # the coordinator over the control plane (the
                    # RequestSplitEvent RPC, SourceCoordinator.java:155)
                    for i in range(counts[v.uid]):
                        if assign[(v.uid, i)] != me or not wanted(v.uid, i):
                            continue
                        ctx = RuntimeContext(
                            task_name=v.name, subtask_index=i,
                            parallelism=counts[v.uid],
                            max_parallelism=v.max_parallelism)
                        t = SourceSubtask(
                            v.uid, i, v.build_operator(),
                            outputs[v.id][i], ctx, self, None,
                            split_requester=self._make_split_requester(
                                v.uid, i))
                        to_start.append((t, pick_restore(v.uid, i,
                                                         sub_snaps)))
                    continue
                for i, split in enumerate(splits):
                    if assign[(v.uid, i)] != me or not wanted(v.uid, i):
                        continue
                    ctx = RuntimeContext(task_name=v.name, subtask_index=i,
                                         parallelism=len(splits),
                                         max_parallelism=v.max_parallelism)
                    t = SourceSubtask(v.uid, i, v.build_operator(),
                                      outputs[v.id][i], ctx, self, split)
                    to_start.append((t, pick_restore(v.uid, i, sub_snaps)))
            else:
                for i in range(n_subs(v)):
                    if assign[(v.uid, i)] != me or not wanted(v.uid, i):
                        continue
                    ctx = RuntimeContext(task_name=v.name, subtask_index=i,
                                         parallelism=n_subs(v),
                                         max_parallelism=v.max_parallelism)
                    t = Subtask(v.uid, i, v.build_operator(),
                                outputs[v.id][i], ctx, self,
                                inputs[v.id][i],
                                input_logical=input_logical[v.id][i],
                                unaligned=opts.get("unaligned", False),
                                alignment_timeout_ms=opts.get(
                                    "alignment_timeout_ms"),
                                alignment_queue_max=opts.get(
                                    "alignment_queue_max", 8192),
                                input_routing=input_routing[v.id][i])
                    to_start.append((t, pick_restore(v.uid, i, sub_snaps)))
        if only is None:
            self.tasks = [t for t, _ in to_start]
        else:
            self.tasks.extend(t for t, _ in to_start)
            with self._lock:
                # re-arm completion reporting (reset_tasks suppressed it);
                # the just-started tasks guarantee a future terminal
                # transition that runs the done check
                self._done_sent = False
        # incremental checkpoints (ISSUE-16): flip delta-tracking on in
        # every capable operator/backend of this worker's slice (mirror of
        # MiniCluster._attach_observability's incremental wiring)
        if opts.get("incremental"):
            for t, _snap in to_start:
                t.incremental_checkpoints = True
                for member in getattr(t.operator, "operators", [t.operator]):
                    if hasattr(member, "incremental_state"):
                        member.incremental_state = True
                        if hasattr(member, "incr_rebase_ratio"):
                            member.incr_rebase_ratio = float(
                                opts.get("incr_rebase_ratio", 0.5))
                        be = getattr(member, "backend", None)
                        if be is not None \
                                and hasattr(be, "snapshot_increment"):
                            be.materialize_threshold = int(
                                opts.get("materialization_threshold", 256))
        lat_ms = int(opts.get("latency_interval_ms") or 0)
        # worker-local deploy barrier (the MiniCluster one, scoped to this
        # process's slice): shared-instance sinks restore by replacement,
        # so no local subtask may process input before the slice restored
        gate = (threading.Barrier(len(to_start)) if len(to_start) > 1
                else None)
        for t, snap in to_start:
            t.latency_tracker = self.latency_tracker
            t._deploy_gate = gate
            if lat_ms and isinstance(t, SourceSubtask):
                t.latency_marker_interval_ms = lat_ms
            t.start(snap)
        if opts.get("queryable_serving", True):
            self._wire_worker_queryable(plan, counts)
        if not self.tasks:
            self._done_sent = True
            self._send(("worker_done", self.index))
        return True

    def _wire_worker_queryable(self, plan, counts: Dict[str, int]) -> None:
        """Per-worker serving tier (ISSUE-13): front THIS worker's live
        views and its own subtasks' checkpoint-replica shards behind a
        local :class:`QueryableStateServer`, and register the (state ->
        local subtasks -> endpoint) mapping with the coordinator — the
        routing table clients use to skip the coordinator entirely.

        Views register with the job's FULL parallelism (foreign subtasks
        are None entries): routing geometry is global, ownership is
        local.  Redeploys re-register wholesale; the server (and its
        port) survives in-place recoveries, so only a worker PROCESS
        restart moves an endpoint — the stale-map case the client's
        evict-then-refresh retry handles."""
        regs: Dict[str, Dict[str, Any]] = {}
        max_par = {v.uid: v.max_parallelism for v in plan.vertices}
        for t in self.tasks:
            op = getattr(t, "operator", None)
            for member in getattr(op, "operators", [op]):
                qname = getattr(member, "queryable", None)
                view = getattr(member, "queryable_view", lambda: None)()
                if qname is None or view is None:
                    continue
                entry = regs.setdefault(qname, {
                    "uid": t.vertex_uid, "op": member, "views": {}})
                entry["views"][t.subtask_index] = view
        if not regs:
            return
        from flink_tpu.queryable.replica import QueryableStateSpec
        from flink_tpu.queryable.service import QueryableStateService
        if self.qservice is None:
            self.qservice = QueryableStateService()
        advertise: Dict[str, Dict[str, Any]] = {}
        for name, entry in regs.items():
            uid = entry["uid"]
            p = counts.get(uid, len(entry["views"]))
            mp = max_par.get(uid, 128)
            views = [entry["views"].get(i) for i in range(p)]
            self.qservice.register_views(name, views, parallelism=p,
                                         max_parallelism=mp)
            if name not in self.qservice.registry.replicas():
                self.qservice.add_replica(
                    name, QueryableStateSpec.from_operator(
                        name, uid, entry["op"]), max_parallelism=mp)
            self._q_states[name] = {
                "uid": uid, "parallelism": p, "max_parallelism": mp,
                "subtasks": sorted(entry["views"])}
            advertise[name] = dict(self._q_states[name])
        server = self.qservice.start_server(host=self.server.host)
        self._send(("qserve", self.index, advertise,
                    self.advertise_host, server.port, self._leader_epoch))

    def _feed_worker_replicas(self, checkpoint_id: int) -> None:
        """notify-complete -> feed this worker's replica shards from the
        stashed ack snapshots: every queryable uid's assembled entry
        carries the GLOBAL subtask list with only the local ones filled,
        so the replica's routing parallelism matches the job while its
        shards cover exactly this worker's key-group ranges."""
        if self.qservice is None or not self._q_states:
            return
        assembled: Dict[str, Any] = {}
        for info in self._q_states.values():
            uid, p = info["uid"], info["parallelism"]
            if uid in assembled:
                continue
            subs = [self._q_acks.get((uid, i)) for i in range(p)]
            if any(s is not None for s in subs):
                assembled[uid] = {"subtasks": subs}
        if assembled:
            self.qservice.on_checkpoint_complete(checkpoint_id, assembled)

    # -- main loop ---------------------------------------------------------
    def run(self) -> int:
        # auth handshake, JSON both ways (never pickle pre-auth): the
        # coordinator challenges, the worker answers with an HMAC over the
        # nonce (cluster shared secret)
        msg = _recv_json(self.sock)
        if not isinstance(msg, dict) or msg.get("kind") != "challenge":
            return 1
        nonce_hex = msg.get("nonce")
        mac_hex = None
        if nonce_hex is not None:
            if self.security is None or self.security.auth_token is None:
                return 1  # cluster requires a token this worker lacks
            try:
                nonce = bytes.fromhex(nonce_hex)
            except (TypeError, ValueError):
                return 1  # malformed challenge
            mac_hex = self.security.sign(nonce).hex()
        _send_json(self.sock, {"kind": "hello", "index": self.index,
                               "host": self.advertise_host,
                               "port": self.server.port, "mac": mac_hex},
                   self._send_lock)
        while True:
            msg = _recv_msg(self.sock)
            if msg is None:
                break
            kind = msg[0]
            # any control traffic proves the coordinator alive — heartbeat
            # BEFORE the epoch fence (a fenced zombie is still a liveness
            # signal only for ITS OWN workers, which share its socket)
            if self._hb is not None:
                self._hb.receive_heartbeat("coordinator")
            base = _MSG_ARITY.get(kind)
            epoch = 0
            if base is not None and len(msg) > base:
                epoch = msg[base] or 0
            if not self._admit_epoch(epoch, kind):
                continue
            if kind == "ping":
                continue
            if kind == "deploy":
                ok = self.deploy(msg[1], msg[2],
                                 only=set(msg[3]) if len(msg) > 3
                                 and msg[3] is not None else None,
                                 expected_digest=msg[4] if len(msg) > 4
                                 else None,
                                 ckpt_opts=msg[5] if len(msg) > 5
                                 else None)
                if ok and msg[2] and (self.recovery_local
                                      or self.recovery_remote):
                    self._send(("recovery_stats", self.index,
                                self.recovery_local,
                                self.recovery_remote))
            elif kind == "checkpoint":
                cid = msg[1]
                for t in self.tasks:
                    if hasattr(t, "split"):  # source: inject barrier
                        t.commands.put(("checkpoint", cid))
            elif kind == "notify":
                if self.local_store is not None:
                    self.local_store.confirm(msg[1])
                for t in self.tasks:
                    t.commands.put(("notify_complete", msg[1]))
                self._feed_worker_replicas(msg[1])
            elif kind == "split_assign":
                uid, idx, split, done = msg[1:5]
                q = self._split_queues.get((uid, idx))
                if q is not None:
                    q.put((split, done))
            elif kind == "reset":
                # surviving-worker recovery: tear down THIS worker's tasks
                # and channels, keep the process (and its warm caches/data
                # plane address) alive for the next deploy
                with self._lock:
                    self._done_sent = True  # suppress worker_done/rows
                for w in self._remote_writers:
                    try:
                        w.close()          # unblocks producers first
                    except OSError:
                        pass
                self._remote_writers = []
                # poison split-request waits: a reader parked in q.get()
                # cannot see cancel(); (None, True) ends its loop cleanly
                for q in self._split_queues.values():
                    q.put((None, True))
                for t in self.tasks:
                    t.cancel()
                for t in self.tasks:
                    t.join(timeout_s=10)
                self.server.reset()
                self.tasks = []
                self._split_queues = {}
                self._writers_by_task = {}
                self._inchans_by_task = {}
                with self._lock:
                    self._terminal = set()
                    self._done_sent = False
                self._send(("reset_done", self.index))
            elif kind == "reset_tasks":
                # region-scoped recovery: tear down ONLY the affected
                # regions' local tasks and their channels; everything else
                # keeps running (surviving regions never restart)
                with self._lock:
                    # suppress worker_done until the follow-up deploy: the
                    # cancels below (and any unaffected task finishing in
                    # the window) must not make this worker look done
                    # while its affected tasks are pending redeploy
                    self._done_sent = True
                aff = set(msg[1])
                mine = [t for t in self.tasks
                        if (t.vertex_uid, t.subtask_index) in aff]
                for t in mine:
                    key = (t.vertex_uid, t.subtask_index)
                    for w in self._writers_by_task.pop(key, []):
                        try:
                            w.close()
                        except OSError:
                            pass
                        if w in self._remote_writers:
                            self._remote_writers.remove(w)
                    q = self._split_queues.pop(key, None)
                    if q is not None:
                        q.put((None, True))
                for t in mine:
                    t.cancel()
                for t in mine:
                    t.join(timeout_s=10)
                drop_chans = [cid for t in mine for cid in
                              self._inchans_by_task.pop(
                                  (t.vertex_uid, t.subtask_index), [])]
                self.server.reset_channels(drop_chans)
                self.tasks = [t for t in self.tasks if t not in mine]
                with self._lock:
                    self._terminal -= {(t.vertex_uid, t.subtask_index)
                                       for t in mine}
                    # _done_sent stays True: deploy(only=...) re-arms it
                self._send(("reset_done", self.index))
            elif kind == "trace_request":
                # ship this process's span ring + latency panel + our wall
                # reading (the coordinator's clock-offset estimation input)
                from flink_tpu.observability import tracing as tracing_mod
                from flink_tpu.utils import clock as _clock
                j = tracing_mod.active()
                self._send(("trace_dump", self.index, {
                    "journal": j.snapshot() if j is not None else None,
                    "latency": (self.latency_tracker.panel()
                                if self.latency_tracker is not None else []),
                    "wall_now_ms": _clock.now_ms()}))
            elif kind == "cancel":
                for t in self.tasks:
                    t.cancel()
            elif kind == "stop":
                break
        if self._hb is not None:
            self._hb.stop()
        for t in self.tasks:
            t.join(timeout_s=10)
        for w in self._remote_writers:
            w.close()
        if self.qservice is not None:
            self.qservice.close()
        self.server.stop()
        return 0


# --------------------------------------------------------------------------
# coordinator (worker processes enter via `python -m flink_tpu worker`,
# which constructs a _WorkerRuntime directly — see __main__._cmd_worker)
# --------------------------------------------------------------------------

class _Pending:
    def __init__(self, cid: int, expected: set, enumerators=None):
        from flink_tpu.utils.clock import MonotoneElapsed

        self.cid = cid
        self.expected = set(expected)
        self.acks: Dict[Tuple[str, int], Dict[str, Any]] = {}
        #: expiry through the injectable clock seam, clamped monotone —
        #: a ClockSkew backward step never un-expires a checkpoint
        self.timer = MonotoneElapsed()
        #: trigger-time perf reading — the trigger→complete trace span
        self.t0_ns = time.perf_counter_ns()
        #: enumerator snapshots taken at trigger time (§3.4 coordinator
        #: snapshots precede task triggers)
        self.enumerators = enumerators


class ProcessCluster:
    """Coordinator: spawns workers, drives deploy/checkpoint/shutdown, and
    assembles results (the Dispatcher + JobMaster + CheckpointCoordinator
    roles collapsed into one process for a single job)."""

    def __init__(self, job: str, n_workers: int = 2,
                 checkpoint_storage=None, checkpoint_interval_ms: int = 0,
                 extra_sys_path: Tuple[str, ...] = (), security=None,
                 spawn: bool = True, bind_host: str = "127.0.0.1",
                 listen_port: int = 0, restart_attempts: int = 0,
                 restart_delay_ms: int = 500, worker_recovery: bool = True,
                 local_recovery_dir: Optional[str] = None,
                 tolerable_failed_checkpoints: int = 0,
                 checkpoint_timeout_s: float = 60.0,
                 unaligned: bool = False,
                 alignment_timeout_ms: Optional[float] = None,
                 alignment_queue_max: int = 8192,
                 tracing: bool = False,
                 latency_interval_ms: Optional[int] = None,
                 trace_capacity: int = 65536,
                 queryable_serving: bool = True,
                 incremental: bool = False,
                 incremental_rebase_ratio: float = 0.5,
                 changelog_materialization_threshold: int = 256,
                 ha_store=None,
                 ha_lease_ttl_s: float = 2.0,
                 ha_job_id: Optional[str] = None,
                 worker_orphan_timeout_s: Optional[float] = 45.0,
                 ping_interval_s: float = 5.0):
        from flink_tpu.observability import tracing as tracing_mod
        from flink_tpu.runtime.checkpoint.failure import \
            CheckpointFailureManager

        self.job = job
        self.n_workers = n_workers
        #: coordinator HA (ISSUE-20): a FileHaStore holding the leader
        #: lease (monotone epoch), the registered job, and the
        #: completed-checkpoint pointer.  None = HA off (epoch stays 0 and
        #: the fences are no-ops).
        self.ha_store = ha_store
        self.ha_lease_ttl_s = float(ha_lease_ttl_s)
        if ha_job_id is None and ha_store is not None:
            from flink_tpu.runtime.ha import job_id_for
            ha_job_id = job_id_for(job)
        self.ha_job_id = ha_job_id
        self._epoch = 0
        self._lease = None
        self._renewer = None
        #: completions this (zombie) coordinator lost to the epoch fence —
        #: each one also charges the checkpoint failure budget, so a fenced
        #: ex-leader fails LOUDLY instead of running forever
        self.ha_fenced_completions = 0
        #: stale-epoch worker messages observed (`("fenced", ...)` reports
        #: plus acks/qserve rejected coordinator-side)
        self.fenced_worker_msgs = 0
        #: how the last HA restore was resolved ("ha-pointer" /
        #: "scan-fallback" / "none"), for the REST panel and tests
        self.ha_restore_source: Optional[str] = None
        #: orphan-worker reaper deadline shipped to workers via ckpt_opts;
        #: the coordinator broadcasts pings every ping_interval_s so a
        #: quiet-but-alive leader keeps its workers
        self.worker_orphan_timeout_s = worker_orphan_timeout_s
        self.ping_interval_s = float(ping_interval_s)
        #: unaligned-checkpoint + observability policy, shipped to every
        #: worker with the deploy message (workers thread it into their
        #: Subtasks / install their span journals)
        self.ckpt_opts = {"unaligned": unaligned,
                          "alignment_timeout_ms": alignment_timeout_ms,
                          "alignment_queue_max": alignment_queue_max,
                          "tracing": tracing,
                          "latency_interval_ms": latency_interval_ms,
                          "trace_capacity": trace_capacity,
                          # per-worker serving (ISSUE-13): workers with
                          # queryable operators stand up local servers and
                          # register their endpoints here at deploy
                          "queryable_serving": queryable_serving,
                          # incremental checkpoints (ISSUE-16): workers flip
                          # delta-tracking on in their operators/backends;
                          # the coordinator resolves increment acks against
                          # the previous completed cut before anything
                          # downstream consumes them
                          "incremental": incremental,
                          "incr_rebase_ratio": incremental_rebase_ratio,
                          "materialization_threshold":
                              changelog_materialization_threshold,
                          # orphan-worker reaper (ISSUE-20 satellite):
                          # workers self-terminate when the coordinator is
                          # silent past this deadline (None disables)
                          "orphan_timeout_s": worker_orphan_timeout_s}
        #: end-to-end tracing: workers record spans locally; at job end
        #: the coordinator pulls every ring and assembles ONE merged
        #: timeline (result["trace"], also kept as self.last_trace)
        self.tracing = tracing
        #: THIS cluster's coordinator-side journal handle (None when
        #: tracing is off): run() resets it per execution so job B never
        #: inherits job A's spans or its consumed ring capacity.  An
        #: adopted pre-existing journal belongs to whoever installed it —
        #: we record into it but never reset() it, and its owner's
        #: capacity choice wins over ``trace_capacity``
        self._trace_journal = None
        self._owns_trace_journal = False
        if tracing:
            self._trace_journal, self._owns_trace_journal = \
                tracing_mod.adopt_or_install(trace_capacity)
        self.last_trace: Optional[Dict[str, Any]] = None
        self._trace_cv = threading.Condition()
        self._trace_dumps: List[Tuple[int, Dict[str, Any], float]] = []
        #: per-checkpoint stats incl. alignment/overtaken/persisted
        #: in-flight accounting aggregated from the subtasks' acks
        self._checkpoint_stats: List[Dict[str, Any]] = []
        self.checkpoint_storage = checkpoint_storage
        self.checkpoint_interval_ms = checkpoint_interval_ms
        #: CheckpointFailureManager policy: storage-failed and timed-out
        #: checkpoints beyond this many CONSECUTIVE failures fail the
        #: execution, which the restart loop recovers from the latest
        #: completed checkpoint (-1 = unlimited tolerance)
        self.failure_manager = CheckpointFailureManager(
            tolerable_failed_checkpoints)
        self.checkpoint_timeout_s = checkpoint_timeout_s
        #: restart attempts performed by the current run() — exported with
        #: the failure manager's counters on a job-scope metric group
        self._restarts = 0
        from flink_tpu.metrics.groups import (MetricRegistry,
                                              job_checkpoint_metrics)
        self.metrics_registry = MetricRegistry()
        self.job_metric_group = job_checkpoint_metrics(
            self.metrics_registry.job_manager_group(), self.failure_manager,
            lambda: self._restarts)
        #: local recovery: workers keep secondary snapshot copies under
        #: this directory and restore from them on same-worker restarts
        #: (``state.backend.local-recovery`` analog); stats from workers
        #: land in ``recovery_stats`` as (worker, local_hits, remote_reads)
        self.local_recovery_dir = local_recovery_dir
        self.recovery_stats: List[Tuple[int, int, int]] = []
        #: run fingerprint: local-store entries are scoped to ONE cluster
        #: run — a reused local_recovery_dir must never serve a previous
        #: run's chk-N files (checkpoint ids restart at 1 per run)
        import uuid
        self.run_token = uuid.uuid4().hex[:16]
        self.extra_sys_path = tuple(extra_sys_path)
        #: optional SecurityConfig: mutual TLS on control + data plane and/or
        #: an HMAC token handshake on worker registration
        self.security = security
        #: spawn=True runs workers as local subprocesses; spawn=False only
        #: LISTENS — workers are started externally (k8s pods, other hosts)
        #: and dial in with `flink_tpu worker --coordinator host:port`
        self.spawn = spawn
        _require_secure_bind(bind_host, security,
                             "ProcessCluster control plane")
        self.bind_host = bind_host
        self.listen_port = listen_port
        #: worker-loss recovery (spawn=True only): a failed execution is
        #: retried up to this many times, restoring from the LATEST
        #: completed checkpoint — the full-restart failover strategy (the
        #: all-to-all edges make the whole job one pipelined region)
        self.restart_attempts = restart_attempts
        self.restart_delay_ms = restart_delay_ms
        #: prefer IN-PLACE recovery on worker loss (respawn the dead
        #: process, redeploy tasks from the latest checkpoint, keep
        #: surviving processes up) over a full-cluster restart
        self.worker_recovery = worker_recovery
        self._recovering = False
        self._reset_cv = threading.Condition()
        self._reset_acks: set = set()
        self._lock = threading.Lock()
        self._next_cid = 1
        self._completed_ids: List[int] = []
        self._counts: Dict[str, int] = {}
        #: queryable serving tier (ISSUE-9): checkpoint-consistency read
        #: replicas fed by this coordinator's checkpoint stream (live views
        #: live in the worker processes — the coordinator serves the
        #: replica tier; see enable_queryable)
        self.queryable = None
        self._reset_attempt()

    def _reset_attempt(self) -> None:
        """Fresh per-execution state (checkpoint ids keep increasing)."""
        #: generation guard: event threads of a PREVIOUS attempt (late EOFs
        #: from killed workers) must not touch this attempt's state
        self._gen = getattr(self, "_gen", 0) + 1
        self._states: Dict[Tuple[str, int], str] = {}
        self._state_log: List[Tuple[str, int, str]] = []
        self._last_recovery: Optional[str] = None
        self._finals: Dict[Tuple[str, int], Dict[str, Any]] = {}
        self._rows: Dict[Tuple[str, int], List[Dict[str, Any]]] = {}
        self._pending: Optional[_Pending] = None
        self._failed: Optional[str] = None
        #: previous completed checkpoint as a RESOLVED (increment-free)
        #: tree — the base increment acks of the next cut resolve against;
        #: reset per attempt (a restored execution's first cut is full)
        self._latest_resolved: Optional[Dict[str, Any]] = None
        self._done_workers: set = set()
        #: control connections that hit EOF this attempt: collect_trace
        #: must not wait its full timeout on a worker that can never
        #: answer (a SIGKILLed worker's socket EOFs long before reaping)
        self._dead_conn_idx: set = set()
        self._all_done = threading.Event()
        self._conns: Dict[int, socket.socket] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        #: per-worker serving registrations: state -> {uid, parallelism,
        #: max_parallelism, endpoints: {subtask: (host, port)}} — the
        #: routing table the coordinator's server advertises to clients
        self._qserve_states: Dict[str, Dict[str, Any]] = {}

    # -- queryable serving tier -------------------------------------------
    def enable_queryable(self, name: str, uid: str, agg, key_column: str,
                         output_column: str = "result",
                         max_parallelism: int = 128):
        """Serve ``uid``'s keyed window state at checkpoint consistency:
        a :class:`~flink_tpu.queryable.replica.CheckpointReplica` fed by
        this coordinator's checkpoint stream (and, when a checkpoint
        storage is configured, able to tail it from any process).  Live
        reads live inside the worker processes and are not proxied here —
        the replica tier is exactly what a cross-process serving fleet
        reads, so queries never touch a worker's hot path.  Returns the
        service; call :meth:`queryable_stats` for the staleness view and
        ``start_queryable_server`` for the TCP front end."""
        from flink_tpu.queryable.replica import QueryableStateSpec
        from flink_tpu.queryable.service import QueryableStateService
        if self.queryable is None:
            self.queryable = QueryableStateService()
        self.queryable.add_replica(
            name, QueryableStateSpec(name, uid, key_column, agg,
                                     output_column=output_column),
            storage=self.checkpoint_storage, max_parallelism=max_parallelism)
        return self.queryable

    def start_queryable_server(self, host: str = "127.0.0.1",
                               port: int = 0):
        if self.queryable is None:
            from flink_tpu.queryable.service import QueryableStateService
            self.queryable = QueryableStateService()
        server = self.queryable.start_server(host=host, port=port)
        # replay the worker endpoint map collected so far: a client's
        # {"routing": true} against this server routes live reads straight
        # to the owning workers (the coordinator serves only the replica
        # tier and the map itself)
        with self._lock:
            # copy the INNER endpoints dict too: the qserve handler keeps
            # mutating the live one under this lock while the registry
            # iterates the replayed copy under its own
            snapshot = {name: {**info, "endpoints": dict(info["endpoints"])}
                        for name, info in self._qserve_states.items()}
        for name, info in snapshot.items():
            self.queryable.set_state_endpoints(
                name, info["endpoints"], parallelism=info["parallelism"],
                max_parallelism=info["max_parallelism"])
        return server

    def queryable_stats(self):
        return self.queryable.stats() if self.queryable is not None else None

    def queryable_endpoints(self) -> Dict[str, Dict[int, Tuple[str, int]]]:
        """state -> {subtask: (host, port)} as registered by the workers'
        per-worker serving tiers (empty until a deploy with queryable
        operators completes)."""
        with self._lock:
            return {name: dict(info["endpoints"])
                    for name, info in self._qserve_states.items()}

    # -- cross-process trace assembly --------------------------------------
    def collect_trace(self, timeout_s: float = 15.0) -> Dict[str, Any]:
        """Pull every live worker's span ring over the control plane and
        merge them — with per-worker clock-offset estimation — into ONE
        Chrome trace-event timeline (Perfetto-loadable).  Workers that
        died or time out are simply absent from the merge."""
        from flink_tpu.observability.assembly import merge_timelines
        from flink_tpu.utils import clock as _clock

        with self._trace_cv:
            self._trace_dumps = []
        t0_ms = float(_clock.now_ms())
        conns = [i for i in self._conns if i not in self._dead_conn_idx]
        for idx in conns:
            self._to_worker(idx, ("trace_request",))
        deadline = time.monotonic() + timeout_s
        with self._trace_cv:
            while time.monotonic() < deadline:
                # recompute the live set every pass: a worker dying
                # MID-collect must shrink what we wait for, not stall
                # the merge until the full timeout.  Match by INDEX, not
                # count — a worker that answers and THEN dies would
                # otherwise satisfy another live worker's quota
                answered = {d[0] for d in self._trace_dumps}
                if all(i in answered or i in self._dead_conn_idx
                       for i in conns):
                    break
                self._trace_cv.wait(timeout=0.2)
            dumps = list(self._trace_dumps)
        j = self._trace_journal
        merged = merge_timelines(j.snapshot() if j is not None else None,
                                 dumps, t0_ms=t0_ms)
        merged["otherData"]["requested_workers"] = len(conns)
        self.last_trace = merged
        return merged

    # -- lifecycle ---------------------------------------------------------
    def run(self, timeout_s: float = 180.0,
            restore: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Execute, restarting from the latest completed checkpoint on
        failure (up to ``restart_attempts`` times, spawned workers only).

        Collect-sink rows come from the FINAL execution; since r3 the
        CollectSink checkpoints its collected rows, so recovery from a
        completed checkpoint preserves pre-checkpoint rows (exactly-once
        for collect too).  Production delivery still belongs to the
        transactional sinks (``connectors/sinks.py``,
        ``connectors/log_service.py``) — the collect path keeps its whole
        result in memory/checkpoints by design."""
        from flink_tpu.observability import tracing as tracing_mod

        restore = self._ha_takeover(restore)
        original_restore = restore
        if self.tracing:
            # shared ownership state machine with MiniCluster.execute —
            # per-execution reset of an owned coordinator ring, fresh ring
            # when an adopted one's owner released, re-adoption otherwise
            self._trace_journal, self._owns_trace_journal = \
                tracing_mod.acquire_for_execution(
                    self._trace_journal, self._owns_trace_journal,
                    capacity=int(self.ckpt_opts.get("trace_capacity")
                                 or 65536))
        j, owned = self._trace_journal, self._owns_trace_journal
        try:
            return self._run_attempts(timeout_s, restore, original_restore)
        finally:
            self._ha_shutdown()
            # self._trace_journal/last_trace keep serving afterwards
            tracing_mod.release_after_execution(j, owned)

    # -- coordinator HA -----------------------------------------------------
    @classmethod
    def from_ha(cls, ha_store, job_id: str, checkpoint_storage=None,
                **overrides) -> "ProcessCluster":
        """Standby takeover: rebuild a coordinator for a job REGISTERED in
        the HA store (``register_job`` persisted the reference + settings
        under the registering leader's epoch).  ``run()`` then acquires
        the lease at epoch+1 and restores from the completed-checkpoint
        pointer."""
        payload = ha_store.load_job(job_id)
        kw = dict(payload.get("settings") or {})
        kw.update(overrides)
        kw.setdefault("n_workers", payload.get("n_workers", 2))
        return cls(payload["job"], checkpoint_storage=checkpoint_storage,
                   ha_store=ha_store, ha_job_id=job_id, **kw)

    def _ha_takeover(self, restore):
        """Acquire the leader lease (epoch+1 over any predecessor),
        register the job, resolve the restore from the HA
        completed-checkpoint pointer, and start renewing.  Returns the
        (possibly pointer-resolved) restore."""
        if self.ha_store is None:
            return restore
        from flink_tpu.runtime import ha as ha_mod

        holder = f"coordinator-{os.getpid()}-{self.run_token}"
        self._lease = self.ha_store.acquire(
            holder, self.ha_lease_ttl_s,
            timeout_s=max(30.0, 10 * self.ha_lease_ttl_s))
        self._epoch = self._lease.epoch
        # epoch-partitioned checkpoint ids: this incarnation can never
        # collide with a zombie predecessor writing the same directory
        self._next_cid = max(self._next_cid,
                             (self._epoch - 1) * _CID_EPOCH_STRIDE + 1)
        self.ha_store.register_job(
            self.ha_job_id,
            {"job": self.job, "n_workers": self.n_workers,
             "settings": {
                 "checkpoint_interval_ms": self.checkpoint_interval_ms,
                 "checkpoint_timeout_s": self.checkpoint_timeout_s,
                 "incremental": bool(self.ckpt_opts.get("incremental"))}},
            self._epoch)
        if restore is None:
            restore, src = ha_mod.resolve_restore(
                self.ha_store, self.ha_job_id, self.checkpoint_storage)
            self.ha_restore_source = src
        if self.checkpoint_storage is not None \
                and hasattr(self.checkpoint_storage, "pin_provider"):
            # retention pinning (satellite 2): the storage re-reads the
            # HA pointer FRESH at every eviction pass, so even a stale
            # leader's concurrent retention never evicts the pointed-at
            # cut (or its increment chain)
            store, job_id = self.ha_store, self.ha_job_id

            def _ha_pin() -> Optional[int]:
                ptr = store.completed_checkpoint(job_id)
                return ptr["checkpoint_id"] if ptr else None

            self.checkpoint_storage.pin_provider = _ha_pin
        self._renewer = ha_mod.LeaseRenewer(
            self.ha_store, self._lease, self.ha_lease_ttl_s,
            on_lost=self._ha_demoted)
        self._renewer.start()
        return restore

    def _ha_shutdown(self) -> None:
        if self._renewer is not None:
            self._renewer.stop()
            self._renewer.join()
            # release only a lease we still hold and cleanly finished
            # with, so a successor skips the TTL wait; a LOST lease (or
            # an injected renewal fault) belongs to whoever took it
            if self._renewer.lost is None and self._lease is not None:
                try:
                    self.ha_store.release(self._renewer.lease)
                except Exception:  # noqa: BLE001
                    pass
            self._renewer = None

    def _ha_demoted(self, exc: Exception) -> None:
        """Lease renewal failed (TTL expired under us, a new leader took
        over, or an injected ``ha.lease`` truncation): demote LOUDLY —
        fail the run so nothing further completes under the stale epoch."""
        with self._lock:
            if self._failed is None:
                self._failed = (f"leader lease lost (epoch {self._epoch}): "
                                f"{exc}")
            self._all_done.set()

    def ha_status(self) -> Dict[str, Any]:
        """HA panel: leader epoch, lease, fence counters, restore source —
        what the REST ``/jobs/<id>/ha`` endpoint serves."""
        lease = self._renewer.lease if self._renewer is not None \
            else self._lease
        lost = self._renewer.lost if self._renewer is not None else None
        return {"enabled": self.ha_store is not None,
                "leader_epoch": self._epoch,
                "job_id": self.ha_job_id,
                "holder": lease.holder if lease is not None else None,
                "lease_deadline": lease.deadline if lease is not None
                else None,
                "demoted": lost is not None,
                "restore_source": self.ha_restore_source,
                "fenced_completions": self.ha_fenced_completions,
                "fenced_worker_msgs": self.fenced_worker_msgs}

    def _run_attempts(self, timeout_s: float,
                      restore: Optional[Dict[str, Any]],
                      original_restore: Optional[Dict[str, Any]]
                      ) -> Dict[str, Any]:
        attempt = 0
        self._restarts = 0
        while True:
            self._restarts = attempt
            if attempt > 0:
                self._reset_attempt()
                self.failure_manager.on_job_restart()
                # restore from this run's newest completed checkpoint
                # (under HA, the store's completed-checkpoint POINTER is
                # consulted first — the same truth a standby leader uses),
                # else the restore the CALLER supplied (a savepoint must
                # not silently drop)
                restore = self._latest_restore(original_restore)
            res = self._run_once(timeout_s, restore, attempt)
            res["attempts"] = attempt + 1
            if res["state"] == "FINISHED" or attempt >= self.restart_attempts \
                    or not self.spawn:
                return res
            attempt += 1
            time.sleep(self.restart_delay_ms / 1000.0)

    def _setup_source_coordinator(self, plan, restore) -> None:
        """Enumerators live HERE, on the coordinator
        (``SourceCoordinator.java:75``); readers request splits via
        split_request control messages.  Restore reconciles reader-owned
        splits (in-flight + consumed) into the assigned sets."""
        from flink_tpu.connectors.enumerator import SourceCoordinator

        self._source_coordinator = SourceCoordinator()
        for v in plan.vertices:
            if v.is_source:
                src = v.chain[0].source
                factory = getattr(src, "create_enumerator", None)
                if factory is not None:
                    self._source_coordinator.register(v.uid, factory())
        if restore:
            self._source_coordinator.restore(restore.get("__enumerators__"))
            for uid, enum in self._source_coordinator._enums.items():
                for s in (restore.get(uid) or {}).get("subtasks", []):
                    if not s:
                        continue
                    if s.get("current_split") is not None:
                        enum.reclaim(s["current_split"])
                    for fs in s.get("finished_splits", []):
                        enum.reclaim(fs)

    def _run_once(self, timeout_s: float,
                  restore: Optional[Dict[str, Any]],
                  attempt: int = 0) -> Dict[str, Any]:
        plan = build_plan(self.job)
        # shipped with every deploy; workers verify their own rebuild
        # against it (nondeterministic job builders fail fast)
        self._plan_digest = plan_structure_digest(plan)
        self._counts, _ = subtask_counts_of(plan)
        if restore:
            # a restore taken at a DIFFERENT parallelism (an autoscaler
            # cut, a resized redeploy) redistributes through the key-group
            # path — persisted in-flight channel state included — before
            # it ships to the workers; matching snapshots pass untouched
            from flink_tpu.cluster.adaptive import maybe_rescale_restore
            restore = maybe_rescale_restore(restore, plan)
        all_subtasks = {(uid, i) for uid, n in self._counts.items()
                        for i in range(n)}
        self._setup_source_coordinator(plan, restore)
        # NOTE: no implicit load_latest() here — a fresh run with a reused
        # --checkpoint-dir starts fresh unless the caller passed an explicit
        # restore (the reference's -s savepoint semantics); the restart loop
        # in run() consults the latest checkpoint only for attempt > 0
        srv = socket.create_server((self.bind_host, self.listen_port))
        _, cport = srv.getsockname()[:2]
        self.control_port = cport
        procs: List[subprocess.Popen] = []
        if self.spawn:
            self._spawn_env = env = dict(os.environ)
            env["PYTHONPATH"] = os.pathsep.join(
                (*self.extra_sys_path, *sys.path, env.get("PYTHONPATH", "")))
            if self.security is not None:
                if self.security.internal_ssl:
                    env["FLINK_TPU_SSL_CERT"] = self.security.cert_path
                    env["FLINK_TPU_SSL_KEY"] = self.security.key_path
                    env["FLINK_TPU_SSL_CA"] = self.security.ca_path
                if self.security.auth_token:
                    env["FLINK_TPU_AUTH_TOKEN"] = self.security.auth_token
            # failure-injection hooks / logs can key on the execution attempt
            env["FLINK_TPU_ATTEMPT"] = str(attempt)
            if self.local_recovery_dir:
                env["FLINK_TPU_LOCAL_RECOVERY"] = self.local_recovery_dir
                env["FLINK_TPU_RUN_TOKEN"] = self.run_token
            procs = [self._spawn_worker(i, cport)
                     for i in range(self.n_workers)]
        self._procs = procs  # chaos tests / operators can observe pids
        try:
            # spawned workers register within seconds; external (pod) workers
            # may take as long as the cluster scheduler needs.  The limit is
            # an OVERALL deadline — stray connections (probes/scans) must
            # not keep resetting it
            reg_deadline = time.monotonic() + (90 if self.spawn
                                               else timeout_s)
            server_ctx = (self.security.server_context()
                          if self.security is not None
                          and self.security.internal_ssl else None)
            need_token = (self.security is not None
                          and bool(self.security.auth_token))
            addresses: Dict[int, Tuple[str, int]] = {}
            hello_conns: List[Tuple[int, socket.socket]] = []
            tmp_lock = threading.Lock()
            try:
                self._register_workers(srv, server_ctx, need_token,
                                       addresses, hello_conns, tmp_lock,
                                       reg_deadline)
            except socket.timeout:
                # a worker that died before saying hello (startup crash)
                # must yield a FAILED result the restart loop can retry,
                # not an escaped exception
                for _i, c in hello_conns:
                    try:
                        c.close()
                    except OSError:
                        pass
                self._failed = (f"worker registration timed out "
                                f"({len(hello_conns)}/{self.n_workers} "
                                f"registered)")
                return {"state": "FAILED", "error": self._failed,
                        "rows": [], "recoveries": 0,
                        "completed_checkpoints": list(self._completed_ids)}
            for idx, conn in hello_conns:
                self._conns[idx] = conn
                self._send_locks[idx] = threading.Lock()
            threads = []
            for idx, conn in hello_conns:
                th = threading.Thread(target=self._serve_worker,
                                      args=(idx, conn), daemon=True)
                th.start()
                threads.append(th)
            for idx in self._conns:
                self._to_worker(idx, ("deploy", addresses, restore, None,
                                      self._plan_digest, self.ckpt_opts))
            if self.ckpt_opts.get("orphan_timeout_s"):
                self._ping_stop = threading.Event()
                threading.Thread(target=self._ping_loop,
                                 args=(self._ping_stop,),
                                 daemon=True).start()
            if self.checkpoint_interval_ms > 0:
                # the ticker loops on ITS attempt's event (self._all_done
                # is replaced between restart attempts/recoveries)
                threading.Thread(
                    target=self._checkpoint_loop,
                    args=(all_subtasks, self._all_done), daemon=True).start()
            # ---- main wait, with SURVIVING-WORKER recovery: a dead worker
            # process is respawned and only the TASKS redeploy (from the
            # latest checkpoint, everywhere — consistency); surviving
            # worker processes stay up with their data-plane addresses
            # (the local-recovery posture; with all-to-all keyed edges the
            # whole job is one pipelined region, so all tasks roll back,
            # but no surviving process restarts)
            deadline = time.monotonic() + timeout_s
            recoveries = 0
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._all_done.wait(
                        timeout=remaining):
                    self._failed = self._failed or "timeout"
                    break
                if self._failed is None:
                    break                   # finished cleanly
                dead = [i for i, p in enumerate(procs)
                        if p.poll() is not None]
                if not dead and self._failed and "died" in str(self._failed):
                    # SIGKILL delivery/reaping can lag the control-plane
                    # EOF by a moment: give the child a beat to be
                    # observable before falling back to a full restart
                    end = time.monotonic() + 5
                    while not dead and time.monotonic() < end:
                        time.sleep(0.05)
                        dead = [i for i, p in enumerate(procs)
                                if p.poll() is not None]
                if not (self.spawn and self.worker_recovery and dead
                        and recoveries < self.restart_attempts
                        and time.monotonic() < deadline):
                    break                   # full-restart path handles it
                recoveries += 1
                time.sleep(self.restart_delay_ms / 1000.0)
                self._recover_workers(plan, procs, dead, addresses, srv,
                                      server_ctx, need_token, cport,
                                      restore)
                if self.checkpoint_interval_ms > 0:
                    threading.Thread(
                        target=self._checkpoint_loop,
                        args=(all_subtasks, self._all_done),
                        daemon=True).start()
            # assemble the merged cross-worker timeline BEFORE stopping
            # the workers (their control loops must still answer).  The
            # latency panel rides the same collection, and a latency
            # interval WITHOUT tracing still deserves its histograms —
            # the workers answer trace_request with journal=None then.
            trace = None
            latency_rows = None
            if self.tracing or self.ckpt_opts.get("latency_interval_ms"):
                merged = self.collect_trace()
                rows = merged["otherData"].get("latency") or []
                # the documented contract: latency_interval_ms alone
                # always yields result["latency"] — an empty panel (no
                # marker observed before the job finished) is an empty
                # list, not a missing key
                if rows or self.ckpt_opts.get("latency_interval_ms"):
                    latency_rows = rows
                if self.tracing:
                    trace = merged
            for idx in self._conns:
                self._to_worker(idx, ("stop",))
            for p in procs:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()
            state = "FAILED" if self._failed else "FINISHED"
            rows: List[Dict[str, Any]] = []
            for key in sorted(self._rows):
                rows.extend(self._rows[key])
            return {"state": state, "error": self._failed, "rows": rows,
                    "recoveries": recoveries,
                    "completed_checkpoints": list(self._completed_ids),
                    "failed_checkpoints": self.failure_manager.num_failed(),
                    "checkpoint_stats": list(self._checkpoint_stats),
                    **({"trace": trace} if trace is not None else {}),
                    **({"latency": latency_rows}
                       if latency_rows is not None else {})}
        finally:
            self._all_done.set()   # stop this attempt's checkpoint ticker
            if getattr(self, "_ping_stop", None) is not None:
                self._ping_stop.set()
            srv.close()
            # close control connections so stale _serve_worker threads
            # unblock, and reap every child before a potential retry
            for conn in self._conns.values():
                try:
                    conn.close()
                except OSError:
                    pass
            for p in procs:
                if p.poll() is None:
                    p.kill()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass

    def _spawn_worker(self, index: int, cport: int) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "flink_tpu", "worker",
             "--index", str(index), "--workers", str(self.n_workers),
             "--job", self.job, "--coordinator", f"127.0.0.1:{cport}"],
            env=self._spawn_env)

    def _respawn_and_register(self, procs, dead, addresses, srv, server_ctx,
                              need_token: bool, cport: int) -> bool:
        """Respawn the dead worker processes and register ONLY them; wires
        their control connections + serve threads.  False = registration
        failed (the attempt was marked FAILED)."""
        for i in dead:
            procs[i] = self._spawn_worker(i, cport)
        new_addr: Dict[int, Tuple[str, int]] = {}
        new_conns: List[Tuple[int, socket.socket]] = []
        try:
            self._register_workers(srv, server_ctx, need_token, new_addr,
                                   new_conns, threading.Lock(),
                                   time.monotonic() + 90,
                                   expected=len(dead), allowed=set(dead))
        except socket.timeout:
            with self._lock:
                self._failed = "respawned worker failed to register"
                self._all_done.set()
            self._recovering = False
            return False
        addresses.update(new_addr)
        for idx, conn in new_conns:
            self._conns[idx] = conn
            self._send_locks[idx] = threading.Lock()
            with self._lock:
                # the respawned worker's NEW control conn can answer
                # trace_requests again — leaving it in the dead set would
                # silently drop its ring from the merged timeline on
                # exactly the recovered-worker runs the trace explains
                self._dead_conn_idx.discard(idx)
            threading.Thread(target=self._serve_worker, args=(idx, conn),
                             daemon=True).start()
        return True

    def _latest_restore(self, original_restore):
        """This run's newest completed checkpoint, else the original
        restore the run started from.  A load failure (corrupt increment
        chain, transient read error) falls back to progressively older
        completed checkpoints — recovery must not die on one bad file.

        Under HA the store's completed-checkpoint pointer is truth
        (satellite 2): it survives coordinator death, so a restarted or
        standby leader restores exactly the cut the last leader durably
        completed; the directory scan stays as a logged fallback inside
        :func:`flink_tpu.runtime.ha.resolve_restore`."""
        if self.ha_store is not None:
            from flink_tpu.runtime import ha as ha_mod
            snap, src = ha_mod.resolve_restore(
                self.ha_store, self.ha_job_id, self.checkpoint_storage)
            if snap is not None:
                self.ha_restore_source = src
                return snap
        if self.checkpoint_storage is not None and self._completed_ids:
            for cid in sorted(self._completed_ids, reverse=True):
                try:
                    return self.checkpoint_storage.load(cid)
                except Exception:  # noqa: BLE001
                    continue
        return original_restore

    def _affected_region_subtasks(self, plan, dead) -> Optional[set]:
        """(vertex_uid, i) set of the pipelined regions touched by the dead
        workers, or None when region-scoped recovery does not apply (the
        whole job is affected, or a runtime-enumerated source shares
        enumerator state across regions)."""
        from flink_tpu.cluster.failover import subtask_regions

        counts, splits_by_vertex = subtask_counts_of(plan)
        if any(s is None for s in splits_by_vertex.values()):
            return None     # dynamic enumerator: shared coordinator state
        assign = assign_subtasks(plan, counts, self.n_workers)
        dead_subs = {st for st, w in assign.items() if w in set(dead)}
        affected: set = set()
        for region in subtask_regions(plan, counts):
            if region & dead_subs:
                affected |= region
        if not affected or affected == set(assign):
            return None     # everything (or nothing) affected: full path
        return affected

    def _recover_workers(self, plan, procs, dead, addresses, srv,
                         server_ctx, need_token: bool, cport: int,
                         original_restore) -> None:
        """In-place recovery: quiesce (only the affected regions of)
        survivors, respawn the dead worker processes, redeploy the affected
        tasks from this run's latest checkpoint.  Surviving processes (and
        their data-plane servers) never restart, and with region-scoped
        recovery the surviving regions' TASKS keep running too — the
        reference's ``RestartPipelinedRegionFailoverStrategy`` + local
        recovery."""
        affected = self._affected_region_subtasks(plan, dead)
        if affected is not None:
            return self._recover_regions(plan, procs, dead, affected,
                                         addresses, srv, server_ctx,
                                         need_token, cport, original_restore)
        self._last_recovery = "full"
        self._recovering = True
        old_done = self._all_done
        survivors = [i for i in range(self.n_workers) if i not in dead]
        # 1. quiesce survivors (tasks cancel, channels drop, process stays)
        with self._reset_cv:
            self._reset_acks = set()
        for i in survivors:
            self._to_worker(i, ("reset",))
        end = time.monotonic() + 30
        with self._reset_cv:
            while not set(survivors) <= self._reset_acks \
                    and time.monotonic() < end:
                self._reset_cv.wait(timeout=1.0)
        # 2. respawn dead workers and register ONLY them
        if not self._respawn_and_register(procs, dead, addresses, srv,
                                          server_ctx, need_token, cport):
            return
        # 3. fresh attempt state (conns, gen and serve threads survive)
        with self._lock:
            self._states = {}
            self._finals = {}
            self._rows = {}
            self._pending = None
            self._failed = None
            # the redeploy restores operators, so their first cut is a
            # full base — the old resolution base is no longer the parent
            self._latest_resolved = None
            self._done_workers = set()
            self._all_done = threading.Event()
            # failover: in-flight checkpoint attempts die with the old
            # execution, so the continuous-failure window restarts too
            self.failure_manager.on_job_restart()
        old_done.set()  # stop the previous checkpoint ticker
        # 4. redeploy from this run's latest completed checkpoint
        restore = self._latest_restore(original_restore)
        self._setup_source_coordinator(plan, restore)
        self._recovering = False
        for idx in self._conns:
            self._to_worker(idx, ("deploy", addresses, restore, None,
                                  self._plan_digest, self.ckpt_opts))

    def _recover_regions(self, plan, procs, dead, affected: set, addresses,
                         srv, server_ctx, need_token: bool, cport: int,
                         original_restore) -> None:
        """Region-scoped recovery (VERDICT r2 #6): only the pipelined
        regions touched by the dead workers roll back; every other region's
        tasks keep RUNNING throughout — matching
        ``RestartPipelinedRegionFailoverStrategy.java``."""
        self._last_recovery = "region"
        self._recovering = True
        old_done = self._all_done
        counts, _ = subtask_counts_of(plan)
        assign = assign_subtasks(plan, counts, self.n_workers)
        touched_workers = {assign[st] for st in affected}
        survivors_touched = sorted(touched_workers - set(dead))
        # 1. cancel ONLY affected tasks on touched survivors
        with self._reset_cv:
            self._reset_acks = set()
        for i in survivors_touched:
            self._to_worker(i, ("reset_tasks", sorted(affected)))
        end = time.monotonic() + 30
        with self._reset_cv:
            while not set(survivors_touched) <= self._reset_acks \
                    and time.monotonic() < end:
                self._reset_cv.wait(timeout=1.0)
        # 2. respawn dead workers and register ONLY them
        if not self._respawn_and_register(procs, dead, addresses, srv,
                                          server_ctx, need_token, cport):
            return
        # 3. reset ONLY the affected tasks' bookkeeping; unaffected
        # regions' states, finals and collected rows stay
        with self._lock:
            for key in affected:
                self._states.pop(key, None)
                self._finals.pop(key, None)
                self._rows.pop(key, None)
            self._pending = None            # in-flight checkpoint aborts
            self._failed = None
            # _latest_resolved survives region recovery ON PURPOSE: the
            # unaffected regions' operators keep their increment chains
            # (anchored at the last completed cut == _latest_resolved),
            # while the affected regions restore and ack full cuts that
            # replace their subtrees wholesale during resolution
            # region failover restarts the continuous-failure window, same
            # as a full restart (MiniCluster does this per region restart)
            self.failure_manager.on_job_restart()
            self._done_workers -= touched_workers
            self._all_done = threading.Event()
        old_done.set()  # stop the previous checkpoint ticker
        # 4. redeploy the affected regions from the latest checkpoint
        restore = self._latest_restore(original_restore)
        self._recovering = False
        only = sorted(affected)
        for idx in sorted(touched_workers):
            self._to_worker(idx, ("deploy", addresses, restore, only,
                                  self._plan_digest, self.ckpt_opts))

    def _register_workers(self, srv, server_ctx, need_token: bool,
                          addresses: Dict[int, Tuple[str, int]],
                          hello_conns: List[Tuple[int, socket.socket]],
                          tmp_lock: threading.Lock,
                          deadline: float,
                          expected: Optional[int] = None,
                          allowed: Optional[set] = None) -> None:
        """Accept until ``expected`` (default: all) workers said a valid
        hello; raises ``socket.timeout`` once the OVERALL deadline passes.
        ``allowed`` restricts acceptable worker indices (recovery accepts
        only the respawned ones)."""
        target = self.n_workers if expected is None else expected
        while len(hello_conns) < target:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("worker registration deadline")
            srv.settimeout(remaining)
            conn, _addr = srv.accept()
            # a stray connection (readiness probe, port scan, wrong token)
            # must neither consume a registration slot nor fail the job —
            # drop it and keep accepting
            try:
                # timeout BEFORE the TLS handshake: a silent connection
                # must not park the accept loop inside wrap_socket
                conn.settimeout(30)
                if server_ctx is not None:
                    conn = server_ctx.wrap_socket(conn, server_side=True)
                nonce = os.urandom(32) if need_token else None
                _send_json(conn, {"kind": "challenge",
                                  "nonce": nonce.hex() if nonce else None},
                           tmp_lock)
                # the hello is JSON (parsed, never unpickled) and the HMAC
                # is verified BEFORE this connection graduates to the
                # pickle control protocol
                msg = _recv_json(conn)
                if not isinstance(msg, dict) or msg.get("kind") != "hello":
                    conn.close()
                    continue
                idx, host = msg.get("index"), msg.get("host")
                port, mac_hex = msg.get("port"), msg.get("mac")
                if not isinstance(idx, int) \
                        or not 0 <= idx < self.n_workers \
                        or idx in addresses \
                        or (allowed is not None and idx not in allowed) \
                        or not isinstance(host, str) \
                        or not isinstance(port, int):
                    conn.close()
                    continue
                if need_token:
                    try:
                        mac = bytes.fromhex(mac_hex or "")
                    except (TypeError, ValueError):
                        mac = b""  # non-string / malformed hex: fails verify
                    if not self.security.verify(nonce, mac):
                        conn.close()
                        continue
                conn.settimeout(None)
            except socket.timeout:
                # per-connection stall, NOT the accept timeout: drop it
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            except (OSError, ValueError, pickle.UnpicklingError):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            addresses[idx] = (host, port)
            hello_conns.append((idx, conn))

    def _to_worker(self, idx: int, msg) -> None:
        # every control message carries the leader epoch as its last
        # element (ISSUE-20); epoch 0 = HA off, workers admit everything
        msg = tuple(msg) + (self._epoch,)
        try:
            _send_msg(self._conns[idx], msg, self._send_locks[idx])
        except OSError:
            pass

    def _ping_loop(self, stop: threading.Event) -> None:
        """Leader liveness pings: workers reset their orphan-reaper
        deadline on every control message, so a quiet-but-alive leader
        (long checkpoint interval, idle job) keeps its workers."""
        while not stop.wait(self.ping_interval_s):
            for idx in list(self._conns):
                if idx not in self._dead_conn_idx:
                    self._to_worker(idx, ("ping",))

    # -- per-worker event loop --------------------------------------------
    def _serve_worker(self, idx: int, conn: socket.socket) -> None:
        gen = self._gen
        while True:
            try:
                msg = _recv_msg(conn)
            except OSError:
                msg = None
            if gen != self._gen:
                return  # a restart superseded this attempt: stale thread
            if msg is None:
                with self._lock:
                    if gen == self._gen:
                        # done or not, this conn can never answer a
                        # trace_request again — unblock any collector
                        self._dead_conn_idx.add(idx)
                    if gen == self._gen and idx not in self._done_workers \
                            and self._failed is None:
                        self._failed = f"worker {idx} died"
                        self._all_done.set()
                with self._trace_cv:
                    self._trace_cv.notify_all()
                return
            kind = msg[0]
            if kind == "state":
                _, uid, i, state, error = msg
                with self._lock:
                    self._states[(uid, i)] = state
                    # full transition history (tests/observability: proves
                    # which subtasks restarted during a recovery)
                    self._state_log.append((uid, i, state))
                    if state == "FAILED" and self._failed is None:
                        self._failed = f"{uid}[{i}]: {error}"
                        self._all_done.set()
                    p = self._pending
                    if state == "FINISHED" and p is not None \
                            and (uid, i) not in p.acks:
                        p.expected.discard((uid, i))
                        if len(p.acks) >= len(p.expected):
                            self._complete(p)
            elif kind == "plan_mismatch":
                _, widx, local, expected = msg
                with self._lock:
                    if self._failed is None:
                        self._failed = (
                            f"worker {widx} rebuilt a DIFFERENT plan "
                            f"(structure digest {local} != coordinator's "
                            f"{expected}): the job builder is "
                            f"nondeterministic — deploy rejected")
                        self._all_done.set()
            elif kind == "recovery_stats":
                with self._lock:
                    self.recovery_stats.append((msg[1], msg[2], msg[3]))
            elif kind == "qserve":
                # per-worker serving registration: merge this worker's
                # (state -> local subtasks) at its advertised endpoint
                # into the routing map (a respawned worker re-registers
                # with its NEW port — stale client maps self-heal on
                # their next refresh)
                widx, advertise, host, port = msg[1:5]
                q_epoch = msg[5] if len(msg) > 5 else 0
                if q_epoch and self._epoch and q_epoch < self._epoch:
                    with self._lock:
                        self.fenced_worker_msgs += 1
                    continue
                with self._lock:
                    for name, info in advertise.items():
                        entry = self._qserve_states.setdefault(
                            name, {"uid": info["uid"],
                                   "parallelism": info["parallelism"],
                                   "max_parallelism":
                                       info["max_parallelism"],
                                   "endpoints": {}})
                        entry["parallelism"] = info["parallelism"]
                        entry["max_parallelism"] = info["max_parallelism"]
                        entry["endpoints"].update(
                            {int(i): (host, int(port))
                             for i in info["subtasks"]})
                if self.queryable is not None:
                    for name, info in advertise.items():
                        self.queryable.set_state_endpoints(
                            name, {int(i): (host, int(port))
                                   for i in info["subtasks"]},
                            parallelism=info["parallelism"],
                            max_parallelism=info["max_parallelism"])
            elif kind == "final":
                _, uid, i, snap = msg
                with self._lock:
                    self._finals[(uid, i)] = snap
                    # a completion deferred on this final (state FINISHED
                    # arrived first) proceeds now that the state is whole
                    p = self._pending
                    if p is not None and len(p.acks) >= len(p.expected):
                        self._complete(p)
            elif kind == "ack":
                cid, uid, i, snap = msg[1:5]
                ack_epoch = msg[5] if len(msg) > 5 else 0
                with self._lock:
                    if ack_epoch and self._epoch \
                            and ack_epoch < self._epoch:
                        # a stale incarnation's worker acking into the new
                        # leader: its snapshot belongs to a fenced epoch
                        self.fenced_worker_msgs += 1
                        continue
                    p = self._pending
                    if p is not None and p.cid == cid:
                        p.acks[(uid, i)] = snap
                        if len(p.acks) >= len(p.expected):
                            self._complete(p)
            elif kind == "decline":
                _, cid, uid, i, error = msg
                from flink_tpu.runtime.checkpoint.failure import \
                    CheckpointFailureReason
                with self._lock:
                    p = self._pending
                    if p is not None and p.cid == cid:
                        # abort the attempt, charge the tolerable budget;
                        # the TASK stays up (decline != task failure)
                        self._pending = None
                        self._checkpoint_failure_locked(
                            CheckpointFailureReason.DECLINED, cid,
                            f"{uid}[{i}] declined: {error}")
            elif kind == "split_request":
                _, uid, i = msg
                split, done_flag = self._source_coordinator.request_split(
                    uid, i)
                self._to_worker(idx, ("split_assign", uid, i, split,
                                      done_flag))
            elif kind == "rows":
                _, uid, i, rows = msg
                with self._lock:
                    self._rows[(uid, i)] = rows
            elif kind == "trace_dump":
                from flink_tpu.utils import clock as _clock
                with self._trace_cv:
                    self._trace_dumps.append((msg[1], msg[2],
                                              float(_clock.now_ms())))
                    self._trace_cv.notify_all()
            elif kind == "fenced":
                # a worker rejected one of our messages as stale-epoch:
                # we are a zombie ex-leader — count it (the decisive
                # demotion comes from the HA-store fence / lease loss)
                with self._lock:
                    self.fenced_worker_msgs += 1
            elif kind == "reset_done":
                with self._reset_cv:
                    self._reset_acks.add(msg[1])
                    self._reset_cv.notify_all()
            elif kind == "worker_done":
                with self._lock:
                    self._done_workers.add(msg[1])
                    if len(self._done_workers) >= self.n_workers:
                        self._all_done.set()

    # -- checkpointing -----------------------------------------------------
    def trigger_checkpoint(self, all_subtasks: set) -> Optional[int]:
        from flink_tpu.runtime.checkpoint.failure import \
            CheckpointFailureReason

        with self._lock:
            if self._pending is not None and (
                    self._pending.timer.seconds()
                    >= self.checkpoint_timeout_s):
                # expired: abort + charge the budget (a dead worker's acks
                # will never arrive; failure detection handles the worker)
                expired = self._pending
                self._pending = None
                self._checkpoint_failure_locked(
                    CheckpointFailureReason.TIMEOUT, expired.cid,
                    f"{len(expired.acks)}/{len(expired.expected)} acks "
                    f"after {self.checkpoint_timeout_s}s")
            if self._pending is not None or self._failed is not None \
                    or self._recovering:
                return None
            live = {k for k in all_subtasks
                    if self._states.get(k) != "FINISHED"}
            if not live:
                return None
            cid = self._next_cid
            self._next_cid += 1
            coord = getattr(self, "_source_coordinator", None)
            enums = (coord.snapshot() if coord is not None and coord._enums
                     else None)
            from flink_tpu.observability import tracing as tracing_mod
            tracing_mod.instant("checkpoint.trigger", cat="checkpoint",
                                checkpoint=cid)
            self._pending = _Pending(cid, live, enumerators=enums)
        for idx in self._conns:
            self._to_worker(idx, ("checkpoint", cid))
        return cid

    def _complete(self, p: _Pending) -> None:
        """Assemble + store (caller holds the lock) — mirrors
        ``MiniCluster._complete_checkpoint`` incl. FLIP-147 finals."""
        # a FINISHED subtask's state arrives as two messages (state, then
        # final); completing between them would persist a HOLE for that
        # subtask — and if its worker dies mid-send, the hole would be
        # silently restored later, losing the subtask's entire output.
        # Defer instead: the final's arrival re-runs completion; a lost
        # final leaves the pending to the checkpoint timeout / recovery
        # abort, and restore falls back to the previous intact checkpoint.
        for key, st in self._states.items():
            if st == "FINISHED" and key not in p.acks \
                    and key not in self._finals:
                return
        assembled: Dict[str, Any] = {"__job__": {
            "checkpoint_id": p.cid,
            "run_token": self.run_token,
            "parallelism": dict(self._counts)}}
        if p.enumerators:
            assembled["__enumerators__"] = p.enumerators
        for (uid, i), snap in p.acks.items():
            entry = assembled.setdefault(
                uid, {"subtasks": [None] * self._counts[uid]})
            entry["subtasks"][i] = snap
        for (uid, i), snap in self._finals.items():
            if (uid, i) not in p.acks:
                entry = assembled.setdefault(
                    uid, {"subtasks": [None] * self._counts[uid]})
                entry["subtasks"][i] = snap
        # claim completion BEFORE dropping the lock for storage I/O: late
        # acks for this id are ignored and a new trigger may start
        self._pending = None
        from flink_tpu.runtime.checkpoint.failure import \
            CheckpointFailureReason
        # coordinator HA (ISSUE-20): verify leadership BEFORE any bytes
        # land — a zombie ex-leader must not even write into the shared
        # checkpoint directory.  (The decisive fence is the pointer write
        # below; this pre-check just narrows the window.)
        if self.ha_store is not None and not self._ha_fence_locked(p.cid):
            return
        # incremental checkpoints (ISSUE-16): delta-tracking operators
        # acked increment nodes — resolve them against the previous
        # completed cut so restore/queryable/rescale keep consuming the
        # dense interchange format; increment-capable storage persists the
        # RAW tree (bytes ∝ change rate), everything else the resolved cut
        from flink_tpu.runtime.checkpoint import delta
        has_delta = delta.tree_has_increment(assembled)
        if has_delta:
            try:
                resolved = delta.apply_increments(self._latest_resolved,
                                                  assembled)
            except delta.IncrementChainError as e:
                self._checkpoint_failure_locked(
                    CheckpointFailureReason.STORAGE, p.cid,
                    f"IncrementChainError: {e}")
                return
        else:
            resolved = assembled
        if self.checkpoint_storage is not None:
            store_tree = assembled if (has_delta and getattr(
                self.checkpoint_storage, "supports_increments", False)) \
                else resolved
            # the store (and any retry/backoff wrapper) must not stall the
            # coordinator lock: worker events keep flowing while bytes land
            self._lock.release()
            try:
                try:
                    self.checkpoint_storage.store(p.cid, store_tree)
                except Exception as e:  # noqa: BLE001
                    store_error = f"{type(e).__name__}: {e}"
                else:
                    store_error = None
            finally:
                self._lock.acquire()
            if store_error is not None:
                # abandoned checkpoint, job keeps running — until the
                # tolerable budget is exhausted (then the restart loop
                # recovers from the latest stored checkpoint)
                self._checkpoint_failure_locked(
                    CheckpointFailureReason.STORAGE, p.cid, store_error)
                return
        # THE zombie fence: advancing the HA completed-checkpoint pointer
        # re-verifies the store epoch atomically — a checkpoint only
        # COMPLETES (and workers only get notify, so 2PC only commits) if
        # this coordinator still holds the current epoch
        if self.ha_store is not None and not self._ha_fence_locked(
                p.cid, advance=True):
            return
        self.failure_manager.on_checkpoint_success(p.cid)
        self._completed_ids.append(p.cid)
        self._latest_resolved = resolved
        if self.queryable is not None:
            # feed the read replicas off the checkpoint stream (enqueue
            # only; the service's ingest thread parses the snapshot)
            self.queryable.on_checkpoint_complete(p.cid, resolved)
        # aggregate the subtasks' channel-state (v1) alignment accounting
        # (one shared reader of the schema: task.aggregate_channel_state)
        from flink_tpu.cluster.task import aggregate_channel_state
        from flink_tpu.observability import tracing as tracing_mod
        agg = aggregate_channel_state(p.acks.values())
        tracing_mod.complete("checkpoint", p.t0_ns, time.perf_counter_ns(),
                             cat="checkpoint", checkpoint=p.cid,
                             acked=len(p.acks),
                             unaligned=bool(agg["unaligned"]))
        from flink_tpu.cluster.minicluster import _state_size
        size = _state_size(resolved)
        self._checkpoint_stats.append({
            "id": p.cid, "duration_ms": round(p.timer.ms(), 1),
            "acked_subtasks": len(p.acks),
            "state_size_bytes": size,
            # full-vs-delta accounting (== state_size_bytes on a full cut)
            "incremental": has_delta,
            "delta_bytes": _state_size(assembled) if has_delta else size,
            **agg})
        del self._checkpoint_stats[:-100]
        for idx in self._conns:
            self._to_worker(idx, ("notify", p.cid))

    def _ha_fence_locked(self, cid: int, advance: bool = False) -> bool:
        """Caller holds ``_lock``: verify this coordinator still owns the
        current leader epoch — with ``advance=True`` by durably moving the
        completed-checkpoint pointer, otherwise by a read-only epoch
        check.  A stale epoch charges the failure budget AND demotes the
        run (the zombie fails loudly, never completing the checkpoint);
        a pointer-write I/O error is charged as a storage failure."""
        from flink_tpu.runtime.checkpoint.failure import \
            CheckpointFailureReason
        from flink_tpu.runtime.ha import StaleEpochError
        try:
            if advance:
                self.ha_store.set_completed_checkpoint(
                    self.ha_job_id, cid, self._epoch)
            else:
                self.ha_store.check_epoch(self._epoch)
        except StaleEpochError as e:
            self.ha_fenced_completions += 1
            self.failure_manager.on_checkpoint_failure(
                CheckpointFailureReason.STORAGE, cid)
            if self._failed is None:
                self._failed = (f"checkpoint {cid} fenced: stale leader "
                                f"epoch {self._epoch}: {e}")
            self._all_done.set()
            return False
        except Exception as e:  # noqa: BLE001 — HA store I/O error
            self._checkpoint_failure_locked(
                CheckpointFailureReason.STORAGE, cid,
                f"HA pointer write failed: {type(e).__name__}: {e}")
            return False
        return True

    def _checkpoint_failure_locked(self, reason: str, cid: int,
                                   detail: str) -> None:
        """Caller holds ``_lock``: charge one checkpoint failure; past the
        tolerable budget the attempt FAILS (run() restores the next attempt
        from the latest completed checkpoint)."""
        if self.failure_manager.on_checkpoint_failure(reason, cid) \
                and self._failed is None:
            self._failed = (
                f"tolerable failed checkpoints "
                f"({self.failure_manager.tolerable}) exceeded — "
                f"checkpoint {cid} {reason}: {detail}")
            self._all_done.set()

    def _checkpoint_loop(self, all_subtasks: set, done: threading.Event) -> None:
        while not done.is_set():
            time.sleep(self.checkpoint_interval_ms / 1000.0)
            if done.is_set():
                return
            self.trigger_checkpoint(all_subtasks)
