"""Adaptive (reactive) scheduler: rescale the job to available slots.

Analog of ``runtime/scheduler/adaptive/AdaptiveScheduler.java:146``
(FLIP-160): a state machine — Created → WaitingForResources → Executing →
Restarting → Finished/Failed — that sizes the job to whatever slots exist.
``declare_slots(n)`` (the reactive-mode resource declaration) triggers a
rescale: take a savepoint, cancel, re-split every keyed vertex's state to
the new parallelism through the key-group redistribution path, and redeploy.

Rescale contract: sources must have STABLE splits (split count independent
of job parallelism — files, log partitions); their offsets carry over
unchanged.  Keyed vertex state is merged across old subtasks and re-split
by key-group range (``StateAssignmentOperation.reDistributeKeyedStates``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from flink_tpu.cluster.failover import (FixedDelayRestartStrategy,
                                        RestartStrategy)
from flink_tpu.cluster.minicluster import JobResult, MiniCluster
from flink_tpu.graph.stream_graph import ExecutionPlan
from flink_tpu.state.redistribute import split_keyed_snapshot
from flink_tpu.state_processor.savepoint import (_is_keyed,
                                                 _merged_operator_snapshot)


class SchedulerStates:
    CREATED = "Created"
    WAITING_FOR_RESOURCES = "WaitingForResources"
    EXECUTING = "Executing"
    RESTARTING = "Restarting"
    FINISHED = "Finished"
    FAILED = "Failed"
    CANCELED = "Canceled"


def _split_member(member: Dict[str, Any], max_parallelism: int,
                  n: int) -> List[Dict[str, Any]]:
    if "pane_base" in member:
        from flink_tpu.operators.window_agg import WindowAggOperator
        return WindowAggOperator.split_snapshot(member, max_parallelism, n)
    if _is_keyed(member):
        fields = sorted({k for k in member
                         if k.startswith("state.") or k == "leaves"})
        return split_keyed_snapshot(member, fields, max_parallelism, n)
    # stateless / non-keyed member: subtask 0 keeps it, others start fresh
    return [member] + [{} for _ in range(n - 1)]


def rescale_snapshot(snapshot: Dict[str, Any], plan: ExecutionPlan,
                     new_counts: Dict[str, int]) -> Dict[str, Any]:
    """A MiniCluster checkpoint taken at one parallelism -> restorable at
    another (the StateAssignmentOperation analog).

    Refuses (loudly) snapshots carrying persisted in-flight channel state:
    an UNALIGNED checkpoint's channel state is keyed by physical channel
    index and cannot be redistributed — drain-then-rescale (rescale from
    an aligned savepoint) is the supported procedure."""
    from flink_tpu.state.redistribute import reject_channel_state

    reject_channel_state(snapshot, "rescale")
    out: Dict[str, Any] = {}
    by_uid = {v.uid: v for v in plan.vertices}
    for uid, entry in snapshot.items():
        if uid.startswith("__"):
            out[uid] = entry
            continue
        v = by_uid.get(uid)
        n_new = new_counts.get(uid)
        if v is None or n_new is None:
            out[uid] = entry
            continue
        old_subs = entry.get("subtasks", []) if isinstance(entry, dict) else []
        if v.is_source:
            if len(old_subs) != n_new:
                raise ValueError(
                    f"rescale: source {uid!r} split count changed "
                    f"({len(old_subs)} -> {n_new}); adaptive rescale needs "
                    f"stable-split sources (files / log partitions)")
            out[uid] = entry
            continue
        if len(old_subs) == n_new:
            out[uid] = entry
            continue
        merged = _merged_operator_snapshot(entry)
        inner = merged.get("operator", merged)
        maxp = v.max_parallelism
        member_keys = [k for k in inner
                       if k.startswith("op") and k[2:].isdigit()]
        parts: List[Dict[str, Any]]
        if member_keys:
            split_members = {mk: _split_member(inner[mk], maxp, n_new)
                             for mk in member_keys}
            passthrough = {k: v2 for k, v2 in inner.items()
                           if k not in member_keys}
            parts = [dict(passthrough,
                          **{mk: split_members[mk][i] for mk in member_keys})
                     for i in range(n_new)]
        else:
            parts = _split_member(inner, maxp, n_new)
        wrapped = []
        for p in parts:
            if isinstance(merged, dict) and "operator" in merged:
                w = {k: v2 for k, v2 in merged.items() if k != "operator"}
                w["operator"] = p
                wrapped.append(w)
            else:
                wrapped.append({"operator": p, "valve": None}
                               if "operator" not in p else p)
        # subtask snapshots are {"operator": ..., "valve": ...} shaped
        out[uid] = {"subtasks": [
            w if "operator" in w else {"operator": w} for w in wrapped]}
    return out


class AdaptiveScheduler:
    """Reactive scheduler over the MiniCluster."""

    def __init__(self, plan_factory: Callable[[int], ExecutionPlan],
                 checkpoint_storage=None, checkpoint_interval_ms: int = 20,
                 restart_strategy: Optional[RestartStrategy] = None,
                 min_slots: int = 1):
        self.plan_factory = plan_factory
        self.checkpoint_storage = checkpoint_storage
        self.checkpoint_interval_ms = checkpoint_interval_ms
        self.restart_strategy = restart_strategy or FixedDelayRestartStrategy(2)
        self.min_slots = min_slots
        self.state = SchedulerStates.CREATED
        self._slots = 0
        self._desired_slots = 0
        self._cluster: Optional[MiniCluster] = None
        self._result: Optional[JobResult] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.rescales = 0
        self.error: Optional[str] = None

    # -- resources (reactive declaration) ------------------------------------
    def declare_slots(self, n: int) -> None:
        """Reactive mode: the cluster now has ``n`` slots; the scheduler
        rescales the job to use all of them (FLIP-160)."""
        with self._lock:
            self._desired_slots = n

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "AdaptiveScheduler":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="adaptive-scheduler")
        self._thread.start()
        return self

    def cancel(self) -> None:
        self._stop.set()
        if self._cluster is not None:
            self._cluster.cancel()

    def join(self, timeout_s: float = 120.0) -> Optional[JobResult]:
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
        return self._result

    # -- state machine --------------------------------------------------------
    def _run(self) -> None:
        try:
            self._run_inner()
        except Exception as e:  # noqa: BLE001 — scheduler thread must not die silently
            self.error = f"{type(e).__name__}: {e}"
            self.state = SchedulerStates.FAILED

    def _run_inner(self) -> None:
        self.state = SchedulerStates.WAITING_FOR_RESOURCES
        while not self._stop.is_set():
            with self._lock:
                desired = self._desired_slots
            if desired >= self.min_slots:
                break
            time.sleep(0.01)
        raw_restore: Optional[Dict[str, Any]] = None
        while not self._stop.is_set():
            with self._lock:
                self._slots = self._desired_slots
            parallelism = max(self.min_slots, self._slots)
            plan = self.plan_factory(parallelism)
            # split the snapshot for the parallelism we ACTUALLY deploy at —
            # desired slots may have moved again since the savepoint was
            # taken, and restoring N-way-split state into M subtasks would
            # silently drop/misroute key-group ranges
            if raw_restore is not None:
                counts = {
                    v.uid: (len(v.chain[0].source.create_splits(parallelism))
                            if v.is_source else parallelism)
                    for v in plan.vertices}
                restore = rescale_snapshot(raw_restore, plan, counts)
            else:
                restore = None
            cluster = MiniCluster(
                checkpoint_storage=self.checkpoint_storage,
                checkpoint_interval_ms=self.checkpoint_interval_ms)
            self._cluster = cluster
            self.state = SchedulerStates.EXECUTING
            done: Dict[str, Any] = {}

            def run_job(pl=plan, cl=cluster, rs=restore):
                done["result"] = cl.execute(pl, restore=rs, timeout_s=600)

            th = threading.Thread(target=run_job, daemon=True)
            th.start()
            rescale_to: Optional[int] = None
            while th.is_alive():
                if self._stop.is_set():
                    cluster.cancel()
                    break
                with self._lock:
                    if self._desired_slots != parallelism and \
                            self._desired_slots >= self.min_slots:
                        rescale_to = self._desired_slots
                if rescale_to is not None:
                    break
                time.sleep(0.01)
            if rescale_to is not None:
                # take a consistent cut and stop; the split happens at the
                # top of the loop for whatever parallelism wins
                self.state = SchedulerStates.RESTARTING
                sp = cluster.savepoint()
                cluster.cancel()
                th.join(timeout=60)
                raw_restore = (self.checkpoint_storage.load(sp)
                               if sp is not None and self.checkpoint_storage
                               else cluster.latest_restore())
                self.rescales += 1
                continue
            th.join(timeout=60)
            result = done.get("result")
            self._result = result
            if result is None or self._stop.is_set():
                self.state = SchedulerStates.CANCELED
                return
            if result.state == "FINISHED":
                self.state = SchedulerStates.FINISHED
                return
            if result.state == "CANCELED":
                self.state = SchedulerStates.CANCELED
                return
            # failure: consult the restart strategy
            self.restart_strategy.notify_failure()
            if not self.restart_strategy.can_restart():
                self.state = SchedulerStates.FAILED
                return
            self.state = SchedulerStates.RESTARTING
            time.sleep(self.restart_strategy.delay_ms() / 1000.0)
            raw_restore = (self.checkpoint_storage.load_latest()
                           if self.checkpoint_storage else
                           self._cluster.latest_restore())
        self.state = SchedulerStates.CANCELED
